"""Cluster telemetry federation: N worker dossiers -> one cluster view.

PRs 3-4 gave every *process* a telemetry spine (one metrics registry,
one flight-recorder ring, one span tracer); PR 5 gave training a
multi-process world (ElasticSupervisor cohorts, gloo collectives,
heartbeats). The two never met: each worker's series die inside its
process, so the supervisor relaunches cohorts blind and a 2-process
chaos run yields N disconnected dossiers instead of one timeline. This
module is the meeting point:

- :class:`TelemetryExporter` — the per-worker publication side. A tiny
  stdlib HTTP endpoint (port derived from ``DL4J_TPU_WORKER_ID`` +
  ``DL4J_TPU_TELEMETRY_PORT_BASE``) serving the worker's default-
  registry scrape (``/metrics``), flight-ring dump
  (``/flightrecorder``), span dump (``/trace``), and the one-GET
  aggregation document (``/snapshot``). Where a port cannot be bound
  (or none is armed) it degrades to a **file sink**: the same snapshot
  document atomically rewritten to
  ``DL4J_TPU_TELEMETRY_DIR/worker_<id>.json`` on a cadence, so the
  aggregator can read workers on filesystems-only environments and the
  *final pre-crash snapshot of a dead worker survives its process*.

- :class:`ClusterAggregator` — the supervisor/coordinator side. Each
  ``poll()`` fetches every worker's snapshot (HTTP first, file-sink
  fallback), keeps the **last-known snapshot per worker** (a dead
  worker's final state stays addressable for the crash dossier), and
  republishes three cluster artifacts:

  * a **federated registry**: every worker's series unioned under
    ``worker``/``generation`` labels (strict collision rules — a family
    whose type/labels/buckets disagree across workers is dropped and
    counted in ``cluster_federation_conflicts_total`` instead of
    silently interleaved), rendered through the same
    ``render_text_multi`` union path as every other scrape;
  * one **ordered cluster timeline**: every worker's flight events
    merged by timestamp (events carry worker identity — see
    ``flightrecorder.record``);
  * one **stitched Perfetto trace**: every worker's spans in a single
    Chrome-trace document with one pid lane per worker, plus
    synthesized ``cluster.step`` roots so the per-step collective legs
    recorded by ``runtime/distributed.py`` (trace ids minted at the
    coordinator and propagated through ``broadcast_host_data``) join
    one trace tree.

- :class:`ClusterTelemetryServer` — the cluster health surface the
  supervisor exposes: ``GET /cluster/metrics`` (federated scrape),
  ``/cluster/debug/workers`` (worker table: generation, restarts, last
  step, heartbeat age), ``/cluster/debug/flightrecorder`` (merged
  timeline), ``/cluster/debug/trace`` (stitched Perfetto JSON), and
  ``/cluster/debug/health`` (an SLO :class:`HealthEngine` pointed at
  the *federated* registry, so burn-rate rules fire on cohort-wide
  availability rather than one survivor's view).

Stdlib only; safe to import from any layer.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Tuple
from urllib.parse import parse_qs

from deeplearning4j_tpu.observability import metrics as _metrics
from deeplearning4j_tpu.observability import reqlog as _reqlog
from deeplearning4j_tpu.observability import trace as _trace
from deeplearning4j_tpu.observability.flightrecorder import (
    get_flight_recorder,
)
from deeplearning4j_tpu.observability.metrics import (
    CONTENT_TYPE_OPENMETRICS,
    CONTENT_TYPE_TEXT,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    render_json_multi,
    render_text_multi,
    wants_openmetrics,
)

ENV_TELEMETRY_PORT = "DL4J_TPU_TELEMETRY_PORT"
ENV_TELEMETRY_PORT_BASE = "DL4J_TPU_TELEMETRY_PORT_BASE"
ENV_TELEMETRY_DIR = "DL4J_TPU_TELEMETRY_DIR"

# labels the federation layer appends to every worker series
FEDERATION_LABELS = ("worker", "generation")

_INF = float("inf")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name) or default)
    except ValueError:  # junk/empty env must not crash telemetry paths
        return default


def worker_identity() -> Dict[str, int]:
    """This process's supervisor-provided identity (zeros/ones when not
    under a supervisor; junk env degrades to the defaults rather than
    crashing a telemetry path). This is the ONE parser of the identity
    env vars — ``resilience.supervisor.worker_identity`` delegates
    here; only ``flightrecorder._identity_fields`` keeps its own
    presence-gated variant (importing this module there would cycle)."""
    return {
        "worker_id": _env_int("DL4J_TPU_WORKER_ID", 0),
        "num_workers": _env_int("DL4J_TPU_NUM_WORKERS", 1),
        "generation": _env_int("DL4J_TPU_GENERATION", 1),
    }


def telemetry_port(worker_id: Optional[int] = None) -> Optional[int]:
    """The exporter port this worker should bind:
    ``DL4J_TPU_TELEMETRY_PORT`` wins outright; otherwise
    ``DL4J_TPU_TELEMETRY_PORT_BASE + worker_id`` (the supervisor arms
    the base, each worker derives its own). None = no port armed."""
    explicit = os.environ.get(ENV_TELEMETRY_PORT)
    if explicit:
        try:
            return int(explicit)
        except ValueError:
            return None
    base = os.environ.get(ENV_TELEMETRY_PORT_BASE)
    if not base:
        return None
    try:
        wid = (worker_identity()["worker_id"]
               if worker_id is None else int(worker_id))
        return int(base) + wid
    except ValueError:
        return None


class _JsonHandler(BaseHTTPRequestHandler):
    """Shared base for the exporter/cluster HTTP handlers: quiet
    logging, one JSON/bytes ``_send``, one ``?seconds=`` parser."""

    def log_message(self, *a):  # noqa: N802 - stdlib API
        pass

    def _send(self, status: int, body, content_type="application/json"):
        raw = (body if isinstance(body, bytes)
               else json.dumps(body, default=str).encode())
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _seconds_param(self, query: str) -> Tuple[Optional[float], bool]:
        """Parsed ``?seconds=`` as (value, ok) — (None, True) when
        absent; sends the 400 itself and returns ok=False on junk."""
        q = parse_qs(query)
        if "seconds" not in q:
            return None, True
        try:
            return float(q["seconds"][0]), True
        except ValueError:
            self._send(400, {"error": "seconds must be a number"})
            return None, False


def build_snapshot(*, extra_registries: Sequence = (),
                   flight_window_s: Optional[float] = None) -> dict:
    """The one-document export the aggregator consumes: identity +
    metrics JSON + flight dump + span dump + incident index + the
    request ledger's recent window, self-describing."""
    ident = worker_identity()
    regs = [default_registry()] + list(extra_registries)
    return {
        "worker": ident["worker_id"],
        "num_workers": ident["num_workers"],
        "generation": ident["generation"],
        "pid": os.getpid(),
        "time": time.time(),
        "metrics": render_json_multi(regs),
        "flight": get_flight_recorder().dump(last_seconds=flight_window_s),
        "spans": [s.to_json() for s in _trace.get_tracer().spans()],
        "incidents": _incident_index(),
        "requests": _request_index(),
        "timeseries": _timeseries_index(),
        "usage": _usage_index(),
        "capacity": _capacity_index(),
    }


def _incident_index() -> List[dict]:
    """This worker's incident-bundle index (observability/incidents.py),
    or [] — never creates a manager as a side effect, never raises."""
    try:
        from deeplearning4j_tpu.observability.incidents import (
            incident_index,
        )

        return incident_index()
    except Exception:  # noqa: BLE001 — telemetry never fails the worker
        return []


def _request_index() -> List[dict]:
    """This worker's recent request-ledger records (reqlog.py), or []
    — never creates a ledger as a side effect, never raises. The spans
    a retained request kept ride the snapshot's ``spans`` list, so the
    cluster view reconstructs the tree from the same document."""
    try:
        from deeplearning4j_tpu.observability.reqlog import request_index

        return request_index()
    except Exception:  # noqa: BLE001 — telemetry never fails the worker
        return []


def _timeseries_index() -> Optional[dict]:
    """This worker's TSDB snapshot (timeseries.py), or None — never
    creates a store as a side effect, never raises. History federates
    as one atomic document; the aggregator rebuilds a queryable store
    per worker from it."""
    try:
        from deeplearning4j_tpu.observability.timeseries import (
            timeseries_index,
        )

        return timeseries_index()
    except Exception:  # noqa: BLE001 — telemetry never fails the worker
        return None


def _usage_index() -> Optional[dict]:
    """This worker's usage-accounting document (usage.py), or None —
    never creates a meter as a side effect, never raises."""
    try:
        from deeplearning4j_tpu.observability.reqlog import (
            get_request_ledger,
        )
        from deeplearning4j_tpu.observability.usage import usage_index

        return usage_index(ledger=get_request_ledger())
    except Exception:  # noqa: BLE001 — telemetry never fails the worker
        return None


#: Last capacity report published by this process's evaluator (every
#: CapacityEvaluator.evaluate() pass stores its report here) — the
#: federation snapshot reads it without holding a server reference.
_LAST_CAPACITY_REPORT: Optional[dict] = None


def publish_capacity_report(report: Optional[dict]) -> None:
    global _LAST_CAPACITY_REPORT
    _LAST_CAPACITY_REPORT = report


def _capacity_index() -> Optional[dict]:
    """This worker's latest published capacity report, or None. Reads
    the cached report only — a federation scrape must not force an
    evaluation pass."""
    return _LAST_CAPACITY_REPORT


class TelemetryExporter:
    """Publish this worker's telemetry for the cluster aggregator.

    HTTP mode (a port resolved from env or passed explicitly): a
    daemon ``ThreadingHTTPServer`` serving ``/snapshot`` (the
    aggregation document), ``/metrics`` (Prometheus text;
    ``?format=json``), ``/flightrecorder`` (``?seconds=``), ``/trace``
    (span JSON; ``?format=chrome`` for Perfetto), ``/identity``, and
    ``/healthz``.

    File sink (``DL4J_TPU_TELEMETRY_DIR`` armed): a daemon thread
    atomically rewrites ``worker_<id>.json`` every ``sink_interval_s``
    — and once more on :meth:`stop`, so a cleanly-exiting worker's
    final state is on disk. The sink runs *alongside* HTTP too (not
    just as the no-port fallback): a SIGKILLed worker's HTTP endpoint
    dies with it, but its last sink write survives for the crash
    dossier. :meth:`publish` forces one write now (training loops may
    call it at epoch boundaries so the sink is never staler than an
    epoch).
    """

    def __init__(self, *, port: Optional[int] = None,
                 host: str = "127.0.0.1",
                 sink_dir: Optional[str | Path] = None,
                 sink_interval_s: float = 1.0,
                 extra_registries: Sequence = ()):
        if sink_interval_s <= 0:
            raise ValueError(
                f"sink_interval_s must be > 0, got {sink_interval_s}")
        self.host = host
        self._requested_port = port
        self.sink_dir = Path(sink_dir) if sink_dir is not None else None
        self.sink_interval_s = float(sink_interval_s)
        self.extra_registries = list(extra_registries)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._serve_thread: Optional[threading.Thread] = None
        self._sink_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # an epoch-boundary publish() and the sink thread both target
        # the same tmp file; unserialized, the losing os.replace raises
        # out of the CALLER (the training loop)
        self._publish_lock = threading.Lock()
        self.mode = "disabled"

    # -- surface -------------------------------------------------------------

    @property
    def port(self) -> Optional[int]:
        return (self._httpd.server_address[1]
                if self._httpd is not None else None)

    @property
    def url(self) -> Optional[str]:
        return (f"http://{self.host}:{self.port}"
                if self._httpd is not None else None)

    @property
    def sink_path(self) -> Optional[Path]:
        if self.sink_dir is None:
            return None
        return self.sink_dir / f"worker_{worker_identity()['worker_id']}.json"

    def snapshot(self) -> dict:
        return build_snapshot(extra_registries=self.extra_registries)

    def publish(self) -> Optional[Path]:
        """Write one file-sink snapshot now (no-op without a sink dir);
        returns the path written, or None when there is nothing to
        write or the write failed. Telemetry never fails the worker: a
        full/read-only sink disk must not crash the training loop that
        calls this at epoch boundaries, nor kill a cohort at launch."""
        path = self.sink_path
        if path is None:
            return None
        doc = json.dumps(self.snapshot(), default=str)
        try:
            # analysis: allow(blocking-under-lock) — the publish lock
            # exists to serialize exactly this atomic rewrite (two
            # publishers would race on the shared .tmp name); payload is
            # pre-serialized above and no other lock ever nests with it
            with self._publish_lock:
                path.parent.mkdir(parents=True, exist_ok=True)
                tmp = path.with_suffix(".tmp")
                tmp.write_text(doc)
                os.replace(tmp, path)
        except OSError:
            return None
        return path

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "TelemetryExporter":
        if self.mode != "disabled":
            return self
        self._stop.clear()
        port = (self._requested_port if self._requested_port is not None
                else telemetry_port())
        if port is not None:
            try:
                self._httpd = ThreadingHTTPServer(
                    (self.host, port), self._handler_class())
                self._serve_thread = threading.Thread(
                    target=self._httpd.serve_forever, daemon=True,
                    name=f"telemetry-exporter-{port}")
                self._serve_thread.start()
                self.mode = "http"
            except OSError:
                # port taken / unbindable: fall through to the file sink
                self._httpd = None
        if self.sink_dir is not None:
            # the sink runs even in HTTP mode: an HTTP endpoint dies
            # with its (SIGKILLed) worker; the sink file outlives it
            self.publish()
            self._sink_thread = threading.Thread(
                target=self._sink_loop, daemon=True,
                name="telemetry-sink")
            self._sink_thread.start()
            if self.mode == "disabled":
                self.mode = "file"
        return self

    def _sink_loop(self):
        while not self._stop.wait(self.sink_interval_s):
            try:
                self.publish()
            except Exception:  # noqa: BLE001 — telemetry never fails the
                pass           # worker; a dead sink loses the final
                               # pre-crash snapshot, so keep publishing

    def stop(self):
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            if self._serve_thread is not None:
                self._serve_thread.join(timeout=5)
            self._httpd.server_close()
            self._httpd = None
            self._serve_thread = None
        if self._sink_thread is not None:
            self._sink_thread.join(timeout=5)
            self._sink_thread = None
            try:
                self.publish()  # the final (possibly pre-exit) state
            except OSError:
                pass
        self.mode = "disabled"

    def __enter__(self) -> "TelemetryExporter":
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- HTTP handler --------------------------------------------------------

    def _handler_class(self):
        exporter = self

        class Handler(_JsonHandler):
            def do_GET(self):  # noqa: N802 - stdlib API
                path, _, query = self.path.partition("?")
                regs = [default_registry()] + exporter.extra_registries
                if path == "/healthz":
                    self._send(200, {"status": "ok"})
                elif path == "/identity":
                    self._send(200, dict(worker_identity(),
                                         pid=os.getpid(),
                                         mode=exporter.mode))
                elif path == "/snapshot":
                    self._send(200, exporter.snapshot())
                elif path == "/metrics":
                    if "format=json" in query:
                        self._send(200, render_json_multi(regs))
                    else:
                        om = wants_openmetrics(self.headers.get("Accept"))
                        self._send(
                            200,
                            render_text_multi(
                                regs, openmetrics=om).encode(),
                            content_type=(CONTENT_TYPE_OPENMETRICS if om
                                          else CONTENT_TYPE_TEXT))
                elif path == "/flightrecorder":
                    seconds, ok = self._seconds_param(query)
                    if not ok:
                        return
                    self._send(200, get_flight_recorder().dump(
                        last_seconds=seconds))
                elif path == "/trace":
                    spans = _trace.get_tracer().spans()
                    if "format=chrome" in query:
                        self._send(200, _trace.to_chrome_trace(spans))
                    else:
                        self._send(200, {"spans": [s.to_json()
                                                   for s in spans]})
                elif path == "/incidents":
                    self._send(200, {"incidents": _incident_index()})
                elif path == "/requests":
                    self._send(200, {"requests": _request_index()})
                elif path.startswith("/requests/"):
                    from deeplearning4j_tpu.observability.reqlog import (
                        request_detail,
                    )

                    cid = path[len("/requests/"):]
                    body = request_detail(cid)
                    if body is None:
                        self._send(404, {"error": f"no request {cid!r}"})
                    else:
                        self._send(200, body)
                else:
                    self._send(404, {"error": f"no route {path}"})

        return Handler


_PROC_EXPORTER: Optional[TelemetryExporter] = None


def telemetry_exporter_from_env() -> Optional[TelemetryExporter]:
    """Start a :class:`TelemetryExporter` from the supervisor-provided
    environment (telemetry port base and/or sink dir), or None when
    neither is armed — the one-liner a worker script calls next to
    ``heartbeat_from_env()``. Idempotent per process."""
    global _PROC_EXPORTER
    port = telemetry_port()
    sink = os.environ.get(ENV_TELEMETRY_DIR) or None
    if port is None and sink is None:
        return None
    if _PROC_EXPORTER is not None and _PROC_EXPORTER.mode != "disabled":
        return _PROC_EXPORTER
    exp = TelemetryExporter(port=port, sink_dir=sink).start()
    if exp.mode == "disabled":
        return None
    _PROC_EXPORTER = exp
    return exp


def get_process_exporter() -> Optional[TelemetryExporter]:
    return _PROC_EXPORTER


def set_process_exporter(exp: Optional[TelemetryExporter]) -> None:
    global _PROC_EXPORTER
    _PROC_EXPORTER = exp


# -- federation: N metrics documents -> one labeled registry ------------------


def _parse_bound(key: str) -> float:
    return _INF if key == "+Inf" else float(key)


def federate_instruments(
        snapshots: Dict[int, dict], *,
        on_conflict: Optional[Callable[[str, str], None]] = None
) -> List[_metrics._Instrument]:
    """Union every worker snapshot's metric families into fresh
    instruments whose label sets are extended with
    ``worker``/``generation``.

    Collision rules are strict: the first worker (lowest id) to expose
    a family fixes its type, label names, and histogram buckets; a
    later worker whose same-named family disagrees on any of those is
    NOT interleaved — its samples are dropped and ``on_conflict(name,
    reason)`` is called, so a federated scrape never mixes
    incompatible series under one family the way a naive concat would.
    """
    insts: Dict[str, _metrics._Instrument] = {}
    shapes: Dict[str, Tuple] = {}  # name -> (kind, labelnames, buckets)
    out: List[_metrics._Instrument] = []
    for wid in sorted(snapshots):
        snap = snapshots[wid]
        if not isinstance(snap, dict):
            continue
        gen = str(snap.get("generation", 1))
        metrics_doc = snap.get("metrics")
        families = (metrics_doc.get("metrics", [])
                    if isinstance(metrics_doc, dict) else [])
        for fam in families:
            # one malformed-but-identity-passing family (version-skewed
            # worker, stray sink file) must drop as a conflict, not
            # poison every future poll of the whole federated view
            try:
                _federate_family(fam, wid, gen, insts, shapes, out,
                                 on_conflict)
            except Exception:  # noqa: BLE001 — contained per family
                if on_conflict is not None:
                    fam_name = (fam.get("name", "?")
                                if isinstance(fam, dict) else "?")
                    on_conflict(str(fam_name), "malformed family")
    return out


def _federate_family(fam: dict, wid: int, gen: str,
                     insts: Dict[str, _metrics._Instrument],
                     shapes: Dict[str, Tuple],
                     out: List[_metrics._Instrument],
                     on_conflict: Optional[Callable[[str, str], None]]
                     ) -> None:
    """Fold one worker's metric family into the federated instruments
    (see :func:`federate_instruments` for the collision rules)."""
    name, kind = fam["name"], fam["type"]
    samples = fam.get("samples", [])
    if not samples:
        return
    labelnames = tuple(samples[0]["labels"].keys())
    if set(labelnames) & set(FEDERATION_LABELS):
        # a family already labeled worker/generation would render
        # duplicate label names (invalid exposition) — a shape
        # conflict like any other
        if on_conflict is not None:
            on_conflict(name, "reserved federation label")
        return
    buckets: Optional[Tuple[float, ...]] = None
    if kind == "histogram":
        buckets = tuple(sorted(
            _parse_bound(k) for k in samples[0]["buckets"]))
    inst = insts.get(name)
    if inst is None:
        try:
            if kind == "histogram":
                inst = Histogram(
                    name, fam.get("help", ""),
                    labelnames + FEDERATION_LABELS,
                    buckets=[b for b in buckets if b != _INF])
            else:
                cls = Gauge if kind == "gauge" else Counter
                inst = cls(name, fam.get("help", ""),
                           labelnames + FEDERATION_LABELS)
        except ValueError:
            if on_conflict is not None:
                on_conflict(name, "invalid name/labels")
            return
        insts[name] = inst
        shapes[name] = (kind, labelnames, buckets)
        out.append(inst)
    elif shapes[name] != (kind, labelnames, buckets):
        if on_conflict is not None:
            on_conflict(name, "type/label/bucket mismatch")
        return
    # stage the writes: a malformed sample mid-family must drop this
    # worker's WHOLE contribution (matching the conflict counter's
    # claim), never leave a partially-folded series behind
    staged: Dict[Tuple[str, ...], object] = {}
    for s in samples:
        key = tuple(str(s["labels"][k]) for k in labelnames) \
            + (str(wid), gen)
        if kind == "histogram":
            bounds = sorted(_parse_bound(k) for k in s["buckets"])
            if tuple(bounds) != buckets:
                if on_conflict is not None:
                    on_conflict(name, "bucket mismatch")
                continue
            cums = [s["buckets"][
                "+Inf" if b == _INF else _metrics._fmt(b)]
                for b in bounds]
            counts = [c - p for c, p in zip(cums, [0] + cums[:-1])]
            staged[key] = {"counts": counts,
                           "sum": float(s["sum"]),
                           "n": int(s["count"])}
        else:
            staged[key] = float(s["value"])
    inst._data.update(staged)


class FederatedRegistry:
    """A read-only registry *view* over the aggregator's latest poll —
    duck-typed to ``MetricsRegistry`` (``instruments()``) so
    ``render_text_multi`` / ``render_json_multi`` and the SLO
    :class:`HealthEngine` consume the federated series exactly like any
    local registry."""

    def __init__(self, aggregator: "ClusterAggregator"):
        self._aggregator = aggregator

    def instruments(self) -> List[_metrics._Instrument]:
        return self._aggregator.federated_instruments()

    def names(self) -> List[str]:
        return [i.name for i in self.instruments()]

    def render_text(self, *, openmetrics: bool = False) -> str:
        return render_text_multi([self], openmetrics=openmetrics)

    def render_json(self) -> dict:
        return render_json_multi([self])


class ClusterMetrics:
    """The aggregator's own exposition — cohort liveness/progress gauges
    plus the poll/ conflict counters the worker-liveness SLO rule reads."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        r = registry if registry is not None else MetricsRegistry()
        self.registry = r
        ns = "cluster"
        self.worker_up = r.gauge(
            "worker_up", "1 while the worker's telemetry snapshot is "
            "fresh (HTTP reachable or file sink younger than the "
            "liveness window), else 0.", ("worker",), namespace=ns)
        self.worker_generation = r.gauge(
            "worker_generation", "Cohort generation the worker's latest "
            "snapshot reported.", ("worker",), namespace=ns)
        self.worker_last_step = r.gauge(
            "worker_last_step", "train_steps_total from the worker's "
            "latest snapshot.", ("worker",), namespace=ns)
        self.worker_step_lag = r.gauge(
            "worker_step_lag", "Steps behind the farthest-ahead worker "
            "(straggler surface: persistent lag on one worker is a "
            "slow host, not a slow model).", ("worker",), namespace=ns)
        self.worker_heartbeat_age_seconds = r.gauge(
            "worker_heartbeat_age_seconds", "Seconds since the worker's "
            "heartbeat beacon was written (resilience/cluster.py "
            "read_heartbeats); -1 when no beacon exists.", ("worker",),
            namespace=ns)
        self.worker_snapshot_age_seconds = r.gauge(
            "worker_snapshot_age_seconds", "Age of the last-known "
            "telemetry snapshot per worker.", ("worker",), namespace=ns)
        self.workers_expected = r.gauge(
            "workers_expected", "Cohort size the aggregator polls.",
            namespace=ns)
        self.workers_up = r.gauge(
            "workers_up", "Workers whose snapshot is currently fresh.",
            namespace=ns)
        self.restarts_total = r.gauge(
            "restarts_total", "Cohort relaunches observed by the "
            "supervisor driving this aggregator.", namespace=ns)
        # -- degraded-mode topology (elastic shrink-to-survivors) ------------
        self.workers_active = r.gauge(
            "workers_active", "Workers the CURRENT topology runs — equal "
            "to cluster_workers_expected at full strength, smaller while "
            "the cohort is shrunken onto its survivors.", namespace=ns)
        self.degraded = r.gauge(
            "degraded", "1 while the cohort runs degraded (one or more "
            "slots classified permanently dead and excluded), else 0.",
            namespace=ns)
        self.polls_total = r.counter(
            "polls_total", "Aggregation passes over the cohort (the "
            "time-in-degraded-mode burn-rate rule's total).",
            namespace=ns)
        self.degraded_ticks_total = r.counter(
            "degraded_ticks_total", "Aggregation passes that found the "
            "cohort degraded — degraded_ticks/polls is the fraction of "
            "time spent below full strength (the degraded-mode "
            "burn-rate rule's bad events).", namespace=ns)
        self.shrinks_total = r.counter(
            "shrinks_total", "Topology shrinks committed by the "
            "supervisor (dead slot excluded, cohort relaunched on the "
            "survivors).", namespace="supervisor")
        self.expands_total = r.counter(
            "expands_total", "Re-expansions committed by the supervisor "
            "(dead slots probed healthy, cohort relaunched at full "
            "strength at a checkpoint boundary).", namespace="supervisor")
        self.worker_polls_total = r.counter(
            "worker_polls_total", "Snapshot poll attempts per worker "
            "(the worker-liveness SLO rule's total).", ("worker",),
            namespace=ns)
        self.worker_poll_failures_total = r.counter(
            "worker_poll_failures_total", "Poll attempts that found no "
            "fresh snapshot (HTTP unreachable and file sink stale/"
            "absent) — the worker-liveness SLO rule's bad events.",
            ("worker",), namespace=ns)
        self.federation_conflicts_total = r.counter(
            "federation_conflicts_total", "Per-poll observations of a "
            "worker metric family dropped from the federated view "
            "because its type/labels/buckets disagreed with the "
            "family's first-seen shape (the view is rebuilt every "
            "poll, so a persistent conflict counts once per poll — a "
            "flat line means it cleared).", ("name",), namespace=ns)
        self.poll_seconds = r.histogram(
            "poll_seconds", "Wall time of one full aggregator poll "
            "across the cohort.", namespace=ns)


def _sanitize_snapshot(snap: dict) -> dict:
    """Coerce an identity-passing snapshot's nested documents to the
    shapes every downstream consumer assumes (worker table, timeline,
    span stitching, dossier): a version-skewed worker's malformed
    'flight'/'spans' must degrade to empty, not permanently poison the
    aggregator's last-known state."""
    flight = snap.get("flight")
    if not isinstance(flight, dict):
        flight = snap["flight"] = {}
    evs = flight.get("events")
    flight["events"] = ([e for e in evs if isinstance(e, dict)]
                        if isinstance(evs, list) else [])
    spans = snap.get("spans")
    snap["spans"] = (
        [d for d in spans if isinstance(d, dict)
         and all(k in d for k in ("name", "trace_id", "span_id"))]
        if isinstance(spans, list) else [])
    incidents = snap.get("incidents")
    snap["incidents"] = (
        [d for d in incidents if isinstance(d, dict) and d.get("id")]
        if isinstance(incidents, list) else [])
    requests = snap.get("requests")
    snap["requests"] = (
        [d for d in requests if isinstance(d, dict) and d.get("cid")]
        if isinstance(requests, list) else [])
    # historical-telemetry documents are optional and self-describing:
    # anything that is not a dict degrades to absent (None)
    for key in ("timeseries", "usage", "capacity"):
        if not isinstance(snap.get(key), dict):
            snap[key] = None
    return snap


def _snapshot_last_step(snap: dict) -> float:
    try:
        for fam in snap.get("metrics", {}).get("metrics", []):
            if fam.get("name") == "train_steps_total":
                return float(sum(s["value"]
                                 for s in fam.get("samples", [])))
    except Exception:  # noqa: BLE001 — a malformed family reads as 0
        pass
    return 0.0


class ClusterAggregator:
    """Poll every worker's exporter; hold the cluster's last-known view.

    ``port_base``/``host`` name the HTTP exporters (worker *i* at
    ``port_base + i``); ``sink_dir`` is the file-sink fallback read
    when HTTP fails. ``heartbeat_dir`` (the supervisor's) feeds the
    per-worker heartbeat-age gauge. ``restarts`` is a callable the
    supervisor provides so ``cluster_restarts_total`` tracks cohort
    relaunches. Snapshots survive worker death — :meth:`dossier` is
    what the supervisor buries in the crash report on cohort teardown.
    """

    def __init__(self, *, num_workers: int,
                 port_base: Optional[int] = None,
                 host: str = "127.0.0.1",
                 sink_dir: Optional[str | Path] = None,
                 heartbeat_dir: Optional[str | Path] = None,
                 fetch_timeout_s: float = 2.0,
                 liveness_window_s: float = 10.0,
                 startup_grace_s: float = 10.0,
                 restarts: Optional[Callable[[], int]] = None,
                 topology: Optional[Callable[[], dict]] = None,
                 local_events: Optional[Callable[[], List[dict]]] = None,
                 registry: Optional[MetricsRegistry] = None):
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        self.num_workers = num_workers
        self.port_base = port_base
        self.host = host
        self.sink_dir = Path(sink_dir) if sink_dir is not None else None
        self.heartbeat_dir = heartbeat_dir
        self.fetch_timeout_s = fetch_timeout_s
        self.liveness_window_s = liveness_window_s
        self.startup_grace_s = startup_grace_s
        self._restarts = restarts
        # optional cohort-shape provider (the elastic supervisor wires
        # its degraded-mode view: workers_active / degraded / dead
        # slots) — feeds the cluster_workers_active/cluster_degraded
        # gauges and the time-in-degraded-mode counter every poll
        self._topology = topology
        # optional provider of the AGGREGATOR-side process's own flight
        # events (the supervisor passes its supervisor.* ring) so the
        # merged cluster timeline shows launches/shrinks/expands next to
        # the worker events they caused — stamped worker="supervisor"
        self._local_events = local_events
        self._started = time.monotonic()
        self.metrics = ClusterMetrics(registry)
        self.federated = FederatedRegistry(self)
        # _poll_lock serializes whole polls (incl. the blocking network
        # fetches); _lock guards only the state swap, so /cluster/*
        # reads never stall behind a wedged worker's fetch timeout
        self._poll_lock = threading.Lock()
        self._lock = threading.Lock()
        self._fetch_pool = None  # built lazily on the first multi-worker poll
        self._snapshots: Dict[int, dict] = {}
        self._live: Dict[int, bool] = {}
        self._federated_insts: List[_metrics._Instrument] = []
        self._last_poll: Optional[float] = None
        # per-worker TSDB stores rebuilt from snapshot documents,
        # cached by (worker, snapshot time) — a re-poll with an
        # unchanged snapshot (dead worker) reuses the rebuilt store
        self._ts_cache: Dict[tuple, object] = {}
        self.metrics.workers_expected.set(num_workers)

    # -- reconfiguration (a new generation moves the port base) --------------

    def set_port_base(self, port_base: Optional[int]) -> None:
        """Same-size regeneration; delegates to :meth:`set_cohort` so
        a caller reaching for the narrower API can never desync the
        polled worker-id range from the base."""
        self.set_cohort(self.num_workers, port_base=port_base)

    def set_cohort(self, num_workers: int,
                   port_base: Optional[int] = None) -> None:
        """Re-derive the polled cohort for a new generation: worker-id
        range AND port base together (a shrink/expand compacts ids and
        moves the base — polling a dead slot's stale reservation would
        count phantom liveness failures forever). Per-worker gauges of
        slots beyond the new range are pruned — their *snapshots* are
        kept (the dossier's last-known state); counters are never
        pruned (a monotonic family must not step backwards mid-window
        under the SLO engine's deltas)."""
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        old = self.num_workers
        self.num_workers = num_workers
        self.port_base = port_base
        m = self.metrics
        m.workers_expected.set(float(num_workers))
        for wid in range(num_workers, old):
            w = str(wid)
            for gauge in (m.worker_up, m.worker_generation,
                          m.worker_last_step, m.worker_step_lag,
                          m.worker_heartbeat_age_seconds,
                          m.worker_snapshot_age_seconds):
                try:
                    gauge.remove(worker=w)
                except ValueError:
                    pass

    # -- polling -------------------------------------------------------------

    # exporter URLs are loopback/cluster-local: a corporate http_proxy
    # env var must not route (and time out) every worker poll
    _OPENER = urllib.request.build_opener(
        urllib.request.ProxyHandler({}))

    def _fetch_http(self, wid: int) -> Optional[dict]:
        if self.port_base is None:
            return None
        url = f"http://{self.host}:{self.port_base + wid}/snapshot"
        try:
            with self._OPENER.open(
                    url, timeout=self.fetch_timeout_s) as resp:
                snap = json.loads(resp.read())
        except Exception:  # noqa: BLE001 — any transport failure = miss
            return None
        # identity check: the port range is picked-then-released before
        # workers bind (racy by design) — a foreign process answering
        # this port (with ANY body shape) must not be attributed to
        # worker `wid`, nor abort the rest of the poll
        if not isinstance(snap, dict) or snap.get("worker") != wid:
            return None
        return _sanitize_snapshot(snap)

    def _fetch_file(self, wid: int) -> Tuple[Optional[dict], bool]:
        """(snapshot, fresh). A stale file still updates the last-known
        view (it IS the dead worker's final state) but reads as down."""
        if self.sink_dir is None:
            return None, False
        path = self.sink_dir / f"worker_{wid}.json"
        try:
            snap = json.loads(path.read_text())
        except (OSError, ValueError):
            return None, False
        if not isinstance(snap, dict) or snap.get("worker") != wid:
            return None, False
        try:
            age = time.time() - float(snap.get("time", 0.0))
        except (TypeError, ValueError):
            return None, False
        return _sanitize_snapshot(snap), age <= self.liveness_window_s

    def poll(self) -> dict:
        """One aggregation pass across the cohort; returns
        :meth:`workers` (the worker table). The (possibly slow —
        ``fetch_timeout_s`` per wedged worker) network fetches,
        heartbeat file reads, and the federation rebuild all run
        OUTSIDE the reader-facing state lock, which guards only the
        final swap: readers of the federated view never stall behind a
        sick worker, which is exactly when the debug surface matters
        most."""
        with self._poll_lock:
            return self._poll_under_lock()

    def _fetch_worker(self, wid: int) -> Tuple[Optional[dict], bool]:
        snap = self._fetch_http(wid)
        if snap is not None:
            return snap, True
        return self._fetch_file(wid)

    def _pool(self):
        """One persistent fetch pool for the aggregator's lifetime —
        a fresh executor per poll would spawn/join N threads per second
        at the production cadence. ``_poll_lock`` serializes users."""
        if self._fetch_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._fetch_pool = ThreadPoolExecutor(
                max_workers=min(self.num_workers, 16),
                thread_name_prefix="agg-fetch")
        return self._fetch_pool

    def close(self) -> None:
        """Release the fetch pool's threads (the supervisor calls this
        on teardown; last-known snapshots stay readable after close)."""
        pool, self._fetch_pool = self._fetch_pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    def _poll_under_lock(self) -> dict:
        """The body of one poll; caller holds ``_poll_lock``."""
        t0 = time.perf_counter()
        m = self.metrics
        # fetch workers CONCURRENTLY (pure blocking IO): one poll is
        # bounded by ~one fetch_timeout_s, not num_workers of them —
        # several wedged-but-accepting workers must not stretch a poll
        # past the cadence exactly when the cohort is sick
        if self.num_workers == 1:
            fetched = {0: self._fetch_worker(0)}
        else:
            futures = {wid: self._pool().submit(self._fetch_worker, wid)
                       for wid in range(self.num_workers)}
            fetched = {wid: f.result() for wid, f in futures.items()}
        with self._lock:
            snapshots = dict(self._snapshots)
        live: Dict[int, bool] = {}
        max_step = 0.0
        steps: Dict[int, float] = {}
        def _snap_time(s: dict) -> float:
            try:
                return float(s.get("time", 0.0))
            except (TypeError, ValueError):
                return 0.0

        for wid in range(self.num_workers):
            w = str(wid)
            m.worker_polls_total.inc(worker=w)
            snap, up = fetched[wid]
            if snap is not None:
                held = snapshots.get(wid)
                # last-known means NEWEST-known: a stale sink file left
                # behind (worker's disk full, old generation) must not
                # overwrite a fresher HTTP snapshot after the worker
                # dies — the dossier's 'final state' depends on it
                if held is None or _snap_time(snap) >= _snap_time(held):
                    snapshots[wid] = snap
            if not up and (wid in snapshots
                           or time.monotonic() - self._started
                           > self.startup_grace_s):
                # a worker we have NEVER seen, inside the startup
                # grace, is still booting (jax import takes seconds) —
                # not a liveness failure; counting it would hold the
                # cohort-liveness rule in pending on every clean
                # launch. A worker that stays invisible past the grace
                # IS down.
                m.worker_poll_failures_total.inc(worker=w)
            live[wid] = up
            known = snapshots.get(wid)
            m.worker_up.set(1.0 if up else 0.0, worker=w)
            if known is not None:
                steps[wid] = _snapshot_last_step(known)
                max_step = max(max_step, steps[wid])
                m.worker_generation.set(
                    float(known.get("generation", 1)), worker=w)
                m.worker_last_step.set(steps[wid], worker=w)
                m.worker_snapshot_age_seconds.set(
                    max(0.0, time.time() - float(known.get("time", 0.0))),
                    worker=w)
        for wid, st in steps.items():
            m.worker_step_lag.set(max_step - st, worker=str(wid))
        m.workers_up.set(float(sum(live.values())))
        if self._restarts is not None:
            try:
                m.restarts_total.set(float(self._restarts()))
            except Exception:  # noqa: BLE001 — telemetry never raises
                pass
        m.polls_total.inc()
        if self._topology is not None:
            try:
                topo = self._topology()
                m.workers_active.set(
                    float(topo.get("workers_active", self.num_workers)))
                degraded = bool(topo.get("degraded"))
                m.degraded.set(1.0 if degraded else 0.0)
                if degraded:
                    # time-in-degraded-mode accumulator: one tick per
                    # poll, so degraded_ticks/polls IS the degraded
                    # fraction the burn-rate rule evaluates
                    m.degraded_ticks_total.inc()
            except Exception:  # noqa: BLE001 — telemetry never raises
                pass
        else:
            # no supervisor-provided shape: the polled range IS the
            # topology (plain aggregators are never degraded)
            m.workers_active.set(float(self.num_workers))
            m.degraded.set(0.0)
        if self.heartbeat_dir is not None:
            from deeplearning4j_tpu.resilience.cluster import (
                read_heartbeats,
            )

            beats = read_heartbeats(self.heartbeat_dir)
            now = time.time()
            for wid in range(self.num_workers):
                doc = beats.get(wid)
                age = (now - float(doc.get("time", now))
                       if doc is not None else -1.0)
                m.worker_heartbeat_age_seconds.set(
                    round(age, 3), worker=str(wid))
        insts = federate_instruments(
            snapshots,
            on_conflict=lambda name, _reason:
                m.federation_conflicts_total.inc(name=name))
        with self._lock:
            self._snapshots = snapshots
            self._live = live
            self._federated_insts = insts
            self._last_poll = time.monotonic()
        m.poll_seconds.observe(time.perf_counter() - t0)
        return self.workers()

    def _stale(self, max_age_s: float) -> bool:
        last = self._last_poll
        return last is None or time.monotonic() - last > max_age_s

    def ensure_fresh(self, max_age_s: float) -> None:
        """Poll now if the last poll is older than ``max_age_s`` (the
        on-demand scrape path — a /cluster/metrics GET must not serve a
        view staler than one poll interval). Non-blocking: when a poll
        is already in flight (possibly slow against a wedged cohort),
        serve the last-known view instead of queueing — and re-check
        staleness after acquiring, so N handler threads never each
        re-run a full poll."""
        if not self._stale(max_age_s):
            return
        if not self._poll_lock.acquire(blocking=False):
            return  # a poll is running right now; stale view is fine
        try:
            if self._stale(max_age_s):
                self._poll_under_lock()
        finally:
            self._poll_lock.release()

    # -- cluster artifacts ---------------------------------------------------

    def federated_instruments(self) -> List[_metrics._Instrument]:
        with self._lock:
            return list(self._federated_insts)

    def registries(self) -> List:
        """Cluster gauges first, then the federated worker series —
        the order render_text_multi resolves collisions in (the
        aggregator's own families win)."""
        return [self.metrics.registry, self.federated]

    def render_metrics_text(self, *, openmetrics: bool = False) -> str:
        return render_text_multi(self.registries(), openmetrics=openmetrics)

    def render_metrics_json(self) -> dict:
        return render_json_multi(self.registries())

    def workers(self) -> dict:
        with self._lock:
            return self._workers_locked()

    def _workers_locked(self) -> dict:
        rows = []
        for wid in range(self.num_workers):
            snap = self._snapshots.get(wid)
            row = {"worker": wid, "up": bool(self._live.get(wid, False)),
                   "snapshot": snap is not None}
            if snap is not None:
                row.update({
                    "generation": snap.get("generation"),
                    "pid": snap.get("pid"),
                    "last_step": _snapshot_last_step(snap),
                    "snapshot_age_s": round(
                        max(0.0, time.time() - float(snap.get("time", 0.0))),
                        3),
                    "flight_events": snap.get("flight", {}).get("count", 0),
                    "spans": len(snap.get("spans", [])),
                    "requests": len(snap.get("requests", [])),
                })
            rows.append(row)
        return {"num_workers": self.num_workers,
                "up": sum(1 for r in rows if r["up"]),
                "workers": rows}

    def cluster_timeline(self, last_seconds: Optional[float] = None) -> dict:
        """Every worker's flight events merged into one ordered
        timeline. Events already carry worker identity (stamped at the
        source by ``flightrecorder.record``); events from pre-identity
        rings are stamped here from the snapshot they rode in on."""
        with self._lock:
            snaps = dict(self._snapshots)
        events: List[dict] = []
        dropped = 0
        for wid, snap in sorted(snaps.items()):
            dump = snap.get("flight", {})
            dropped += int(dump.get("dropped_total", 0))
            for ev in dump.get("events", []):
                if "worker" not in ev:
                    ev = dict(ev, worker=wid,
                              generation=snap.get("generation", 1))
                events.append(ev)
        if self._local_events is not None:
            try:
                for ev in self._local_events():
                    if isinstance(ev, dict):
                        if "worker" not in ev:
                            ev = dict(ev, worker="supervisor")
                        events.append(ev)
            except Exception:  # noqa: BLE001 — the merged view degrades
                pass           # to workers-only, never fails
        if last_seconds is not None:
            cutoff = time.time() - last_seconds
            events = [e for e in events if e.get("t", 0.0) >= cutoff]
        events.sort(key=lambda e: e.get("t", 0.0))
        return {"workers": sorted(snaps), "dropped_total": dropped,
                "window_seconds": last_seconds, "count": len(events),
                "events": events}

    def worker_spans(self) -> Dict[int, List[_trace.Span]]:
        with self._lock:
            snaps = dict(self._snapshots)
        return {wid: [_trace.Span.from_json(d)
                      for d in snap.get("spans", [])]
                for wid, snap in sorted(snaps.items())}

    def cluster_chrome_trace(self, *, synthesize_roots: bool = True) -> dict:
        """One Perfetto document over the whole cohort: worker *i*'s
        spans on pid lane ``i + 1`` (named ``worker-i``), with
        synthesized ``cluster.step`` root spans joining each step's
        per-worker collective legs (which share a coordinator-minted
        trace id but whose root exists in no single worker's ring)."""
        return stitch_chrome_trace(self.worker_spans(),
                                   synthesize_roots=synthesize_roots)

    def cluster_incidents(self) -> dict:
        """Every worker's incident-bundle index, worker/generation-
        stamped and merged (newest first) — the cohort's incident view
        (``GET /cluster/debug/incidents``). Built from last-known
        snapshots, so a dead worker's open incidents stay visible."""
        with self._lock:
            snaps = dict(self._snapshots)
        rows: List[dict] = []
        for wid, snap in sorted(snaps.items()):
            for inc in snap.get("incidents", []):
                rows.append(dict(inc, worker=wid,
                                 generation=snap.get("generation", 1)))
        def _opened(r):
            # opened_at arrives over HTTP from version-skewed peers: a
            # non-numeric value must sort low, never crash the cohort
            # view (dossier() runs this during crash-report writing)
            try:
                return float(r.get("opened_at") or 0.0)
            except (TypeError, ValueError):
                return 0.0

        rows.sort(key=lambda r: -_opened(r))
        return {"workers": sorted(snaps), "count": len(rows),
                "open": sum(1 for r in rows if r.get("state") == "open"),
                "incidents": rows}

    def cluster_requests(self, *, outcome: Optional[str] = None,
                         tenant: Optional[str] = None,
                         model: Optional[str] = None,
                         min_latency_s: Optional[float] = None,
                         limit: int = 100) -> dict:
        """Every worker's recent request-ledger records, worker/
        generation-stamped and merged newest-first — the cohort request
        view (``GET /cluster/debug/requests``). Built from last-known
        snapshots, so a dead worker's requests stay answerable."""
        with self._lock:
            snaps = dict(self._snapshots)
        rows: List[dict] = []
        for wid, snap in sorted(snaps.items()):
            for rec in snap.get("requests", []):
                if outcome is not None and rec.get("outcome") != outcome:
                    continue
                if tenant is not None and rec.get("tenant") != tenant:
                    continue
                if model is not None and rec.get("model") != model:
                    continue
                if min_latency_s is not None and \
                        (rec.get("latency_s") or 0.0) < min_latency_s:
                    continue
                rows.append(dict(rec, worker=wid,
                                 generation=snap.get("generation", 1)))

        def _started(r):
            try:
                return float(r.get("t_start") or 0.0)
            except (TypeError, ValueError):
                return 0.0

        rows.sort(key=_started, reverse=True)
        rows = rows[:max(1, int(limit))]
        return {"workers": sorted(snaps), "count": len(rows),
                "requests": rows}

    def cluster_trace_export(self, *, plane: Optional[str] = None,
                             model: Optional[str] = None) -> dict:
        """The fleet-wide replayable trace: every worker's recent
        ledger records merged and reduced to payload-scrubbed trace
        rows, ordered by absolute arrival wall-time across workers
        (``GET /cluster/debug/requests?format=trace``). A trace
        recorded from N workers replays against one target as the
        cohort's combined offered load."""
        with self._lock:
            snaps = dict(self._snapshots)
        records: List[dict] = []
        for _wid, snap in sorted(snaps.items()):
            records.extend(snap.get("requests", []))
        return _reqlog.trace_from_records(records, plane=plane,
                                          model=model)

    def _timeseries_stores(self) -> Dict[int, tuple]:
        """Queryable per-worker TSDB stores rebuilt from last-known
        snapshot documents: {worker: (store, generation, anchor_time)}.
        Built from last-known snapshots, so a dead worker's history
        stays queryable (anchored at its final snapshot time)."""
        from deeplearning4j_tpu.observability.timeseries import (
            store_from_snapshot,
        )

        with self._lock:
            snaps = dict(self._snapshots)
        stores: Dict[int, tuple] = {}
        for wid, snap in sorted(snaps.items()):
            doc = snap.get("timeseries")
            if not isinstance(doc, dict):
                continue
            anchor = doc.get("time") or snap.get("time")
            key = (wid, anchor)
            store = self._ts_cache.get(key)
            if store is None:
                store = store_from_snapshot(doc)
                # one cached store per worker: drop the stale build
                self._ts_cache = {k: v for k, v in self._ts_cache.items()
                                  if k[0] != wid}
                if store is not None:
                    self._ts_cache[key] = store
            if store is not None:
                stores[wid] = (store, snap.get("generation", 1), anchor)
        return stores

    def cluster_timeseries(self, family: Optional[str] = None, *,
                           op: str = "range", window_s: float = 600.0,
                           step_s: Optional[float] = None,
                           q: Optional[float] = None,
                           labels: Optional[Dict[str, str]] = None) -> dict:
        """The fleet history query (``GET /cluster/debug/timeseries``):
        every worker's store answers over its own trailing window
        (anchored at that worker's last snapshot time, so a dead
        worker's final history still answers), series stamped with
        worker/generation labels. Without ``family``: the merged
        catalog. ``rate`` aggregates to the fleet-wide sum; ``max`` to
        the fleet max; quantiles stay per-worker (cross-worker
        quantiles cannot be merged from values — read the per-worker
        documents)."""
        stores = self._timeseries_stores()
        if family is None:
            fams: Dict[str, List[int]] = {}
            for wid, (store, _gen, _anchor) in stores.items():
                for name in store.families():
                    fams.setdefault(name, []).append(wid)
            return {"workers": sorted(stores),
                    "families": {n: sorted(w)
                                 for n, w in sorted(fams.items())}}
        out: dict = {"family": family, "op": op,
                     "window_s": float(window_s),
                     "workers": sorted(stores), "series": []}
        agg = None
        for wid, (store, gen, anchor) in stores.items():
            try:
                if op == "rate":
                    doc = store.rate(family, window_s=window_s,
                                     step_s=step_s, labels=labels,
                                     now=anchor)
                    agg = (agg or 0.0) + doc.get("rate", 0.0)
                elif op == "quantile":
                    doc = store.quantile_over_time(
                        family, float(q if q is not None else 0.99),
                        window_s=window_s, labels=labels, now=anchor)
                    out["series"].append({
                        "labels": {"worker": str(wid),
                                   "generation": str(gen)},
                        "value": doc.get("value"),
                        "count": doc.get("count")})
                    continue
                elif op == "max":
                    doc = store.max_over_time(family, window_s=window_s,
                                              labels=labels, now=anchor)
                    v = doc.get("value")
                    if v is not None:
                        agg = v if agg is None else max(agg, v)
                else:
                    doc = store.range(family, window_s=window_s,
                                      step_s=step_s, labels=labels,
                                      now=anchor)
            except Exception:  # noqa: BLE001 — a version-skewed worker's
                continue       # store must not fail the fleet query
            for series in doc.get("series", []):
                lbls = dict(series.get("labels") or {})
                lbls["worker"] = str(wid)
                lbls["generation"] = str(gen)
                out["series"].append(dict(series, labels=lbls))
        if op == "rate":
            out["rate"] = agg or 0.0
        elif op == "max":
            out["value"] = agg
        return out

    def cluster_usage(self) -> dict:
        """The fleet usage ledger (``GET /cluster/debug/usage``):
        every worker's accounts worker/generation-stamped, plus fleet
        roll-ups per (tenant, model) and overall. Built from last-known
        snapshots — a dead worker's final attribution is retained."""
        with self._lock:
            snaps = dict(self._snapshots)
        rows: List[dict] = []
        fleet: Dict[tuple, dict] = {}
        totals = {"requests": 0, "errors": 0, "tokens_in": 0,
                  "tokens_out": 0}
        for wid, snap in sorted(snaps.items()):
            doc = snap.get("usage")
            if not isinstance(doc, dict):
                continue
            gen = snap.get("generation", 1)
            for acct in doc.get("tenants", []):
                if not isinstance(acct, dict):
                    continue
                rows.append(dict(acct, worker=wid, generation=gen))
                key = (acct.get("tenant"), acct.get("model"))
                agg = fleet.setdefault(key, {
                    "tenant": key[0], "model": key[1], "requests": 0,
                    "errors": 0, "tokens_in": 0, "tokens_out": 0})
                for k in totals:
                    try:
                        v = int(acct.get(k) or 0)
                    except (TypeError, ValueError):
                        v = 0
                    agg[k] += v
                    totals[k] += v
        return {"workers": sorted(snaps), "accounts": rows,
                "fleet": sorted(fleet.values(),
                                key=lambda a: (-a["requests"],
                                               str(a["tenant"]))),
                "totals": totals}

    def cluster_capacity(self) -> dict:
        """The fleet capacity view (``GET /cluster/debug/capacity``):
        per-worker headroom reports plus per-model fleet aggregates
        (rates and peaks sum across workers serving the same model;
        fleet headroom = 1 - sum(rate)/sum(peak)) and the worst
        verdict. The autoscaler's fleet-level input contract."""
        with self._lock:
            snaps = dict(self._snapshots)
        rank = {"ok": 0, "warn": 1, "exhausted": 2}
        workers: List[dict] = []
        fleet: Dict[str, dict] = {}
        worst = "ok"
        for wid, snap in sorted(snaps.items()):
            doc = snap.get("capacity")
            if not isinstance(doc, dict):
                continue
            workers.append(dict(doc, worker=wid,
                                generation=snap.get("generation", 1)))
            for model, row in (doc.get("models") or {}).items():
                if not isinstance(row, dict):
                    continue
                agg = fleet.setdefault(model, {
                    "rate_rps": 0.0, "peak_rps": 0.0, "workers": 0})
                try:
                    agg["rate_rps"] += float(row.get("rate_rps") or 0.0)
                    agg["peak_rps"] += float(row.get("peak_rps") or 0.0)
                except (TypeError, ValueError):
                    pass
                agg["workers"] += 1
                v = row.get("verdict")
                if rank.get(v, 0) > rank[worst]:
                    worst = v
        for model, agg in fleet.items():
            peak = agg["peak_rps"]
            agg["headroom"] = (round(1.0 - agg["rate_rps"] / peak, 4)
                               if peak > 0 else 1.0)
        return {"workers": workers, "models": fleet, "verdict": worst}

    def cluster_request(self, cid: str) -> Optional[dict]:
        """Find one request by correlation id on whichever worker
        served it: the ledger record from that worker's snapshot plus
        its retained span tree reconstructed from the same snapshot's
        span dump (``GET /cluster/debug/requests/<id>``). The newest
        record wins when a retried request touched several workers."""
        with self._lock:
            snaps = dict(self._snapshots)
        best = None  # (t_start, worker, record, snapshot)
        for wid, snap in sorted(snaps.items()):
            for rec in snap.get("requests", []):
                if rec.get("cid") != cid:
                    continue
                try:
                    t = float(rec.get("t_start") or 0.0)
                except (TypeError, ValueError):
                    t = 0.0
                if best is None or t >= best[0]:
                    best = (t, wid, rec, snap)
        if best is None:
            return None
        _, wid, rec, snap = best
        spans = [d for d in snap.get("spans", [])
                 if d.get("trace_id") == cid]
        return {
            "worker": wid,
            "generation": snap.get("generation", 1),
            "record": dict(rec, worker=wid),
            "trace": {
                "retained": bool(spans),
                "reason": rec.get("trace_retained"),
                "span_count": len(spans),
                "spans": spans,
                "chrome": (_trace.to_chrome_trace(
                    [_trace.Span.from_json(d) for d in spans],
                    pid=wid + 1, process_name=f"worker-{wid}")
                    if spans else None),
            },
        }

    def dossier(self) -> dict:
        """The cohort post-mortem bundle: worker table + merged
        timeline + every worker's LAST-KNOWN full snapshot (the dead
        worker's final pre-crash state included) + the open incidents
        the cohort was carrying at teardown. The supervisor writes this
        into the crash report on cohort teardown."""
        with self._lock:
            snaps = dict(self._snapshots)
            table = self._workers_locked()
        incidents = self.cluster_incidents()
        return {"workers": table, "timeline": self.cluster_timeline(),
                "open_incidents": [r for r in incidents["incidents"]
                                   if r.get("state") == "open"],
                "snapshots": {str(w): s for w, s in sorted(snaps.items())}}


# the deterministic per-step root ids runtime/distributed.py derives:
# 8-hex cluster prefix + 'r' marker + 8-hex step — a shape new_id()
# (pure 16-hex) can never produce
_STEP_ROOT_RE = re.compile(r"^[0-9a-f]{8}r[0-9a-f]{8}$")


def synthesize_step_roots(spans: Sequence[_trace.Span]
                          ) -> List[_trace.Span]:
    """For every *step-root* parent id referenced but owned by no span
    (the deterministic per-step root ids ``runtime/distributed.py``
    derives on every worker — recognizable by their ``r`` marker),
    synthesize one ``cluster.step`` root spanning its children — so a
    stitched trace renders each step's collective legs as ONE tree
    instead of N orphans. Ordinary orphans (a parent still open at
    snapshot time, or evicted from the bounded tracer ring) are left
    alone: fabricating a root there would collide with the real parent
    when a later snapshot carries it."""
    spans = list(spans)
    owned = {s.span_id for s in spans}
    orphans: Dict[Tuple[str, str], List[_trace.Span]] = {}
    for s in spans:
        if s.parent_id and s.parent_id not in owned \
                and _STEP_ROOT_RE.match(s.parent_id):
            orphans.setdefault((s.trace_id, s.parent_id), []).append(s)
    roots = []
    for (trace_id, parent_id), children in sorted(orphans.items()):
        attrs = {"synthesized": True}
        step = children[0].attrs.get("step")
        if step is not None:
            attrs["step"] = step
        roots.append(_trace.Span(
            "cluster.step", trace_id=trace_id, span_id=parent_id,
            start=min(c.start for c in children),
            end=max(c.end for c in children),
            thread="cluster", attrs=attrs))
    return roots


def stitch_chrome_trace(worker_spans: Dict[int, List[_trace.Span]], *,
                        synthesize_roots: bool = True) -> dict:
    """Merge per-worker span sets into one Chrome-trace document with
    one pid lane per worker (``pid = worker + 1``, named
    ``worker-<id>``); synthesized roots ride on pid 0 (``cluster``).
    Lossless against :func:`trace.from_chrome_trace` — every span's
    ids/attrs/threads survive, and ``attrs["worker"]`` is stamped so
    the per-worker grouping itself round-trips."""
    events: List[dict] = []
    all_spans: List[_trace.Span] = []
    for wid, spans in sorted(worker_spans.items()):
        stamped = []
        for s in spans:
            if "worker" not in s.attrs:
                s = _trace.Span(
                    s.name, trace_id=s.trace_id, span_id=s.span_id,
                    parent_id=s.parent_id, start=s.start, end=s.end,
                    thread=s.thread, attrs=dict(s.attrs, worker=wid))
            stamped.append(s)
        all_spans.extend(stamped)
        doc = _trace.to_chrome_trace(stamped, pid=wid + 1,
                                     process_name=f"worker-{wid}")
        events.extend(doc["traceEvents"])
    if synthesize_roots:
        roots = synthesize_step_roots(all_spans)
        if roots:
            doc = _trace.to_chrome_trace(roots, pid=0,
                                         process_name="cluster")
            events.extend(doc["traceEvents"])
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# -- cluster SLO rules --------------------------------------------------------


def default_cluster_rules() -> List["_slo.SLORule"]:
    """The rules a supervisor-side HealthEngine evaluates against the
    federated registry when none are supplied: worker liveness (every
    poll should find every worker up) — mirrored by the
    ``cluster-worker-liveness`` rule in ``example_rules.json``."""
    from deeplearning4j_tpu.observability import slo as _slo

    return [
        _slo.SLORule(
            name="cluster-worker-liveness", kind="availability",
            objective=0.99,
            total=_slo.Selector("cluster_worker_polls_total"),
            bad=_slo.Selector("cluster_worker_poll_failures_total"),
            windows=_slo.DEFAULT_WINDOWS, for_s=60.0,
            resolve_hold_s=300.0),
    ]


# -- the supervisor-side HTTP surface -----------------------------------------


class ClusterTelemetryServer:
    """``GET /cluster/*`` — the cohort's health surface, served from the
    supervisor process over its :class:`ClusterAggregator`:

    - ``/cluster/metrics`` — federated scrape (cluster gauges UNION
      every worker's series, worker/generation-labeled);
      ``?format=json`` for the JSON twin;
    - ``/cluster/debug/workers`` — the worker table (up, generation,
      last step, snapshot age);
    - ``/cluster/debug/flightrecorder`` — merged ordered timeline
      (``?seconds=N`` trims);
    - ``/cluster/debug/trace`` — the stitched Perfetto document;
    - ``/cluster/debug/incidents`` — every worker's incident-bundle
      index merged (worker/generation-stamped, newest first);
    - ``/cluster/debug/requests`` — every worker's recent request-ledger
      records merged (``?outcome=&tenant=&model=&min_latency_ms=``);
      ``/cluster/debug/requests/<correlation-id>`` finds one request on
      whichever worker served it, retained span tree included;
    - ``/cluster/debug/health`` — the federated SLO engine's states
      (404 when no engine is attached);
    - ``/healthz``.

    Every GET freshens the aggregator if its last poll is older than
    ``max_staleness_s`` — an on-demand scrape never reads a stale view.
    """

    def __init__(self, aggregator: ClusterAggregator, *,
                 host: str = "127.0.0.1", port: int = 0,
                 engine: Optional["_slo.HealthEngine"] = None,
                 max_staleness_s: float = 1.0):
        self.aggregator = aggregator
        self.engine = engine
        self.max_staleness_s = max_staleness_s
        server = self

        class Handler(_JsonHandler):
            def do_GET(self):  # noqa: N802 - stdlib API
                path, _, query = self.path.partition("?")
                agg = server.aggregator
                if path == "/healthz":
                    self._send(200, {"status": "ok"})
                    return
                try:
                    agg.ensure_fresh(server.max_staleness_s)
                except Exception:  # noqa: BLE001 — serve the stale view
                    pass
                if path == "/cluster/metrics":
                    if "format=json" in query:
                        self._send(200, agg.render_metrics_json())
                    else:
                        om = wants_openmetrics(self.headers.get("Accept"))
                        self._send(
                            200,
                            agg.render_metrics_text(
                                openmetrics=om).encode(),
                            content_type=(CONTENT_TYPE_OPENMETRICS if om
                                          else CONTENT_TYPE_TEXT))
                elif path == "/cluster/debug/workers":
                    self._send(200, agg.workers())
                elif path == "/cluster/debug/flightrecorder":
                    seconds, ok = self._seconds_param(query)
                    if not ok:
                        return
                    self._send(200, agg.cluster_timeline(seconds))
                elif path == "/cluster/debug/trace":
                    self._send(200, agg.cluster_chrome_trace())
                elif path == "/cluster/debug/incidents":
                    self._send(200, agg.cluster_incidents())
                elif path == "/cluster/debug/requests":
                    q = parse_qs(query)
                    try:
                        min_latency_s = (
                            float(q["min_latency_ms"][0]) / 1000.0
                            if "min_latency_ms" in q else None)
                        limit = int(q.get("limit", ["100"])[0])
                    except ValueError:
                        self._send(400, {"error": "min_latency_ms and "
                                                  "limit must be numbers"})
                        return
                    if q.get("format", [None])[0] == "trace":
                        self._send(200, agg.cluster_trace_export(
                            plane=q.get("plane", [None])[0],
                            model=q.get("model", [None])[0]))
                        return
                    self._send(200, agg.cluster_requests(
                        outcome=q.get("outcome", [None])[0],
                        tenant=q.get("tenant", [None])[0],
                        model=q.get("model", [None])[0],
                        min_latency_s=min_latency_s, limit=limit))
                elif path.startswith("/cluster/debug/requests/"):
                    cid = path[len("/cluster/debug/requests/"):]
                    body = agg.cluster_request(cid)
                    if body is None:
                        self._send(404, {"error": f"no request {cid!r} "
                                                  "on any worker"})
                    else:
                        self._send(200, body)
                elif path == "/cluster/debug/timeseries":
                    q = parse_qs(query)
                    try:
                        window_s = (float(q["window"][0])
                                    if "window" in q else 600.0)
                        step_s = (float(q["step"][0])
                                  if "step" in q else None)
                        quant = float(q["q"][0]) if "q" in q else None
                    except ValueError:
                        self._send(400, {"error": "window, step and q "
                                                  "must be numbers"})
                        return
                    labels = {k[len("label."):]: v[0]
                              for k, v in q.items()
                              if k.startswith("label.")}
                    if "model" in q:
                        labels["model"] = q["model"][0]
                    self._send(200, agg.cluster_timeseries(
                        q.get("family", [None])[0],
                        op=q.get("op", ["range"])[0],
                        window_s=window_s, step_s=step_s, q=quant,
                        labels=labels or None))
                elif path == "/cluster/debug/usage":
                    self._send(200, agg.cluster_usage())
                elif path == "/cluster/debug/capacity":
                    self._send(200, agg.cluster_capacity())
                elif path == "/cluster/debug/health":
                    if server.engine is None:
                        self._send(404, {"error": "no cluster health "
                                                  "engine attached"})
                    elif "format=text" in query:
                        server.engine.tick()
                        self._send(200,
                                   server.engine.render_text().encode(),
                                   content_type="text/plain")
                    else:
                        self._send(200, server.engine.tick())
                else:
                    self._send(404, {"error": f"no route {path}"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host = self._httpd.server_address[0]
        return f"http://{host}:{self.port}"

    def start(self) -> "ClusterTelemetryServer":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="cluster-telemetry")
        self._thread.start()
        return self

    def stop(self):
        if self._thread is not None:
            # shutdown() blocks on an event only serve_forever() sets —
            # calling it on a never-started server deadlocks
            self._httpd.shutdown()
            self._thread.join(timeout=5)
            self._thread = None
        self._httpd.server_close()

    def __enter__(self) -> "ClusterTelemetryServer":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
