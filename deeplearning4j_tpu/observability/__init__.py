"""Unified telemetry core (↔ the reference's StatsListener / UIServer /
ProfilingListener family as ONE spine instead of per-layer silos).

- ``metrics``: Counter/Gauge/Histogram on a process-global default
  registry with Prometheus text + JSON exposition; per-layer bundles
  (training, resilience, checkpoint) register lazily so one scrape of a
  running ``ModelServer`` tells the whole story — serving AND training
  AND recovery AND runtime series.
- ``trace``: nested spans with correlation IDs propagated from
  ``ServingClient`` request headers through admission, batch assembly,
  and ``ParallelInference`` dispatch; exported as JSONL and Chrome-trace
  JSON, loadable in Perfetto alongside the XLA traces.
- ``runtime``: device-memory / live-array gauges, XLA recompile events
  (count + wall time via jax.monitoring), host↔device transfer counters.

``metrics.set_enabled(False)`` / ``trace.set_tracing_enabled(False)``
turn the hot-path instrumentation off; ``bench.py observability``
measures its cost (instrumented vs bare step time, span enter/exit,
registry render latency).

The diagnostics plane consumes the spine (PR 4):

- ``slo``: declarative SLO rules + multi-window burn-rate alerting over
  the registries' counters/histograms, an ok→pending→firing→resolved
  alert state machine per rule, a background :class:`HealthEngine`
  evaluator, and a ``--check`` CLI for offline rule validation;
- ``flightrecorder``: the black-box ring of structured events every
  layer feeds (train steps, sheds, rollbacks, quarantines, fault
  injections, alert transitions) — dumped into every crash report and
  served at ``GET /debug/flightrecorder``.
"""

from deeplearning4j_tpu.observability.federation import (
    ClusterAggregator,
    ClusterMetrics,
    ClusterTelemetryServer,
    FederatedRegistry,
    TelemetryExporter,
    build_snapshot,
    default_cluster_rules,
    federate_instruments,
    get_process_exporter,
    set_process_exporter,
    stitch_chrome_trace,
    synthesize_step_roots,
    telemetry_exporter_from_env,
    telemetry_port,
)
from deeplearning4j_tpu.observability.flightrecorder import (
    FlightRecorder,
    get_flight_recorder,
    record_event,
    recording_enabled,
    set_flight_recorder,
    set_recording,
)
from deeplearning4j_tpu.observability.hostsampler import (
    HostStackSampler,
    get_host_sampler,
    set_host_sampler,
)
from deeplearning4j_tpu.observability.incidents import (
    IncidentManager,
    get_incident_manager,
    incident_index,
    register_profile_hook,
    request_step_capture,
    set_incident_manager,
    unregister_profile_hook,
)
from deeplearning4j_tpu.observability.metrics import (
    COMPILE_BUCKETS,
    CONTENT_TYPE_OPENMETRICS,
    CONTENT_TYPE_TEXT,
    DEFAULT_LATENCY_BUCKETS,
    OCCUPANCY_BUCKETS,
    CheckpointMetrics,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    ResilienceMetrics,
    TrainingMetrics,
    default_registry,
    enabled,
    get_checkpoint_metrics,
    get_resilience_metrics,
    get_training_metrics,
    render_json_multi,
    render_text_multi,
    reset_default_registry,
    set_enabled,
    wants_openmetrics,
)
from deeplearning4j_tpu.observability.reqlog import (
    ReqLogMetrics,
    RequestLedger,
    get_reqlog_metrics,
    get_request_ledger,
    ledger_enabled,
    request_detail,
    request_index,
    set_ledger_enabled,
    set_request_ledger,
)
from deeplearning4j_tpu.observability.runtime import (
    RuntimeCollector,
    get_runtime_collector,
    record_transfer,
)
from deeplearning4j_tpu.observability.sentinel import (
    Detector,
    Sentinel,
    SentinelMetrics,
    default_detectors,
    default_fleet_detectors,
    get_sentinel_metrics,
)
from deeplearning4j_tpu.observability.slo import (
    DEFAULT_WINDOWS,
    BurnWindow,
    HealthEngine,
    Selector,
    SLOMetrics,
    SLORule,
    default_fleet_rules,
    default_serving_rules,
    get_default_engine,
    get_slo_metrics,
    load_rules,
    set_default_engine,
    validate_rules_doc,
)
from deeplearning4j_tpu.observability.trace import (
    RetentionPolicy,
    Span,
    TailSampler,
    Tracer,
    current_span,
    from_chrome_trace,
    get_tail_sampler,
    get_tracer,
    load_jsonl,
    new_id,
    record_span,
    set_tail_sampler,
    set_tracing_enabled,
    span,
    stitch_named_lanes,
    to_chrome_trace,
    tracing_enabled,
    write_chrome_trace,
)

__all__ = [
    "COMPILE_BUCKETS",
    "CONTENT_TYPE_OPENMETRICS",
    "CONTENT_TYPE_TEXT",
    "DEFAULT_LATENCY_BUCKETS",
    "DEFAULT_WINDOWS",
    "OCCUPANCY_BUCKETS",
    "BurnWindow",
    "CheckpointMetrics",
    "ClusterAggregator",
    "ClusterMetrics",
    "ClusterTelemetryServer",
    "Counter",
    "Detector",
    "FederatedRegistry",
    "FlightRecorder",
    "Gauge",
    "HealthEngine",
    "Histogram",
    "HostStackSampler",
    "IncidentManager",
    "MetricsRegistry",
    "ReqLogMetrics",
    "RequestLedger",
    "ResilienceMetrics",
    "RetentionPolicy",
    "RuntimeCollector",
    "SLOMetrics",
    "SLORule",
    "Selector",
    "Sentinel",
    "SentinelMetrics",
    "Span",
    "TailSampler",
    "TelemetryExporter",
    "Tracer",
    "TrainingMetrics",
    "build_snapshot",
    "current_span",
    "default_cluster_rules",
    "default_detectors",
    "default_fleet_detectors",
    "default_fleet_rules",
    "default_registry",
    "default_serving_rules",
    "enabled",
    "federate_instruments",
    "get_process_exporter",
    "set_process_exporter",
    "stitch_chrome_trace",
    "synthesize_step_roots",
    "telemetry_exporter_from_env",
    "telemetry_port",
    "from_chrome_trace",
    "get_checkpoint_metrics",
    "get_default_engine",
    "get_flight_recorder",
    "get_host_sampler",
    "get_incident_manager",
    "get_reqlog_metrics",
    "get_request_ledger",
    "get_resilience_metrics",
    "get_runtime_collector",
    "get_sentinel_metrics",
    "get_tail_sampler",
    "get_slo_metrics",
    "get_tracer",
    "get_training_metrics",
    "incident_index",
    "ledger_enabled",
    "load_jsonl",
    "load_rules",
    "new_id",
    "record_event",
    "request_detail",
    "request_index",
    "record_span",
    "record_transfer",
    "recording_enabled",
    "register_profile_hook",
    "render_json_multi",
    "render_text_multi",
    "request_step_capture",
    "reset_default_registry",
    "set_default_engine",
    "set_enabled",
    "set_flight_recorder",
    "set_host_sampler",
    "set_incident_manager",
    "set_ledger_enabled",
    "set_recording",
    "set_request_ledger",
    "set_tail_sampler",
    "set_tracing_enabled",
    "unregister_profile_hook",
    "span",
    "stitch_named_lanes",
    "to_chrome_trace",
    "tracing_enabled",
    "validate_rules_doc",
    "wants_openmetrics",
    "write_chrome_trace",
]
