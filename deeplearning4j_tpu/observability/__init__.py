"""Unified telemetry core (↔ the reference's StatsListener / UIServer /
ProfilingListener family as ONE spine instead of per-layer silos).

- ``metrics``: Counter/Gauge/Histogram on a process-global default
  registry with Prometheus text + JSON exposition; per-layer bundles
  (training, resilience, checkpoint) register lazily so one scrape of a
  running ``ModelServer`` tells the whole story — serving AND training
  AND recovery AND runtime series.
- ``trace``: nested spans with correlation IDs propagated from
  ``ServingClient`` request headers through admission, batch assembly,
  and ``ParallelInference`` dispatch; exported as JSONL and Chrome-trace
  JSON, loadable in Perfetto alongside the XLA traces.
- ``runtime``: device-memory / live-array gauges, XLA recompile events
  (count + wall time via jax.monitoring), host↔device transfer counters.

``metrics.set_enabled(False)`` / ``trace.set_tracing_enabled(False)``
turn the hot-path instrumentation off; ``bench.py observability``
measures its cost (instrumented vs bare step time, span enter/exit,
registry render latency).
"""

from deeplearning4j_tpu.observability.metrics import (
    COMPILE_BUCKETS,
    DEFAULT_LATENCY_BUCKETS,
    OCCUPANCY_BUCKETS,
    CheckpointMetrics,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    ResilienceMetrics,
    TrainingMetrics,
    default_registry,
    enabled,
    get_checkpoint_metrics,
    get_resilience_metrics,
    get_training_metrics,
    render_json_multi,
    render_text_multi,
    reset_default_registry,
    set_enabled,
)
from deeplearning4j_tpu.observability.runtime import (
    RuntimeCollector,
    get_runtime_collector,
    record_transfer,
)
from deeplearning4j_tpu.observability.trace import (
    Span,
    Tracer,
    current_span,
    from_chrome_trace,
    get_tracer,
    load_jsonl,
    new_id,
    record_span,
    set_tracing_enabled,
    span,
    to_chrome_trace,
    tracing_enabled,
    write_chrome_trace,
)

__all__ = [
    "COMPILE_BUCKETS",
    "DEFAULT_LATENCY_BUCKETS",
    "OCCUPANCY_BUCKETS",
    "CheckpointMetrics",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "ResilienceMetrics",
    "RuntimeCollector",
    "Span",
    "Tracer",
    "TrainingMetrics",
    "current_span",
    "default_registry",
    "enabled",
    "from_chrome_trace",
    "get_checkpoint_metrics",
    "get_resilience_metrics",
    "get_runtime_collector",
    "get_tracer",
    "get_training_metrics",
    "load_jsonl",
    "new_id",
    "record_span",
    "record_transfer",
    "render_json_multi",
    "render_text_multi",
    "reset_default_registry",
    "set_enabled",
    "set_tracing_enabled",
    "span",
    "to_chrome_trace",
    "tracing_enabled",
    "write_chrome_trace",
]
