"""Per-tenant / per-model usage metering and capacity headroom.

The request ledger answers "what happened to request X"; ``/metrics``
answers "how is the process doing". Neither answers the accounting
question — *who* consumed the fleet, in which currency (requests,
tokens, device-batch-seconds, FLOPs) — or the planning question — how
close is each backend to its measured peak. This module adds both:

- :class:`UsageMeter`: bounded-cardinality accounts keyed
  (tenant, model), fed from the request ledger's finish path (both
  serving planes flow through it, so predict and generation meter
  uniformly) and from the model registry's ``on_batch`` hook
  (device-batch-seconds and estimated FLOPs = static ``cost_analysis``
  x batches). The FLOPs-per-batch cache is keyed by the entry's
  **active version**, so a hot-swap or rollback re-resolves the cost
  model instead of billing the old version's FLOPs. Accounts roll up
  into the time-series store as synthetic cumulative families
  (``usage_*_total``) on the sampler cadence, and
  :meth:`UsageMeter.describe` reconciles metered request counts against
  the ledger's window.
- :class:`CapacityEvaluator`: per-model offered load (rate over the
  store) vs the measured running peak -> occupancy, headroom, trend
  and an ``ok`` / ``warn`` / ``exhausted`` verdict per model and for
  the backend — the input contract for the autoscaler (ROADMAP item
  5). Verdict flips are flight-recorded; the exhausted condition also
  ticks a counter pair that the ``capacity-headroom-exhausted``
  burn-rate rule consumes.

Served at ``GET /debug/usage`` / ``GET /debug/capacity`` and federated
at ``/cluster/debug/{usage,capacity}``. Stdlib only; every hook
swallows its own failures — metering never fails serving.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu.observability import metrics as _metrics
from deeplearning4j_tpu.observability.flightrecorder import record_event

ENV_USAGE_MAX_ACCOUNTS = "DL4J_TPU_USAGE_MAX_ACCOUNTS"
ENV_USAGE_ROLLUP_S = "DL4J_TPU_USAGE_ROLLUP_S"

#: Overflow bucket: once the account table is full, new tenants fold
#: into this pseudo-tenant per model instead of growing the table.
OVERFLOW_TENANT = "__other__"

#: Tenant label used when a request carried no tenant annotation.
ANON_TENANT = "-"


class UsageMetrics:
    """The meter's own exposition (default registry)."""

    def __init__(self, registry: Optional[_metrics.MetricsRegistry] = None):
        r = registry if registry is not None else _metrics.default_registry()
        self.registry = r
        ns = "usage"
        self.accounts = r.gauge(
            "accounts", "Live (tenant, model) usage accounts (bounded "
            "by DL4J_TPU_USAGE_MAX_ACCOUNTS; overflow folds into the "
            "__other__ tenant).", namespace=ns)
        self.overflow_total = r.counter(
            "overflow_total", "Records folded into the __other__ "
            "overflow tenant because the account table was full.",
            namespace=ns)
        self.errors_total = r.counter(
            "errors_total", "Metering hook invocations that raised and "
            "were swallowed — usage accounting never fails serving.",
            namespace=ns)


class CapacityMetrics:
    """The capacity evaluator's exposition. The tick pair feeds the
    ``capacity-headroom-exhausted`` burn-rate rule (bad/total)."""

    def __init__(self, registry: Optional[_metrics.MetricsRegistry] = None):
        r = registry if registry is not None else _metrics.default_registry()
        self.registry = r
        ns = "capacity"
        self.ticks_total = r.counter(
            "ticks_total", "Capacity evaluation passes (the burn-rate "
            "rule's total stream).", namespace=ns)
        self.exhausted_ticks_total = r.counter(
            "exhausted_ticks_total", "Evaluation passes during which at "
            "least one model's headroom verdict was 'exhausted' (the "
            "burn-rate rule's bad stream).", namespace=ns)
        self.headroom = r.gauge(
            "headroom", "Current headroom fraction per model: 1 - "
            "offered_rate / measured_peak_rate (1.0 = idle, 0.0 = at "
            "measured peak).", labelnames=("model",), namespace=ns)
        self.peak_rps = r.gauge(
            "peak_rps", "Measured peak request rate per model — the "
            "running max of observed window rates (re-seeded from "
            "TSDB history after a warm restart).",
            labelnames=("model",), namespace=ns)


_usage_metrics: Optional[UsageMetrics] = None
_capacity_metrics: Optional[CapacityMetrics] = None
_um_lock = threading.Lock()


def get_usage_metrics() -> UsageMetrics:
    global _usage_metrics
    if _usage_metrics is None:
        with _um_lock:
            if _usage_metrics is None:
                _usage_metrics = UsageMetrics()
    return _usage_metrics


def get_capacity_metrics() -> CapacityMetrics:
    global _capacity_metrics
    if _capacity_metrics is None:
        with _um_lock:
            if _capacity_metrics is None:
                _capacity_metrics = CapacityMetrics()
    return _capacity_metrics


def _drop_usage_metrics():
    global _usage_metrics, _capacity_metrics
    _usage_metrics = None
    _capacity_metrics = None


_metrics.register_reset_hook(_drop_usage_metrics)


def _usage_metrics_or_none() -> Optional[UsageMetrics]:
    try:
        if not _metrics.enabled():
            return None
        return get_usage_metrics()
    except Exception:  # noqa: BLE001
        return None


def _capacity_metrics_or_none() -> Optional[CapacityMetrics]:
    try:
        if not _metrics.enabled():
            return None
        return get_capacity_metrics()
    except Exception:  # noqa: BLE001
        return None


def _new_tenant_account() -> dict:
    return {"requests": 0, "errors": 0, "tokens_in": 0, "tokens_out": 0,
            "planes": {}}


def _new_model_account() -> dict:
    return {"batches": 0, "batched_requests": 0, "batch_seconds": 0.0,
            "est_flops": 0.0, "flops_unresolved_batches": 0}


class UsageMeter:
    """Cumulative usage accounts on both serving planes.

    Feed it with :meth:`on_record` (install via
    ``reqlog.set_usage_sink``) and :meth:`on_batch` (install via
    ``ModelRegistry.add_batch_listener``); point :meth:`collect` at a
    :class:`~deeplearning4j_tpu.observability.timeseries.TimeSeriesStore`
    collector slot to get history. All hooks swallow their own
    exceptions and count them.
    """

    def __init__(self, *, max_accounts: Optional[int] = None,
                 cost_resolver: Optional[Callable] = None,
                 clock: Optional[Callable[[], float]] = None):
        if max_accounts is None:
            try:
                max_accounts = int(
                    os.environ.get(ENV_USAGE_MAX_ACCOUNTS) or 256)
            except ValueError:
                max_accounts = 256
        if max_accounts < 1:
            raise ValueError(
                f"max_accounts must be >= 1, got {max_accounts}")
        self.max_accounts = int(max_accounts)
        self._lock = threading.Lock()
        self._tenants: Dict[Tuple[str, str], dict] = {}
        self._models: Dict[str, dict] = {}
        # FLOPs-per-batch keyed by the entry's ACTIVE version: a
        # hot-swap/rollback changes the version, so the next batch
        # re-resolves cost_analysis instead of billing the old
        # version's cost model (the /debug/costs drift fix).
        self._cost_cache: Dict[Tuple[str, str, int], Optional[float]] = {}
        self._cost_resolver = cost_resolver
        self._clock = clock if clock is not None else time.time
        self._overflow = 0
        self._overflow_seen: set = set()
        self._started = self._clock()

    def set_cost_resolver(self, fn: Optional[Callable]) -> None:
        """``fn(model_name) -> ModelEntry | None`` — how the meter
        finds the active entry (and therefore the active version) when
        pricing a batch. ModelServer installs its registry's ``get``."""
        self._cost_resolver = fn

    # -- write path -----------------------------------------------------------

    def on_record(self, rec: dict) -> None:
        """Ledger finish sink: attribute one sealed request record to
        its (tenant, model) account. Never raises."""
        try:
            model = str(rec.get("model") or "?")
            tenant = str(rec.get("tenant") or ANON_TENANT)
            plane = str(rec.get("plane") or "?")
            outcome = str(rec.get("outcome") or "?")
            tokens_out = rec.get("tokens")
            tokens_in = rec.get("prompt_len")
            with self._lock:
                acct = self._account_locked(tenant, model)
                acct["requests"] += 1
                if outcome not in ("ok", "completed"):
                    acct["errors"] += 1
                planes = acct["planes"]
                planes[plane] = planes.get(plane, 0) + 1
                if tokens_in is not None:
                    acct["tokens_in"] += int(tokens_in)
                if tokens_out is not None:
                    acct["tokens_out"] += int(tokens_out)
        except Exception:  # noqa: BLE001 — metering never fails serving
            m = _usage_metrics_or_none()
            if m is not None:
                m.errors_total.inc()

    def _account_locked(self, tenant: str, model: str) -> dict:
        key = (tenant, model)
        acct = self._tenants.get(key)
        if acct is not None:
            return acct
        if len(self._tenants) >= self.max_accounts \
                and tenant != OVERFLOW_TENANT:
            # table full: fold into the per-model overflow tenant (its
            # accounts are bounded by the registry's model count)
            self._overflow += 1
            m = _usage_metrics_or_none()
            if m is not None:
                m.overflow_total.inc()
            if model not in self._overflow_seen:
                self._overflow_seen.add(model)
                record_event("usage.overflow", model=model,
                             max_accounts=self.max_accounts)
            return self._account_locked(OVERFLOW_TENANT, model)
        acct = self._tenants[key] = _new_tenant_account()
        m = _usage_metrics_or_none()
        if m is not None:
            m.accounts.set(len(self._tenants))
        return acct

    def on_batch(self, name: str, n_requests: int, rows: int,
                 bucket: int, seconds: float) -> None:
        """Registry batch listener: device-batch-seconds and estimated
        FLOPs (static cost x 1 batch) per model. Never raises."""
        try:
            flops = self._flops_for(name, int(bucket or rows or 1))
            with self._lock:
                acct = self._models.get(name)
                if acct is None:
                    acct = self._models[name] = _new_model_account()
                acct["batches"] += 1
                acct["batched_requests"] += int(n_requests)
                acct["batch_seconds"] += float(seconds)
                if flops is not None:
                    acct["est_flops"] += float(flops)
                else:
                    acct["flops_unresolved_batches"] += 1
        except Exception:  # noqa: BLE001 — metering never fails serving
            m = _usage_metrics_or_none()
            if m is not None:
                m.errors_total.inc()

    def _flops_for(self, name: str, rows: int) -> Optional[float]:
        resolver = self._cost_resolver
        if resolver is None:
            return None
        try:
            entry = resolver(name)
            if entry is None:
                return None
            version = str(entry.version)
            key = (name, version, rows)
            if key in self._cost_cache:
                return self._cost_cache[key]
            ca = entry.cost_analysis(rows=rows)
            flops = (float(ca["flops"])
                     if ca.get("available") and ca.get("flops") else None)
            if len(self._cost_cache) > 256:     # bounded: versions churn
                self._cost_cache.clear()
            self._cost_cache[key] = flops
            return flops
        except Exception:  # noqa: BLE001 — cost pricing is best-effort
            return None

    # -- read path ------------------------------------------------------------

    def collect(self, now: float) -> List[tuple]:
        """TSDB collector: the accounts as synthetic cumulative
        families — ``(family, labels, kind, value)`` tuples for
        :meth:`TimeSeriesStore.ingest`."""
        out: List[tuple] = []
        with self._lock:
            for (tenant, model), acct in self._tenants.items():
                base = {"tenant": tenant, "model": model}
                out.append(("usage_tenant_requests_total", base,
                            "counter", acct["requests"]))
                out.append(("usage_tenant_tokens_total",
                            dict(base, direction="in"), "counter",
                            acct["tokens_in"]))
                out.append(("usage_tenant_tokens_total",
                            dict(base, direction="out"), "counter",
                            acct["tokens_out"]))
            for model, acct in self._models.items():
                lbl = {"model": model}
                out.append(("usage_model_batches_total", lbl, "counter",
                            acct["batches"]))
                out.append(("usage_model_batch_seconds_total", lbl,
                            "counter", acct["batch_seconds"]))
                out.append(("usage_model_est_flops_total", lbl,
                            "counter", acct["est_flops"]))
        return out

    def describe(self, *, ledger=None) -> dict:
        """The ``/debug/usage`` document. With a ledger, each account
        carries a reconciliation block: the ledger's retained-window
        count for the same (tenant, model) and whether the cumulative
        meter covers it (it must — both are fed from the same finish
        path; a shortfall means lost attribution)."""
        ledger_counts: Dict[Tuple[str, str], int] = {}
        if ledger is not None:
            try:
                for rec in ledger.recent(limit=4096):
                    if rec.get("state") != "done":
                        continue
                    key = (str(rec.get("tenant") or ANON_TENANT),
                           str(rec.get("model") or "?"))
                    ledger_counts[key] = ledger_counts.get(key, 0) + 1
            except Exception:  # noqa: BLE001 — reconciliation is advisory
                ledger_counts = {}
        with self._lock:
            tenants = []
            totals = {"requests": 0, "errors": 0, "tokens_in": 0,
                      "tokens_out": 0}
            for (tenant, model), acct in sorted(self._tenants.items()):
                row = {"tenant": tenant, "model": model, **{
                    k: v for k, v in acct.items() if k != "planes"},
                    "planes": dict(acct["planes"])}
                for k in totals:
                    totals[k] += acct[k]
                if ledger_counts or ledger is not None:
                    # overflow accounts aggregate many real tenants;
                    # their ledger twin is under the real tenant names,
                    # so reconciliation only applies to direct accounts
                    lw = ledger_counts.get((tenant, model))
                    if tenant != OVERFLOW_TENANT and lw is not None:
                        row["reconciliation"] = {
                            "ledger_window": lw,
                            "metered": acct["requests"],
                            "covered": acct["requests"] >= lw,
                        }
                tenants.append(row)
            models = {m: dict(a) for m, a in sorted(self._models.items())}
            return {
                "since": self._started,
                "max_accounts": self.max_accounts,
                "accounts": len(self._tenants),
                "overflow_folds": self._overflow,
                "tenants": tenants,
                "models": models,
                "totals": totals,
            }


class CapacityEvaluator:
    """Headroom verdicts per model and backend, from TSDB history.

    ``evaluate()`` reads offered load per model off the store (request
    counters on both planes), tracks the measured running peak, and
    derives occupancy / headroom / trend / verdict. Thresholds are on
    headroom: below ``warn_headroom`` -> ``warn``; below
    ``exhausted_headroom`` -> ``exhausted``. The report is the
    autoscaler's input contract: a scale-out candidate is a backend
    whose verdict is warn/exhausted with a rising trend; scale-to-zero
    wants ``ok`` with rate ~0 over the long window.
    """

    RATE_FAMILIES = ("serving_requests_total", "generation_requests_total")

    def __init__(self, store, *, resolver: Optional[Callable] = None,
                 warn_headroom: float = 0.30,
                 exhausted_headroom: float = 0.10,
                 window_s: float = 60.0, trend_window_s: float = 600.0,
                 clock: Optional[Callable[[], float]] = None):
        if not 0.0 <= exhausted_headroom <= warn_headroom <= 1.0:
            raise ValueError(
                "need 0 <= exhausted_headroom <= warn_headroom <= 1, "
                f"got {exhausted_headroom}/{warn_headroom}")
        self.store = store
        self.warn_headroom = float(warn_headroom)
        self.exhausted_headroom = float(exhausted_headroom)
        self.window_s = float(window_s)
        self.trend_window_s = float(trend_window_s)
        self._resolver = resolver
        self._clock = clock if clock is not None else time.time
        self._lock = threading.Lock()
        self._peak: Dict[str, float] = {}
        self._verdicts: Dict[str, str] = {}
        self._footprints: Dict[Tuple[str, str], dict] = {}
        self.last: Optional[dict] = None

    def set_resolver(self, fn: Optional[Callable]) -> None:
        """``fn(model) -> ModelEntry | None`` for footprint data."""
        self._resolver = fn

    def _rates(self, now: float, window_s: float) -> Dict[str, float]:
        rates: Dict[str, float] = {}
        for family in self.RATE_FAMILIES:
            doc = self.store.rate(family, window_s=window_s, now=now)
            for series in doc.get("series", []):
                model = series.get("labels", {}).get("model", "?")
                rates[model] = rates.get(model, 0.0) + series.get(
                    "rate", 0.0)
        return rates

    def _seed_peak(self, model: str, now: float) -> float:
        """After a warm restart the running peak restarts at 0 but the
        restored TSDB still holds the ``capacity_peak_rps`` gauge
        history — re-seed from it so one restart doesn't erase the
        measured peak."""
        try:
            doc = self.store.max_over_time(
                "capacity_peak_rps", window_s=self.store.tiers[-1].coverage_s,
                labels={"model": model}, now=now)
            return float(doc.get("value") or 0.0)
        except Exception:  # noqa: BLE001
            return 0.0

    def _footprint(self, model: str) -> Optional[dict]:
        resolver = self._resolver
        if resolver is None:
            return None
        try:
            entry = resolver(model)
            if entry is None:
                return None
            version = str(entry.version)
            key = (model, version)
            cached = self._footprints.get(key)
            if cached is not None:
                return dict(cached)
            ca = entry.cost_analysis()
            fp = {"version": version,
                  "rows": ca.get("rows"),
                  "flops_per_batch": ca.get("flops"),
                  "bytes_per_batch": ca.get("bytes_accessed"),
                  "available": bool(ca.get("available"))}
            if len(self._footprints) > 64:
                self._footprints.clear()
            self._footprints[key] = fp
            return dict(fp)
        except Exception:  # noqa: BLE001 — footprint is best-effort
            return None

    def evaluate(self, now: Optional[float] = None) -> dict:
        """One pass: the ``/debug/capacity`` document (also cached on
        ``self.last`` for the federation snapshot). Never raises."""
        t = self._clock() if now is None else now
        cm = _capacity_metrics_or_none()
        try:
            short = self._rates(t, self.window_s)
            long = self._rates(t, self.trend_window_s)
        except Exception:  # noqa: BLE001 — a store hiccup yields idle
            short, long = {}, {}
        models: Dict[str, dict] = {}
        worst = "ok"
        rank = {"ok": 0, "warn": 1, "exhausted": 2}
        with self._lock:
            for model in sorted(set(short) | set(self._peak)):
                rate = short.get(model, 0.0)
                peak = self._peak.get(model)
                if peak is None:
                    peak = self._seed_peak(model, t)
                peak = max(peak, rate)
                self._peak[model] = peak
                occupancy = rate / peak if peak > 0 else 0.0
                headroom = 1.0 - occupancy
                if headroom < self.exhausted_headroom:
                    verdict = "exhausted"
                elif headroom < self.warn_headroom:
                    verdict = "warn"
                else:
                    verdict = "ok"
                lr = long.get(model, 0.0)
                if rate > lr * 1.2 and rate - lr > 0.1:
                    trend = "rising"
                elif lr > rate * 1.2 and lr - rate > 0.1:
                    trend = "falling"
                else:
                    trend = "flat"
                prev = self._verdicts.get(model)
                if prev != verdict:
                    self._verdicts[model] = verdict
                    record_event("capacity.verdict", model=model,
                                 verdict=verdict, prev=prev,
                                 headroom=round(headroom, 4),
                                 rate_rps=round(rate, 4),
                                 peak_rps=round(peak, 4))
                row = {"rate_rps": rate, "peak_rps": peak,
                       "occupancy": round(occupancy, 4),
                       "headroom": round(headroom, 4),
                       "verdict": verdict, "trend": trend}
                fp = self._footprint(model)
                if fp is not None:
                    row["footprint"] = fp
                models[model] = row
                if rank[verdict] > rank[worst]:
                    worst = verdict
                if cm is not None:
                    cm.headroom.set(headroom, model=model)
                    cm.peak_rps.set(peak, model=model)
        if cm is not None:
            cm.ticks_total.inc()
            if worst == "exhausted":
                cm.exhausted_ticks_total.inc()
        report = {
            "time": t,
            "window_s": self.window_s,
            "trend_window_s": self.trend_window_s,
            "thresholds": {"warn_headroom": self.warn_headroom,
                           "exhausted_headroom": self.exhausted_headroom},
            "models": models,
            "verdict": worst,
        }
        self.last = report
        try:
            # lazy import: federation pulls usage only inside guarded
            # index helpers, so this cannot cycle at import time
            from deeplearning4j_tpu.observability.federation import (
                publish_capacity_report,
            )

            publish_capacity_report(report)
        except Exception:  # noqa: BLE001 — federation is optional here
            pass
        return report

    def report(self) -> dict:
        """Latest cached report (evaluating once if never run)."""
        return self.last if self.last is not None else self.evaluate()

    def collect(self, now: float) -> List[tuple]:
        """TSDB collector slot: run an evaluation on the sampler
        cadence (the headroom/peak gauges it sets are scraped into
        history by the same sampler pass)."""
        self.evaluate(now)
        return []


# -- process-global meter (federation snapshot + zero-config consumers) -------

_METER: Optional[UsageMeter] = None
_meter_lock = threading.Lock()


def set_usage_meter(meter: Optional[UsageMeter]) -> None:
    global _METER
    with _meter_lock:
        _METER = meter


def get_usage_meter(create: bool = False) -> Optional[UsageMeter]:
    global _METER
    if _METER is None and create:
        with _meter_lock:
            if _METER is None:
                _METER = UsageMeter()
    return _METER


def usage_index(*, ledger=None) -> Optional[dict]:
    """This process's usage document, or None — what the federation
    snapshot embeds (never creates a meter as a side effect, never
    raises)."""
    meter = get_usage_meter()
    if meter is None:
        return None
    try:
        return meter.describe(ledger=ledger)
    except Exception:  # noqa: BLE001 — telemetry never fails the caller
        return None


__all__ = [
    "ANON_TENANT",
    "ENV_USAGE_MAX_ACCOUNTS",
    "ENV_USAGE_ROLLUP_S",
    "OVERFLOW_TENANT",
    "CapacityEvaluator",
    "CapacityMetrics",
    "UsageMeter",
    "UsageMetrics",
    "get_capacity_metrics",
    "get_usage_meter",
    "get_usage_metrics",
    "set_usage_meter",
    "usage_index",
]
