"""Shared metrics core: Counter / Gauge / Histogram on one registry.

Promoted out of ``serving/metrics.py`` (which remains a thin re-export)
so every layer — serving, train, resilience, serde, data, runtime
collectors — feeds ONE process-global default registry and a single
scrape tells the whole story (↔ the reference's StatsListener/UIServer
family, where one StatsStorage held every module's series).

Exposition semantics follow the Prometheus text format scrapers expect:
``# HELP``/``# TYPE`` headers (HELP text escaped per the format:
backslash and newline), cumulative ``_bucket{le=...}`` series,
``_sum``/``_count``. A JSON twin serves scripts and tests. Exemplars
(kept per histogram bucket by ``observe(..., exemplar_trace_id=)``)
appear only in the JSON twin and in the OpenMetrics rendering a client
negotiates via ``Accept: application/openmetrics-text`` — never in the
classic text format, whose grammar forbids them.

Registration is strict: a second instrument under an already-reserved
name — including a histogram's derived ``_bucket``/``_sum``/``_count``
sample names — raises with a clear error naming the prior owner, so two
subsystems can never silently interleave samples in one family.

Thread-safety: every mutation takes the instrument's lock — serving
handlers, ParallelInference workers, checkpoint writer threads, and the
training loop all write concurrently.

``set_enabled(False)`` is the kill switch the instrumented hot paths
consult (Trainer.fit, recovery, checkpoint, inference): recording
becomes a no-op so ``bench.py observability`` can measure the
instrumentation's own cost against a bare run.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_INF = float("inf")

# Latency buckets spanning sub-ms host overhead to multi-second cold paths.
DEFAULT_LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                           0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
# rows/bucket of a dispatched device batch — 1.0 means no padding waste.
OCCUPANCY_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)
# XLA compiles: tens of ms (cache hit) to minutes (cold BERT via a relay).
COMPILE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                   60.0, 120.0)

_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Content types the /metrics endpoints negotiate between. Exemplars are
# an *OpenMetrics* construct: a classic-format parser treats the
# mid-line '#' as garbage and rejects the whole scrape, so the default
# (classic) rendering NEVER carries them — a client opts in via
# ``Accept: application/openmetrics-text`` and gets the exemplar
# suffixes plus the mandatory ``# EOF`` trailer.
CONTENT_TYPE_TEXT = "text/plain; version=0.0.4"
CONTENT_TYPE_OPENMETRICS = ("application/openmetrics-text; "
                            "version=1.0.0; charset=utf-8")


def wants_openmetrics(accept: Optional[str]) -> bool:
    """Did the request's Accept header negotiate OpenMetrics?

    Deliberately conservative: OpenMetrics only when the client asks
    for it WITHOUT also accepting the classic text format. A stock
    Prometheus server (>= 2.49) advertises both media types with
    q-values and reliably parses classic, so it gets the classic
    document — serving a type the client listed is valid content
    negotiation, and this hand-rolled OpenMetrics variant is
    "OpenMetrics-style" (counter families keep their ``_total`` names)
    rather than strictly spec-compliant, so it is reserved for clients
    that explicitly ask for it alone (curl, tests, exemplar-aware
    tooling). Media types compare case-insensitively (RFC 9110)."""
    accept = (accept or "").lower()
    if "application/openmetrics-text" not in accept:
        return False
    return "text/plain" not in accept


def _fmt(v: float) -> str:
    f = float(v)
    # NaN/±Inf are legal Prometheus sample values; crashing on them here
    # would poison EVERY future scrape of the registry over one bad
    # observation (f == int(f) raises on non-finite floats).
    if f != f:
        return "NaN"
    if f == _INF:
        return "+Inf"
    if f == -_INF:
        return "-Inf"
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _esc_label(v) -> str:
    """Label-value escaping: backslash, double-quote, newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _esc_help(v) -> str:
    """HELP-text escaping per the exposition format: backslash and
    newline only (quotes are legal in help text)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


class _Instrument:
    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        if not _METRIC_NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_NAME_RE.match(ln) or ln.startswith("__"):
                raise ValueError(f"invalid label name {ln!r} on {name!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._data: Dict[Tuple[str, ...], object] = {}

    def sample_names(self) -> Tuple[str, ...]:
        """Every exposition sample-line name this instrument owns — the
        registry reserves all of them to reject cross-family collisions."""
        return (self.name,)

    def _key(self, labels: dict) -> Tuple[str, ...]:
        if not labels and not self.labelnames:
            return ()  # fast path: label-less hot-loop instruments
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}")
        return tuple(str(labels[k]) for k in self.labelnames)

    def _label_str(self, key: Tuple[str, ...], extra: str = "") -> str:
        parts = [f'{k}="{_esc_label(v)}"'
                 for k, v in zip(self.labelnames, key)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def remove(self, **labels) -> bool:
        """Drop one label series from the family (e.g. the federation
        layer pruning a departed worker's gauges when the cohort
        shrinks); returns True when the series existed."""
        key = self._key(labels)
        with self._lock:
            return self._data.pop(key, None) is not None


class Counter(_Instrument):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels):
        key = self._key(labels)
        with self._lock:
            self._data[key] = self._data.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._data.get(self._key(labels), 0.0))

    def render(self, *, openmetrics: bool = False) -> List[str]:
        with self._lock:
            return [f"{self.name}{self._label_str(k)} {_fmt(v)}"
                    for k, v in sorted(self._data.items())]

    def to_json(self) -> dict:
        with self._lock:
            samples = [{"labels": dict(zip(self.labelnames, k)), "value": v}
                       for k, v in sorted(self._data.items())]
        return {"name": self.name, "type": self.kind, "help": self.help,
                "samples": samples}


class Gauge(Counter):
    kind = "gauge"

    def set(self, value: float, **labels):
        key = self._key(labels)
        with self._lock:
            self._data[key] = float(value)

    def dec(self, amount: float = 1.0, **labels):
        self.inc(-amount, **labels)


class Histogram(_Instrument):
    kind = "histogram"

    def __init__(self, name, help, labelnames=(),
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(buckets)) + (_INF,)

    def sample_names(self) -> Tuple[str, ...]:
        return (self.name, f"{self.name}_bucket", f"{self.name}_sum",
                f"{self.name}_count")

    def observe(self, value: float, *, exemplar_trace_id: Optional[str] = None,
                **labels):
        """Record one observation. ``exemplar_trace_id`` (OpenMetrics-
        style exemplars) keeps the LAST exemplar per bucket — a slow
        bucket in the scrape links straight to a trace id that actually
        landed in it (the serving path passes the request's correlation
        id)."""
        key = self._key(labels)
        with self._lock:
            st = self._data.get(key)
            if st is None:
                st = self._data[key] = {
                    "counts": [0] * len(self.buckets), "sum": 0.0, "n": 0}
            for i, b in enumerate(self.buckets):
                if value <= b:
                    st["counts"][i] += 1
                    if exemplar_trace_id is not None:
                        st.setdefault("exemplars", {})[i] = (
                            str(exemplar_trace_id), float(value),
                            time.time())
                    break
            st["sum"] += float(value)
            st["n"] += 1

    def summary(self, **labels) -> Dict[str, float]:
        """{'count', 'sum', 'mean'} for one label set (0s when unseen)."""
        with self._lock:
            st = self._data.get(self._key(labels))
            if st is None:
                return {"count": 0, "sum": 0.0, "mean": 0.0}
            return {"count": st["n"], "sum": st["sum"],
                    "mean": st["sum"] / st["n"] if st["n"] else 0.0}

    def render(self, *, openmetrics: bool = False) -> List[str]:
        lines = []
        with self._lock:
            for key, st in sorted(self._data.items()):
                cum = 0
                exemplars = st.get("exemplars", {}) if openmetrics else {}
                for i, (b, c) in enumerate(zip(self.buckets, st["counts"])):
                    cum += c
                    le = 'le="%s"' % _fmt(b)
                    line = f"{self.name}_bucket{self._label_str(key, le)} {cum}"
                    ex = exemplars.get(i)
                    if ex is not None:
                        # OpenMetrics exemplar suffix on the bucket the
                        # observation landed in:
                        #   ... # {trace_id="<id>"} <value> <timestamp>
                        # (only under the negotiated OpenMetrics format —
                        # a classic parser errors on the mid-line '#')
                        tid, val, ts = ex
                        line += (f' # {{trace_id="{_esc_label(tid)}"}} '
                                 f"{_fmt(val)} {repr(round(ts, 3))}")
                    lines.append(line)
                lines.append(f"{self.name}_sum{self._label_str(key)} "
                             f"{_fmt(st['sum'])}")
                lines.append(f"{self.name}_count{self._label_str(key)} "
                             f"{st['n']}")
        return lines

    def to_json(self) -> dict:
        with self._lock:
            samples = []
            for key, st in sorted(self._data.items()):
                cum, bucket_map = 0, {}
                for b, c in zip(self.buckets, st["counts"]):
                    cum += c
                    bucket_map[_fmt(b)] = cum
                sample = {"labels": dict(zip(self.labelnames, key)),
                          "sum": st["sum"], "count": st["n"],
                          "buckets": bucket_map}
                if st.get("exemplars"):
                    sample["exemplars"] = {
                        _fmt(self.buckets[i]): {"trace_id": tid,
                                                "value": val, "t": ts}
                        for i, (tid, val, ts)
                        in sorted(st["exemplars"].items())}
                samples.append(sample)
        return {"name": self.name, "type": self.kind, "help": self.help,
                "samples": samples}


class MetricsRegistry:
    """A set of named instruments rendered together.

    ``namespace=`` on the constructors prefixes the metric name
    (``counter("steps_total", ..., namespace="train")`` registers
    ``train_steps_total``) — the one-registry-many-subsystems
    convention that keeps family names collision-free by layer.
    """

    def __init__(self):
        self._instruments: List[_Instrument] = []
        # every sample-line name any instrument exposes -> owning family
        self._reserved: Dict[str, str] = {}
        self._lock = threading.Lock()

    def _add(self, inst: _Instrument) -> _Instrument:
        with self._lock:
            for n in inst.sample_names():
                owner = self._reserved.get(n)
                if owner is not None:
                    raise ValueError(
                        f"duplicate metric registration: {inst.kind} "
                        f"{inst.name!r} would expose sample name {n!r}, "
                        f"already owned by instrument {owner!r} — metric "
                        "names must be unique per registry")
            for n in inst.sample_names():
                self._reserved[n] = inst.name
            self._instruments.append(inst)
        return inst

    @staticmethod
    def _full_name(name: str, namespace: Optional[str]) -> str:
        return f"{namespace}_{name}" if namespace else name

    def counter(self, name, help, labelnames=(), *,
                namespace: Optional[str] = None) -> Counter:
        return self._add(Counter(self._full_name(name, namespace), help,
                                 labelnames))

    def gauge(self, name, help, labelnames=(), *,
              namespace: Optional[str] = None) -> Gauge:
        return self._add(Gauge(self._full_name(name, namespace), help,
                               labelnames))

    def histogram(self, name, help, labelnames=(),
                  buckets=DEFAULT_LATENCY_BUCKETS, *,
                  namespace: Optional[str] = None) -> Histogram:
        return self._add(Histogram(self._full_name(name, namespace), help,
                                   labelnames, buckets))

    def instruments(self) -> List[_Instrument]:
        with self._lock:
            return list(self._instruments)

    def names(self) -> List[str]:
        return [i.name for i in self.instruments()]

    def render_text(self, *, openmetrics: bool = False) -> str:
        return render_text_multi([self], openmetrics=openmetrics)

    def render_json(self) -> dict:
        return render_json_multi([self])


def render_text_multi(registries: Sequence[MetricsRegistry], *,
                      openmetrics: bool = False) -> str:
    """One exposition document over several registries (first wins on a
    family-name collision — how the serving bundle's private registry and
    the process default merge into one scrape).

    ``openmetrics=True`` renders the negotiated OpenMetrics variant:
    histogram buckets carry their exemplar suffixes and the document
    ends with the mandatory ``# EOF`` marker. The default (classic
    ``text/plain; version=0.0.4``) document never carries exemplars —
    they are invalid in that grammar and would fail the whole scrape.
    """
    out: List[str] = []
    seen = set()
    for reg in registries:
        for inst in reg.instruments():
            if inst.name in seen:
                continue
            seen.add(inst.name)
            out.append(f"# HELP {inst.name} {_esc_help(inst.help)}")
            out.append(f"# TYPE {inst.name} {inst.kind}")
            out.extend(inst.render(openmetrics=openmetrics))
    if openmetrics:
        out.append("# EOF")
    return "\n".join(out) + "\n"


def render_json_multi(registries: Sequence[MetricsRegistry]) -> dict:
    out, seen = [], set()
    for reg in registries:
        for inst in reg.instruments():
            if inst.name in seen:
                continue
            seen.add(inst.name)
            out.append(inst.to_json())
    return {"metrics": out}


# -- process-global default registry ----------------------------------------

_DEFAULT = MetricsRegistry()
_BUNDLES: Dict[str, object] = {}
_RESET_HOOKS: List[Callable[[], None]] = []
_ENABLED = True
_state_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    """The process-global registry every built-in collector feeds; the
    ``/metrics`` endpoint renders it alongside the server's own bundle."""
    return _DEFAULT


def reset_default_registry() -> MetricsRegistry:
    """Replace the global registry with a fresh one (tests/bench): bundle
    singletons are dropped and re-create lazily on the new registry."""
    global _DEFAULT
    with _state_lock:
        _DEFAULT = MetricsRegistry()
        _BUNDLES.clear()
    for hook in list(_RESET_HOOKS):
        hook()
    return _DEFAULT


def register_reset_hook(fn: Callable[[], None]):
    """Run ``fn`` on every ``reset_default_registry`` (lets runtime.py
    drop its collector singleton without an import cycle)."""
    _RESET_HOOKS.append(fn)


def set_enabled(flag: bool):
    """Master switch for the built-in hot-path instrumentation."""
    global _ENABLED
    _ENABLED = bool(flag)


def enabled() -> bool:
    return _ENABLED


def _bundle(key: str, factory):
    b = _BUNDLES.get(key)
    if b is None:
        with _state_lock:
            b = _BUNDLES.get(key)
            if b is None:
                b = _BUNDLES[key] = factory(_DEFAULT)
    return b


# -- built-in bundles (lazy singletons on the default registry) -------------


class TrainingMetrics:
    """Trainer.fit hot-loop instruments (↔ PerformanceListener's numbers,
    continuously exported instead of printed)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        r = registry if registry is not None else default_registry()
        self.registry = r
        ns = "train"
        self.steps_total = r.counter(
            "steps_total", "Optimizer steps dispatched by Trainer.fit "
            "(TBPTT windows each count as one step).", namespace=ns)
        self.samples_total = r.counter(
            "samples_total",
            "Training samples consumed (leading batch dim).", namespace=ns)
        self.epochs_total = r.counter(
            "epochs_total", "Completed training epochs.", namespace=ns)
        self.step_seconds = r.histogram(
            "step_seconds",
            "Host wall time per dispatched train step. Dispatch is async: "
            "this measures the host loop's pace, not device latency — "
            "a backed-up pipeline shows up here, a fast one shows "
            "dispatch cost.", namespace=ns)
        self.data_read_seconds = r.histogram(
            "data_read_seconds",
            "Data-iterator next() latency as seen by the fit loop.",
            namespace=ns)
        # Diagnostics-plane gauges (train/trainer.py _StepTelemetry):
        self.step_flops = r.gauge(
            "step_flops", "Analytic FLOPs of one compiled train step "
            "(XLA cost_analysis; computed once per batch shape in a "
            "background thread).", namespace=ns)
        self.flops_per_second = r.gauge(
            "flops_per_second", "Analytic model FLOP/s: step_flops over "
            "the last measured host step wall-time.", namespace=ns)
        self.analytic_mfu = r.gauge(
            "analytic_mfu", "flops_per_second / peak chip FLOP/s; set "
            "only when DL4J_TPU_PEAK_FLOPS declares the peak.",
            namespace=ns)
        self.data_starved = r.gauge(
            "data_starved", "1 while data-read latency dominates step "
            "wall-time over the recent window (input pipeline is the "
            "bottleneck), else 0.", namespace=ns)


class ResilienceMetrics:
    """Recovery/crash events (resilience/recovery.py, retry.py,
    utils/crash.py) — previously only visible in local logs/files."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        r = registry if registry is not None else default_registry()
        self.registry = r
        ns = "resilience"
        self.rollbacks_total = r.counter(
            "rollbacks_total", "Rollbacks to the latest verified "
            "checkpoint (NaN/inf recovery).", namespace=ns)
        self.skipped_batches_total = r.counter(
            "skipped_batches_total",
            "Poison batches skipped on replay.", namespace=ns)
        self.lr_cuts_total = r.counter(
            "lr_cuts_total",
            "Learning-rate cuts applied after rollbacks.", namespace=ns)
        self.checkpoint_skips_total = r.counter(
            "checkpoint_skips_total", "Checkpoint saves refused because "
            "params were non-finite.", namespace=ns)
        self.data_retries_total = r.counter(
            "data_retries_total", "Transient data-read failures retried "
            "by RetryingIterator.", namespace=ns)
        self.crash_reports_total = r.counter(
            "crash_reports_total",
            "Crash dumps written by utils.crash.", namespace=ns)
        self.collective_timeouts_total = r.counter(
            "collective_timeouts_total",
            "Host collectives (barrier/broadcast/checkpoint sync) that "
            "exceeded the watchdog deadline (resilience/cluster.py).",
            namespace=ns)
        self.supervisor_restarts_total = r.counter(
            "supervisor_restarts_total",
            "Training-worker cohort relaunches by the elastic supervisor "
            "(resilience/supervisor.py).", namespace=ns)


class CheckpointMetrics:
    """serde/checkpoint.py latency + quarantine instruments."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        r = registry if registry is not None else default_registry()
        self.registry = r
        ns = "checkpoint"
        self.op_seconds = r.histogram(
            "op_seconds", "Checkpoint operation latency by op "
            "(save = snapshot serialization + atomic file IO, "
            "verify = manifest check, restore = load into a template).",
            ("op",), namespace=ns)
        self.quarantined_total = r.counter(
            "quarantined_total",
            "Corrupt checkpoints moved to quarantine/.", namespace=ns)


class WarmstartMetrics:
    """Cold-start robustness instruments: the persistent compile cache's
    integrity layer (runtime/compilecache.py) and the traffic-derived
    warmup manifests (serving/warmstart.py). Process-global — a compile
    cache is shared by every server/trainer in the process."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        r = registry if registry is not None else default_registry()
        self.registry = r
        self.cache_active = r.gauge(
            "compile_cache_active",
            "1 while a verified persistent compile cache directory is "
            "armed on jax (0 = cold compiles every process start).")
        self.cache_entries = r.gauge(
            "compile_cache_entries",
            "Artifacts currently recorded in the compile-cache "
            "integrity manifest.")
        self.cache_bytes = r.gauge(
            "compile_cache_bytes",
            "Total bytes of manifest-recorded compile-cache artifacts.")
        self.cache_quarantined_total = r.counter(
            "compile_cache_quarantined_total",
            "Cache artifacts quarantined instead of being handed to "
            "jax (corrupt = digest mismatch, truncated = size "
            "mismatch, version_skew = written by a different jax).",
            ("reason",))
        self.cache_op_seconds = r.histogram(
            "compile_cache_op_seconds",
            "Compile-cache integrity operation latency (verify = "
            "manifest walk + digests, seal = manifest rewrite).",
            ("op",))
        self.warmup_shapes_total = r.counter(
            "warmup_shapes_total",
            "Shapes AOT-compiled during warmup, by serving plane and "
            "shape source (manifest = the traffic-derived warmup "
            "manifest chose it, full = the closed bucket vocabulary).",
            ("plane", "source"))
        self.warmup_seconds = r.histogram(
            "warmup_seconds",
            "Per-shape warmup latency (compile + first dispatch).",
            ("plane",))
        self.manifest_entries = r.gauge(
            "warmup_manifest_entries",
            "Distinct (plane, model, shape) entries in the live warmup "
            "manifest.")
        self.manifest_writes_total = r.counter(
            "warmup_manifest_writes_total",
            "Atomic rewrites of the warmup-manifest file.")
        self.recompiles_after_warm_total = r.counter(
            "warmup_recompiles_after_warm_total",
            "Compiles observed AFTER a plane declared itself warm — "
            "the exact stall warmup exists to kill; the sentinel's "
            "recompile_after_warmup detector and the recompile-after-"
            "warmup burn-rate rule both gate this staying at zero.",
            ("plane",))


class SanitizerMetrics:
    """Runtime concurrency-sanitizer instruments (analysis/lockcheck.py).
    All-zero in a healthy process; the sanitizer-violation burn-rate
    rule pages when the lockorder sanitizer sees an inversion or a
    long hold in a canary/chaos environment."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        r = registry if registry is not None else default_registry()
        self.registry = r
        ns = "sanitizer"
        self.violations_total = r.counter(
            "violations_total",
            "Concurrency-invariant violations detected at runtime "
            "(rule = lock-order-inversion | lock-long-hold).",
            ("rule",), namespace=ns)
        self.lock_acquisitions_total = r.counter(
            "lock_acquisitions_total",
            "Acquisitions observed by instrumented locks while the "
            "lockorder sanitizer is armed (DL4J_TPU_SANITIZERS).",
            namespace=ns)
        self.locks_tracked = r.gauge(
            "locks_tracked",
            "Instrumented lock objects created while armed.",
            namespace=ns)
        self.lock_hold_seconds = r.histogram(
            "lock_hold_seconds",
            "Observed lock hold durations (instrumented locks only).",
            namespace=ns)


def get_training_metrics() -> TrainingMetrics:
    return _bundle("training", TrainingMetrics)


def get_resilience_metrics() -> ResilienceMetrics:
    return _bundle("resilience", ResilienceMetrics)


def get_checkpoint_metrics() -> CheckpointMetrics:
    return _bundle("checkpoint", CheckpointMetrics)


def get_warmstart_metrics() -> WarmstartMetrics:
    return _bundle("warmstart", WarmstartMetrics)


def get_sanitizer_metrics() -> SanitizerMetrics:
    return _bundle("sanitizer", SanitizerMetrics)


def warmstart_metrics_or_none() -> Optional[WarmstartMetrics]:
    """The warmstart bundle gated on the kill switch — the ONE guard
    every producer (compile cache, registry, generation engine, warmup
    manifest) shares, so the telemetry-off contract lives here and not
    in four drifting copies."""
    return get_warmstart_metrics() if _ENABLED else None
