"""Tracing spans: nested, correlation-ID-linked, Perfetto-loadable.

The metrics registry answers "how much / how often"; spans answer
"where did THIS request's time go". A :class:`Span` is one named,
timed interval with a ``trace_id`` (the correlation ID every span of
one logical request shares), a ``span_id``, and a ``parent_id`` link
forming the tree. Producers:

- ``span("name")`` — context manager with thread-local nesting (a span
  opened inside another becomes its child automatically);
- ``record_span(...)`` — post-hoc recording with explicit timestamps,
  for work measured on another thread (ParallelInference workers record
  the batch/dispatch legs of a request after the fact).

Correlation propagation over HTTP uses two headers the serving layer
reads and writes: ``X-Correlation-ID`` (the trace id) and ``X-Span-ID``
(the caller's span, adopted as the server-side root's parent) — so one
served request yields a linked tree: client → request → admission /
batch → dispatch.

Finished spans land in a process-global bounded ring (:class:`Tracer`)
and export two ways: JSONL (one span per line — the same convention as
train/listeners.py records) and Chrome-trace JSON (``ph: "X"`` complete
events) loadable in Perfetto next to the XLA traces from
train/profiling.py. The two forms convert losslessly in both
directions: ids, parent links, and attributes ride in the Chrome
events' ``args``.

**Tail-based sampling** (:class:`TailSampler` + :class:`RetentionPolicy`):
at millions-of-requests scale the ring cannot hold every request's
spans, yet the requests worth explaining — errors, sheds, preemptions,
deadline blow-ups, p99.9 stragglers — are exactly the ones head
sampling would have discarded before knowing they mattered. The tail
sampler inverts the decision: a request registered via ``begin(cid)``
has its spans diverted into a per-request *staging buffer* as they
finish, and only at request completion does the retention policy decide
keep-vs-drop — keep on a bad outcome, keep when the request's latency
sits far above a rolling baseline (sentinel's ``RollingBaseline``
machinery), plus a deterministic 1-in-N baseline sample. Kept requests'
spans land in the bounded ring like any other span; dropped requests
cost only the staging append. The serving request ledger
(``observability/reqlog.py``) drives ``begin``/``finish`` for every
request on both serving planes.

Stdlib only; safe to import from any layer (the retention policy's
rolling baseline is imported lazily from ``observability.sentinel``).
"""

from __future__ import annotations

import itertools
import json
import threading
import time
import uuid
from collections import OrderedDict, deque
from contextlib import contextmanager
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Wall-clock anchor + monotonic progression: timestamps are comparable
# across threads and meaningful as dates, but never go backwards the way
# raw time.time() can under NTP slew.
_T0 = time.time() - time.perf_counter()


def now() -> float:
    """Trace timestamp (seconds, wall-anchored monotonic)."""
    return _T0 + time.perf_counter()


# Span ids are minted on the serving hot path; uuid4 costs ~8 µs a call,
# so ids are a random-per-process 8-hex prefix + an atomic counter
# (itertools.count is GIL-atomic): unique across processes by the prefix,
# unique within one by the counter, ~0.3 µs a call.
_ID_PREFIX = uuid.uuid4().hex[:8]
_ID_COUNTER = itertools.count()


def new_id() -> str:
    """A fresh 16-hex-char correlation/span id."""
    return f"{_ID_PREFIX}{next(_ID_COUNTER) & 0xFFFFFFFF:08x}"


class Span:
    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start", "end",
                 "thread", "attrs")

    def __init__(self, name: str, *, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None, start: float = 0.0,
                 end: float = 0.0, thread: Optional[str] = None,
                 attrs: Optional[dict] = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = start
        self.end = end
        self.thread = thread
        self.attrs = attrs if attrs is not None else {}

    @property
    def duration(self) -> float:
        return max(0.0, self.end - self.start)

    def to_json(self) -> dict:
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "start": self.start, "end": self.end, "thread": self.thread,
                "attrs": dict(self.attrs)}

    @classmethod
    def from_json(cls, d: dict) -> "Span":
        return cls(d["name"], trace_id=d["trace_id"], span_id=d["span_id"],
                   parent_id=d.get("parent_id"), start=d.get("start", 0.0),
                   end=d.get("end", 0.0), thread=d.get("thread"),
                   attrs=dict(d.get("attrs", {})))

    def __repr__(self):
        return (f"Span({self.name!r}, trace={self.trace_id}, "
                f"id={self.span_id}, parent={self.parent_id}, "
                f"dur={self.duration * 1e3:.3f}ms)")


class Tracer:
    """Bounded ring of finished spans (oldest evicted first)."""

    def __init__(self, capacity: int = 4096):
        self._spans: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def record(self, span: Span):
        with self._lock:
            self._spans.append(span)

    def spans(self, trace_id: Optional[str] = None) -> List[Span]:
        with self._lock:
            snap = list(self._spans)
        if trace_id is None:
            return snap
        return [s for s in snap if s.trace_id == trace_id]

    def clear(self):
        with self._lock:
            self._spans.clear()

    def export_jsonl(self, path: str, trace_id: Optional[str] = None) -> int:
        """Append spans as JSONL; returns the number written."""
        spans = self.spans(trace_id)
        with open(path, "a") as fh:
            for s in spans:
                fh.write(json.dumps(s.to_json()) + "\n")
        return len(spans)


_TRACER = Tracer()
_ENABLED = True
_tls = threading.local()


def get_tracer() -> Tracer:
    return _TRACER


# -- tail-based sampling ------------------------------------------------------


class RetentionPolicy:
    """The completion-time keep-vs-drop decision for one request's spans.

    ``decide()`` returns the retention *reason* (a short string the
    ledger records and the ``trace_retained_total`` counter labels) or
    None to drop:

    - ``keep_outcomes`` — any outcome in the set is kept outright
      (errors, sheds, preemptions, deadline misses: the requests a
      post-mortem needs most);
    - ``"slow"`` — the request's latency scores ``slow_score`` robust-z
      above a rolling median+MAD baseline of *dropped-ok* latencies AND
      exceeds the median by ``min_increase`` (the sentinel discipline:
      kept-slow samples never feed the baseline, so a sustained
      regression cannot teach itself into "normal");
    - ``"sampled"`` — a deterministic 1-in-``sample_every`` baseline
      sample of everything else, so healthy-path traces exist to
      compare the tail against.
    """

    def __init__(self, *, sample_every: int = 128, slow_score: float = 8.0,
                 min_increase: float = 0.5, baseline_window: int = 128,
                 min_history: int = 16,
                 keep_outcomes: Optional[Iterable[str]] = None):
        if sample_every < 1:
            raise ValueError(
                f"sample_every must be >= 1, got {sample_every}")
        from deeplearning4j_tpu.observability.sentinel import RollingBaseline

        self.sample_every = int(sample_every)
        self.slow_score = float(slow_score)
        self.min_increase = float(min_increase)
        self.min_history = int(min_history)
        self.keep_outcomes = frozenset(
            keep_outcomes if keep_outcomes is not None
            else ("error", "failed", "shed", "preempted", "deadline"))
        self._baseline = RollingBaseline(baseline_window)
        self._count = itertools.count()
        self._lock = threading.Lock()

    def decide(self, *, outcome: str = "ok",
               latency_s: Optional[float] = None) -> Optional[str]:
        """Retention reason for one completed request, or None (drop)."""
        if outcome in self.keep_outcomes:
            return outcome
        with self._lock:
            n = next(self._count)
            slow = False
            if latency_s is not None \
                    and len(self._baseline) >= self.min_history \
                    and not self._baseline.degenerate():
                med = self._baseline.median()
                slow = (self._baseline.score(latency_s) >= self.slow_score
                        and latency_s >= med * (1.0 + self.min_increase))
            if not slow and latency_s is not None:
                # only dropped-or-sampled OK latencies teach "normal" —
                # a kept-slow request is the anomaly, not the baseline
                self._baseline.add(latency_s)
        if slow:
            return "slow"
        if n % self.sample_every == 0:
            return "sampled"
        return None

    def describe(self) -> dict:
        with self._lock:
            return {"sample_every": self.sample_every,
                    "slow_score": self.slow_score,
                    "min_increase": self.min_increase,
                    "min_history": self.min_history,
                    "keep_outcomes": sorted(self.keep_outcomes),
                    "baseline": self._baseline.to_json()}


class TailSampler:
    """Per-request span staging + completion-time retention.

    ``begin(trace_id)`` registers a request; every span finishing with
    that trace id is diverted into its staging buffer instead of the
    ring (``offer`` — one dict lookup on the span-finish hot path for
    unregistered traces). ``finish(trace_id, outcome=, latency_s=)``
    pops the buffer and either records every staged span into the
    tracer ring (kept) or drops them all.

    Bounded both ways: at most ``max_staged`` requests stage at once
    (oldest evicted — a request that never finishes must not pin spans
    forever) and at most ``max_spans_per_request`` spans per request
    (newest dropped, eviction counted on the buffer).
    """

    def __init__(self, *, policy: Optional[RetentionPolicy] = None,
                 max_staged: int = 512, max_spans_per_request: int = 256,
                 dropped_memory: int = 512):
        if max_staged < 1:
            raise ValueError(f"max_staged must be >= 1, got {max_staged}")
        self.policy = policy if policy is not None else RetentionPolicy()
        self.max_staged = int(max_staged)
        self.max_spans_per_request = int(max_spans_per_request)
        self._lock = threading.Lock()
        self._staged: "OrderedDict[str, List[Span]]" = OrderedDict()
        # trace ids recently decided DROPPED: a straggler span closing
        # after the decision (the client-side span of an in-process
        # request, a worker's post-hoc leg) is swallowed instead of
        # leaking an orphan into the ring the retention just cleaned
        self._dropped: "OrderedDict[str, bool]" = OrderedDict()
        self.dropped_memory = int(dropped_memory)
        self.staging_evictions = 0  # whole requests evicted un-decided
        self.span_overflows = 0     # spans dropped over the per-request cap

    def begin(self, trace_id: str) -> None:
        """Register one request for staging (idempotent per trace id)."""
        with self._lock:
            if trace_id in self._staged:
                return
            # a retry reusing a previously-dropped id starts fresh
            self._dropped.pop(trace_id, None)
            while len(self._staged) >= self.max_staged:
                self._staged.popitem(last=False)
                self.staging_evictions += 1
            self._staged[trace_id] = []

    def watching(self, trace_id: str) -> bool:
        with self._lock:
            return trace_id in self._staged

    def staged_count(self) -> int:
        with self._lock:
            return len(self._staged)

    def offer(self, span: Span) -> bool:
        """Divert a finishing span into its request's staging buffer;
        False when the trace is not staged (caller records normally)."""
        with self._lock:
            buf = self._staged.get(span.trace_id)
            if buf is None:
                # late span of a dropped request: swallow it, or the
                # decision the sampler just made would leak an orphan
                return span.trace_id in self._dropped
            if len(buf) >= self.max_spans_per_request:
                self.span_overflows += 1
                return True  # consumed (dropped): the cap is the cap
            buf.append(span)
            return True

    def finish(self, trace_id: str, *, outcome: str = "ok",
               latency_s: Optional[float] = None,
               tracer: Optional[Tracer] = None
               ) -> Tuple[Optional[str], int]:
        """Decide retention for one completed request. Returns
        ``(reason, n_spans)`` — reason None means the staged spans were
        dropped; otherwise they were recorded into ``tracer`` (default:
        the process ring) and are queryable by trace id."""
        with self._lock:
            buf = self._staged.pop(trace_id, None)
            if buf is not None:
                # tentatively dropped from the same critical section
                # that un-stages: a span closing while the policy
                # deliberates below is swallowed, never an orphan in
                # the ring for a request the decision then drops. (The
                # flip side — a kept trace losing a span from that
                # microsecond window — is benign: every load-bearing
                # leg is recorded before finish() runs by design.)
                self._dropped[trace_id] = True
                while len(self._dropped) > self.dropped_memory:
                    self._dropped.popitem(last=False)
        if buf is None:
            return None, 0
        reason = self.policy.decide(outcome=outcome, latency_s=latency_s)
        if reason is None:
            return None, len(buf)
        with self._lock:
            self._dropped.pop(trace_id, None)
        t = tracer if tracer is not None else _TRACER
        for s in buf:
            t.record(s)
        return reason, len(buf)

    def discard(self, trace_id: str) -> int:
        """Drop a staged request without a retention decision (e.g. the
        ledger evicted its record); returns the span count dropped."""
        with self._lock:
            buf = self._staged.pop(trace_id, None)
        return len(buf) if buf is not None else 0


_TAIL_SAMPLER: Optional[TailSampler] = None


def get_tail_sampler(create: bool = False) -> Optional[TailSampler]:
    """The process tail sampler routing span finishes; ``create=True``
    installs one when none exists (the request ledger does this)."""
    global _TAIL_SAMPLER
    if _TAIL_SAMPLER is None and create:
        _TAIL_SAMPLER = TailSampler()
    return _TAIL_SAMPLER


def set_tail_sampler(sampler: Optional[TailSampler]) -> None:
    global _TAIL_SAMPLER
    _TAIL_SAMPLER = sampler


def _route(span: Span, tracer: Optional[Tracer]) -> None:
    """The one span-finish funnel: an explicit ``tracer`` always wins
    (tests and collectors that own a private ring bypass staging); a
    staged trace id diverts to the tail sampler; everything else lands
    in the process ring exactly as before."""
    if tracer is not None:
        tracer.record(span)
        return
    ts = _TAIL_SAMPLER
    if ts is not None and ts.offer(span):
        return
    _TRACER.record(span)


def set_tracing_enabled(flag: bool):
    global _ENABLED
    _ENABLED = bool(flag)


def tracing_enabled() -> bool:
    return _ENABLED


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_span() -> Optional[Span]:
    st = _stack()
    return st[-1] if st else None


@contextmanager
def span(name: str, *, trace_id: Optional[str] = None,
         parent_id: Optional[str] = None, tracer: Optional[Tracer] = None,
         **attrs):
    """Open a span around a block. Nesting is thread-local: without an
    explicit ``trace_id``/``parent_id`` the current span (if any) is the
    parent and shares its trace. Yields the live Span (attrs mutable)
    or None when tracing is disabled. An exception in the block is
    recorded as an ``error`` attr and re-raised; the span always closes.
    """
    if not _ENABLED:
        yield None
        return
    parent = current_span()
    if trace_id is None:
        trace_id = parent.trace_id if parent is not None else new_id()
    if parent_id is None and parent is not None:
        parent_id = parent.span_id
    s = Span(name, trace_id=trace_id, span_id=new_id(), parent_id=parent_id,
             start=now(), thread=threading.current_thread().name,
             attrs=dict(attrs))
    _stack().append(s)
    try:
        yield s
    except BaseException as e:
        s.attrs.setdefault("error", type(e).__name__)
        raise
    finally:
        _stack().pop()
        s.end = now()
        _route(s, tracer)


def record_span(name: str, *, start: float, end: float, trace_id: str,
                parent_id: Optional[str] = None,
                span_id: Optional[str] = None, thread: Optional[str] = None,
                tracer: Optional[Tracer] = None, **attrs) -> Span:
    """Record a span with explicit timestamps (post-hoc, cross-thread).
    Returns the Span so callers can parent further spans to it."""
    s = Span(name, trace_id=trace_id,
             span_id=span_id if span_id is not None else new_id(),
             parent_id=parent_id, start=start, end=end,
             thread=(thread if thread is not None
                     else threading.current_thread().name),
             attrs=dict(attrs))
    _route(s, tracer)
    return s


# -- JSONL / Chrome-trace conversion ----------------------------------------


def load_jsonl(path: str) -> List[Span]:
    spans = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                spans.append(Span.from_json(json.loads(line)))
    return spans


def to_chrome_trace(spans: Iterable[Span], *, pid: int = 1,
                    process_name: Optional[str] = None) -> dict:
    """Chrome-trace JSON (Perfetto-loadable). One ``"X"`` complete event
    per span; ids/attrs ride in ``args`` so :func:`from_chrome_trace`
    reconstructs the exact span set (nesting included). Threads map to
    tids with ``thread_name`` metadata events. ``pid``/``process_name``
    place the whole span set on one process lane — the cluster
    federation layer stitches per-worker traces into a single document
    by giving each worker its own pid (observability/federation.py)."""
    spans = list(spans)
    tids: Dict[str, int] = {}
    for s in spans:
        tids.setdefault(s.thread or "main", len(tids) + 1)
    events: List[dict] = []
    if process_name is not None:
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": process_name}})
    events.extend({"ph": "M", "name": "thread_name", "pid": pid,
                   "tid": tid, "args": {"name": tname}}
                  for tname, tid in tids.items())
    for s in spans:
        # attrs ride in their own sub-dict: a user attr named "span_id"
        # must not clobber the identity keys the round trip depends on
        args = {"trace_id": s.trace_id, "span_id": s.span_id,
                "parent_id": s.parent_id, "attrs": dict(s.attrs)}
        events.append({
            "ph": "X", "cat": "span", "name": s.name, "pid": pid,
            "tid": tids[s.thread or "main"],
            "ts": s.start * 1e6, "dur": s.duration * 1e6, "args": args})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def from_chrome_trace(trace: dict) -> List[Span]:
    """Inverse of :func:`to_chrome_trace` for events it wrote (spans with
    ``span_id`` in args); foreign events without one — e.g. XLA ops in a
    merged profile — are skipped."""
    events = trace.get("traceEvents", [])
    # thread names are keyed per (pid, tid): a stitched multi-worker
    # document reuses tid 1 on every worker's pid lane
    tid_names = {(ev.get("pid"), ev.get("tid")):
                 ev.get("args", {}).get("name")
                 for ev in events
                 if ev.get("ph") == "M" and ev.get("name") == "thread_name"}
    spans = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = dict(ev.get("args", {}))
        if "span_id" not in args:
            continue
        start = float(ev.get("ts", 0.0)) / 1e6
        spans.append(Span(
            ev.get("name", "?"), trace_id=args.get("trace_id"),
            span_id=args.get("span_id"), parent_id=args.get("parent_id"),
            start=start, end=start + float(ev.get("dur", 0.0)) / 1e6,
            thread=tid_names.get((ev.get("pid"), ev.get("tid"))),
            attrs=dict(args.get("attrs", {}))))
    return spans


def stitch_named_lanes(lanes: Sequence[Tuple[str, Iterable[Span]]],
                       *, attr: str = "tier") -> dict:
    """One Perfetto document from several span sets, one pid lane per
    entry in order (client=0, router=1, backend=2 for a cross-tier
    request stitch). Each span is stamped ``attrs[attr] = lane name``
    so :func:`from_chrome_trace` round-trips the grouping, not just the
    spans — the federation layer's pid-lane idiom with named tiers
    instead of worker ids."""
    events: List[dict] = []
    for pid, (name, spans) in enumerate(lanes):
        stamped = []
        for s in spans:
            attrs = dict(s.attrs)
            attrs[attr] = name
            stamped.append(Span(
                s.name, trace_id=s.trace_id, span_id=s.span_id,
                parent_id=s.parent_id, start=s.start, end=s.end,
                thread=s.thread, attrs=attrs))
        events.extend(to_chrome_trace(
            stamped, pid=pid, process_name=name)["traceEvents"])
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, spans: Iterable[Span]) -> int:
    spans = list(spans)
    with open(path, "w") as fh:
        json.dump(to_chrome_trace(spans), fh)
    return len(spans)
