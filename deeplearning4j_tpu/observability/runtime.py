"""Runtime collectors: device memory, live arrays, JIT compiles,
host↔device transfers — the "what is the process doing to the chip"
gauges the serving/training instruments don't see.

Three signal sources:

- **Sampled** (``collect()``, or a background thread via ``start()``):
  per-device HBM stats from PJRT (``device.memory_stats()``, the same
  numbers utils/crash.py dumps post-mortem — here continuously) and
  live jax array count/bytes (``jax.live_arrays()``) — the host-visible
  proxy for buffer leaks and donation failures.
- **Event-driven**: XLA compilations via ``jax.monitoring``'s
  ``backend_compile_duration`` events — count + wall time per
  recompile, so a serving warmup that misses a batch bucket (every miss
  is a fresh compile on the request path) is visible in the scrape
  rather than only as a latency outlier.
- **Explicit**: :func:`record_transfer` counters the instrumented hot
  paths call with the byte counts they move (Trainer.fit's batch
  device_put, ParallelInference's per-dispatch H2D/D2H, checkpoint
  snapshot D2H).

All instruments live on the process-global default registry; one module
-level jax.monitoring listener dispatches to whichever collector is
current, so registry resets (tests, bench) never stack listeners.
jax itself is imported lazily — importing this module costs nothing.
"""

from __future__ import annotations

import threading
from typing import Optional

from deeplearning4j_tpu.observability import metrics as _metrics

# memory_stats keys worth a gauge (present on TPU PJRT; CPU returns {}).
_MEMORY_STATS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                 "largest_alloc_size")


class RuntimeCollector:
    def __init__(self, registry: Optional[_metrics.MetricsRegistry] = None):
        r = registry if registry is not None else _metrics.default_registry()
        self.registry = r
        ns = "runtime"
        self.device_memory_bytes = r.gauge(
            "device_memory_bytes",
            "Per-device PJRT memory stats (labels: device id, stat key).",
            ("device", "stat"), namespace=ns)
        self.live_arrays = r.gauge(
            "live_arrays", "Live jax arrays held by this process.",
            namespace=ns)
        self.live_array_bytes = r.gauge(
            "live_array_bytes", "Total bytes of live jax arrays.",
            namespace=ns)
        self.jit_compiles_total = r.counter(
            "jit_compiles_total",
            "XLA backend compilations observed via jax.monitoring — "
            "a rising count in steady-state serving means bucket-miss "
            "recompiles on the request path.", namespace=ns)
        self.jit_compile_seconds = r.histogram(
            "jit_compile_seconds", "Wall time per XLA backend compile.",
            buckets=_metrics.COMPILE_BUCKETS, namespace=ns)
        self.transfers_total = r.counter(
            "transfers_total", "Host<->device transfers recorded by "
            "instrumented paths (direction: h2d | d2h).",
            ("direction",), namespace=ns)
        self.transfer_bytes_total = r.counter(
            "transfer_bytes_total",
            "Bytes moved host<->device by instrumented paths.",
            ("direction",), namespace=ns)
        self.collections_total = r.counter(
            "collections_total", "collect() sampling passes.", namespace=ns)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- event-driven --------------------------------------------------------

    def on_compile(self, duration_s: float):
        self.jit_compiles_total.inc()
        self.jit_compile_seconds.observe(float(duration_s))

    def record_transfer(self, direction: str, nbytes: int):
        if direction not in ("h2d", "d2h"):
            raise ValueError(f"direction must be h2d|d2h, got {direction!r}")
        self.transfers_total.inc(direction=direction)
        self.transfer_bytes_total.inc(float(nbytes), direction=direction)

    # -- sampled -------------------------------------------------------------

    def collect(self):
        """One sampling pass (never raises: a backend that exposes no
        memory stats just leaves those gauges untouched). No-op while
        ``metrics.set_enabled(False)`` — the kill switch must silence a
        running sampling thread like every other instrumented path."""
        if not _metrics.enabled():
            return
        import jax

        try:
            arrs = jax.live_arrays()
            self.live_arrays.set(len(arrs))
            self.live_array_bytes.set(
                sum(getattr(a, "nbytes", 0) or 0 for a in arrs))
        except Exception:  # noqa: BLE001 - deleted-buffer races, odd backends
            pass
        try:
            for d in jax.devices():
                stats = d.memory_stats() or {}
                for key in _MEMORY_STATS:
                    v = stats.get(key)
                    if isinstance(v, (int, float)):
                        self.device_memory_bytes.set(
                            float(v), device=str(d.id), stat=key)
        except Exception:  # noqa: BLE001 - backend-dependent
            pass
        self.collections_total.inc()

    def start(self, interval_s: float = 10.0) -> "RuntimeCollector":
        """Sample periodically on a daemon thread until ``stop()``."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                self.collect()

        self._thread = threading.Thread(
            target=loop, daemon=True, name="runtime-collector")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# -- module singleton + the one jax.monitoring listener ----------------------

_collector: Optional[RuntimeCollector] = None
_collector_lock = threading.Lock()
_listener_installed = False


def _dispatch_event(event: str, duration: float, **kw):
    c = _collector
    if (c is not None and _metrics.enabled()
            and event.endswith("backend_compile_duration")):
        try:
            c.on_compile(duration)
        except Exception:  # noqa: BLE001 - telemetry never breaks compiles
            pass


def _install_listener():
    """Register the module-level listener once per process. jax has no
    unregister, so the listener is a fixed dispatcher that forwards to
    the CURRENT collector — registry resets swap the target, never
    stack callbacks."""
    global _listener_installed
    if _listener_installed:
        return
    try:
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(_dispatch_event)
        _listener_installed = True
    except Exception:  # noqa: BLE001 - older jax without the API
        pass


def get_runtime_collector() -> RuntimeCollector:
    """The process collector on the default registry (created lazily,
    compile listener installed on first use)."""
    global _collector
    with _collector_lock:
        if _collector is None:
            _collector = RuntimeCollector()
            _install_listener()
    return _collector


def record_transfer(direction: str, nbytes: int):
    """Hot-path hook: count a host<->device transfer. No-op when
    instrumentation is disabled; never raises."""
    if not _metrics.enabled():
        return
    try:
        get_runtime_collector().record_transfer(direction, int(nbytes))
    except Exception:  # noqa: BLE001 - telemetry never fails the caller
        pass


def _reset():
    global _collector
    with _collector_lock:
        if _collector is not None:
            _collector.stop()
        _collector = None


_metrics.register_reset_hook(_reset)
