"""Bounded in-process multi-resolution time-series store (mini-TSDB).

Every other observability surface answers "what is true *now*":
``/metrics`` is one scrape, burn rates are windowed deltas over private
deques, the flight ring evicts. This module gives the telemetry spine a
**time axis**: a background sampler snapshots selected metric families
from the live registries into fixed-size rings at tiered resolutions
(1 s x 10 min, 10 s x 2 h, 60 s x 24 h by default), so "what did queue
depth do over the last hour" and "what is the request rate trend" are
answerable in-process, with no external TSDB.

Design points:

- **Bounded everywhere**: rings are fixed-capacity per tier, the series
  count is capped (``DL4J_TPU_TSDB_MAX_SERIES``; overflow series are
  dropped and counted, never grown), and a point is a small list — the
  store's memory is a static function of its configuration.
- **Multi-resolution downsampling**: every sample lands in the finest
  tier; a coarser tier keeps one point per ``step_s`` bucket (the last
  value wins — correct for cumulative counters — with the bucket max
  retained for gauges, so ``max_over_time`` does not lose spikes).
- **Counters stay cumulative** at rest; :meth:`TimeSeriesStore.rate`
  converts to per-second rates at query time with counter-reset
  detection (a restart's drop-to-zero reads as ``delta = new_value``,
  not a huge negative rate).
- **Histograms** keep (count, sum, cumulative bucket counts) per point,
  so :meth:`TimeSeriesStore.quantile_over_time` answers "p99 over the
  last 10 minutes" from bucket deltas — the same math the SLO engine
  runs, but over history.
- **Snapshot/restore is atomic**: :meth:`snapshot` is one JSON document
  built under the lock; :meth:`restore` builds fresh state and swaps it
  in, so history survives the warm-restart path alongside the warmup
  manifest and compile cache.
- **Collectors** let non-registry sources (the usage meter's per-tenant
  accounts, the capacity evaluator's headroom gauges) roll up into the
  same store on the sampler cadence via :meth:`ingest`.
- The SLO engine's burn-rate windows deduplicate onto this store:
  :meth:`slo_series` hands the engine a store-owned cumulative ring
  (same deque semantics as its historical private one, included in
  snapshot/restore) instead of each rule keeping parallel history.

Served at ``GET /debug/timeseries?family=&window=&step=`` on
ModelServer and federated at ``GET /cluster/debug/timeseries`` (worker
series merged under worker/generation labels). Stdlib only; safe to
import from any layer.
"""

from __future__ import annotations

import dataclasses
import math
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.observability import metrics as _metrics
from deeplearning4j_tpu.observability.flightrecorder import record_event

ENV_TSDB_TIERS = "DL4J_TPU_TSDB_TIERS"
ENV_TSDB_MAX_SERIES = "DL4J_TPU_TSDB_MAX_SERIES"
ENV_TSDB_INTERVAL_S = "DL4J_TPU_TSDB_INTERVAL_S"

SNAPSHOT_VERSION = 1


@dataclasses.dataclass(frozen=True)
class Tier:
    """One retention tier: a ring of ``capacity`` points at ``step_s``
    resolution (coverage = ``step_s * capacity`` seconds)."""

    step_s: float
    capacity: int

    @property
    def coverage_s(self) -> float:
        return self.step_s * self.capacity

    def to_json(self) -> dict:
        return {"step_s": self.step_s, "capacity": self.capacity}


# 1 s x 10 min / 10 s x 2 h / 60 s x 24 h — ~2.8k points per series.
DEFAULT_TIERS: Tuple[Tier, ...] = (
    Tier(1.0, 600), Tier(10.0, 720), Tier(60.0, 1440))


def resolve_tiers(spec: Optional[str] = None) -> Tuple[Tier, ...]:
    """Parse a ``"1x600,10x720,60x1440"`` tier spec (the
    ``DL4J_TPU_TSDB_TIERS`` knob format); malformed specs fall back to
    the defaults — a bad env var must not kill the process."""
    if spec is None:
        spec = os.environ.get(ENV_TSDB_TIERS) or ""
    spec = spec.strip()
    if not spec:
        return DEFAULT_TIERS
    try:
        tiers = []
        for part in spec.split(","):
            step, _, cap = part.strip().partition("x")
            tier = Tier(float(step), int(cap))
            if tier.step_s <= 0 or tier.capacity < 1:
                raise ValueError(part)
            tiers.append(tier)
        tiers.sort(key=lambda t: t.step_s)
        return tuple(tiers) if tiers else DEFAULT_TIERS
    except (ValueError, TypeError):
        return DEFAULT_TIERS


class TsdbMetrics:
    """The store's own exposition (on the process default registry):
    the sampler is observable like every other background plane."""

    def __init__(self, registry: Optional[_metrics.MetricsRegistry] = None):
        r = registry if registry is not None else _metrics.default_registry()
        self.registry = r
        ns = "tsdb"
        self.samples_total = r.counter(
            "samples_total", "Sampler passes completed (registry scrape "
            "+ collector roll-up into the ring tiers).", namespace=ns)
        self.sample_errors_total = r.counter(
            "sample_errors_total", "Sampler passes (or individual "
            "collectors) that raised and were swallowed — history "
            "capture never fails the process.", namespace=ns)
        self.series = r.gauge(
            "series", "Live series (family x label-set) currently held "
            "in the ring tiers.", namespace=ns)
        self.points = r.gauge(
            "points", "Points currently retained across all series and "
            "tiers (the store's memory bound in sample units).",
            namespace=ns)
        self.series_dropped_total = r.counter(
            "series_dropped_total", "New series rejected by the "
            "max-series cardinality bound (existing series keep "
            "sampling; the overflow is counted, never grown).",
            namespace=ns)
        self.restores_total = r.counter(
            "restores_total", "Snapshot restores applied (the "
            "warm-restart path carrying history across a process "
            "swap).", namespace=ns)


_tsdb_metrics: Optional[TsdbMetrics] = None
_tm_lock = threading.Lock()


def get_tsdb_metrics() -> TsdbMetrics:
    global _tsdb_metrics
    if _tsdb_metrics is None:
        with _tm_lock:
            if _tsdb_metrics is None:
                _tsdb_metrics = TsdbMetrics()
    return _tsdb_metrics


def _drop_tsdb_metrics():
    global _tsdb_metrics
    _tsdb_metrics = None


_metrics.register_reset_hook(_drop_tsdb_metrics)


def _tsdb_metrics_or_none() -> Optional[TsdbMetrics]:
    try:
        if not _metrics.enabled():
            return None
        return get_tsdb_metrics()
    except Exception:  # noqa: BLE001 — metrics never fail the store
        return None


# -- sampling kill switch (the bench overhead gate prices against it) ---------

_SAMPLING_ENABLED = True


def set_sampling_enabled(flag: bool) -> None:
    """Kill switch for the sampler/ingest hot path (``bench.py
    timeseries`` prices the plane against this)."""
    global _SAMPLING_ENABLED
    _SAMPLING_ENABLED = bool(flag)


def sampling_enabled() -> bool:
    return _SAMPLING_ENABLED


# -- series storage -----------------------------------------------------------


def _parse_bound(key: str) -> float:
    return float("inf") if key == "+Inf" else float(key)


class _Series:
    """One (family, label-set) series: a ring per tier.

    Scalar points are ``[t, value, vmax]`` (``vmax`` = max raw sample
    folded into the point's bucket); histogram points are
    ``[t, count, sum, [cum_0, ..., cum_n]]`` with the bucket bounds
    held once at series level. Lists, not tuples: points serialize to
    the snapshot document as-is.
    """

    __slots__ = ("kind", "bounds", "rings")

    def __init__(self, kind: str, tiers: Sequence[Tier],
                 bounds: Optional[List[float]] = None):
        self.kind = kind
        self.bounds = bounds            # histogram bucket bounds, sorted
        self.rings: List[deque] = [deque(maxlen=t.capacity) for t in tiers]

    def add_scalar(self, t: float, value: float, tiers: Sequence[Tier]):
        for ring, tier in zip(self.rings, tiers):
            if not ring or t >= ring[-1][0] + tier.step_s:
                ring.append([t, value, value])
            else:
                last = ring[-1]
                last[1] = value
                last[2] = max(last[2], value)

    def add_hist(self, t: float, count: float, total: float,
                 cum: List[float], tiers: Sequence[Tier]):
        for ring, tier in zip(self.rings, tiers):
            if not ring or t >= ring[-1][0] + tier.step_s:
                ring.append([t, count, total, cum])
            else:
                last = ring[-1]
                last[1], last[2], last[3] = count, total, cum

    def n_points(self) -> int:
        return sum(len(r) for r in self.rings)


def _labels_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))


def _labels_match(key: Tuple[Tuple[str, str], ...],
                  want: Optional[Dict[str, str]]) -> bool:
    if not want:
        return True
    have = dict(key)
    return all(have.get(str(k)) == str(v) for k, v in want.items())


class TimeSeriesStore:
    """The in-process mini-TSDB: sampler + ring tiers + query API.

    ``registries``: the metric registries the sampler scrapes (None =
    the live process default registry, resolved per pass so registry
    resets in tests are honored). ``families``: an allow-list of family
    names to retain (None = everything exposed, up to ``max_series``).
    ``clock`` is wall time by default — snapshots cross process
    restarts, so points are wall-anchored.
    """

    def __init__(self, registries: Optional[Sequence] = None, *,
                 tiers: Optional[Sequence[Tier]] = None,
                 interval_s: Optional[float] = None,
                 families: Optional[Sequence[str]] = None,
                 max_series: Optional[int] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.tiers: Tuple[Tier, ...] = (tuple(tiers) if tiers is not None
                                        else resolve_tiers())
        if interval_s is None:
            try:
                interval_s = float(
                    os.environ.get(ENV_TSDB_INTERVAL_S) or
                    self.tiers[0].step_s)
            except ValueError:
                interval_s = self.tiers[0].step_s
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        if max_series is None:
            try:
                max_series = int(
                    os.environ.get(ENV_TSDB_MAX_SERIES) or 512)
            except ValueError:
                max_series = 512
        if max_series < 1:
            raise ValueError(f"max_series must be >= 1, got {max_series}")
        self.interval_s = float(interval_s)
        self.max_series = int(max_series)
        self.families_filter = frozenset(families) if families else None
        self._registries = list(registries) if registries is not None else None
        self._clock = clock if clock is not None else time.time
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                           _Series] = {}
        self._slo_series: Dict[str, deque] = {}
        self._collectors: List[dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_sample: Optional[float] = None
        self._samples = 0

    # -- wiring ---------------------------------------------------------------

    def _resolve_registries(self):
        if self._registries is not None:
            return self._registries
        return [_metrics.default_registry()]

    def add_collector(self, fn: Callable[[float], Sequence[tuple]], *,
                      every_s: Optional[float] = None) -> None:
        """Register ``fn(now) -> [(family, labels, kind, value), ...]``
        to roll external cumulative series (usage accounts, capacity
        gauges) into the store. Runs on the sampler cadence, throttled
        to ``every_s`` when given; a raising collector is counted and
        skipped, never fatal."""
        self._collectors.append(
            {"fn": fn, "every_s": every_s, "last": None})

    def slo_series(self, name: str, maxlen: int) -> deque:
        """The SLO engine's cumulative ``(t, bad, total)`` ring for one
        rule, owned by the store (and therefore snapshot/restored with
        it). Same deque semantics the engine historically kept
        privately — handing it out here is the dedup, not a behavior
        change. Re-requesting with a different ``maxlen`` re-caps while
        preserving the retained tail."""
        with self._lock:
            d = self._slo_series.get(name)
            if d is None or d.maxlen != maxlen:
                d = deque(list(d or ()), maxlen=max(1, int(maxlen)))
                self._slo_series[name] = d
            return d

    # -- sampling -------------------------------------------------------------

    def sample(self, now: Optional[float] = None) -> int:
        """One sampler pass: scrape the registries' JSON document into
        the ring tiers, then run due collectors. Returns the number of
        series touched. Never raises."""
        if not _SAMPLING_ENABLED:
            return 0
        t = self._clock() if now is None else now
        tm = _tsdb_metrics_or_none()
        touched = 0
        try:
            doc = _metrics.render_json_multi(self._resolve_registries())
            with self._lock:
                for fam in doc.get("metrics", []):
                    name = fam.get("name")
                    if self.families_filter is not None \
                            and name not in self.families_filter:
                        continue
                    kind = fam.get("type")
                    for s in fam.get("samples", []):
                        if self._ingest_locked(name, s.get("labels") or {},
                                               kind, s, t):
                            touched += 1
                self._last_sample = t
                self._samples += 1
        except Exception:  # noqa: BLE001 — history capture never fails
            if tm is not None:
                tm.sample_errors_total.inc()
            return touched
        for col in self._collectors:
            if col["every_s"] is not None and col["last"] is not None \
                    and t - col["last"] < col["every_s"]:
                continue
            col["last"] = t
            try:
                points = col["fn"](t) or ()
                with self._lock:
                    for family, labels, kind, value in points:
                        self._ingest_locked(
                            family, labels or {}, kind,
                            {"value": float(value)}, t)
            except Exception:  # noqa: BLE001 — a bad collector is skipped
                if tm is not None:
                    tm.sample_errors_total.inc()
        if tm is not None:
            tm.samples_total.inc()
            with self._lock:
                tm.series.set(len(self._series))
                tm.points.set(sum(s.n_points()
                                  for s in self._series.values()))
        return touched

    def ingest(self, family: str, labels: Dict[str, str], kind: str,
               value, now: Optional[float] = None) -> None:
        """Write one external point (``kind`` of ``counter`` / ``gauge``
        expects a float ``value``) — the collector path, callable
        directly in tests."""
        if not _SAMPLING_ENABLED:
            return
        t = self._clock() if now is None else now
        with self._lock:
            self._ingest_locked(family, labels or {}, kind,
                                {"value": float(value)}, t)

    def _ingest_locked(self, family: str, labels: Dict[str, str],
                       kind: str, sample: dict, t: float) -> bool:
        key = (family, _labels_key(labels))
        series = self._series.get(key)
        if series is None:
            if len(self._series) >= self.max_series:
                tm = _tsdb_metrics_or_none()
                if tm is not None:
                    tm.series_dropped_total.inc()
                return False
            bounds = None
            if kind == "histogram":
                bounds = sorted(_parse_bound(k)
                                for k in sample.get("buckets", {}))
            series = _Series(kind or "gauge", self.tiers, bounds)
            self._series[key] = series
        if kind == "histogram":
            buckets = sample.get("buckets", {})
            bounds = sorted(_parse_bound(k) for k in buckets)
            if series.bounds != bounds:
                # bucket layout changed (re-registered family): restart
                # the series rather than mixing incomparable points
                series.bounds = bounds
                for ring in series.rings:
                    ring.clear()
            cum = [float(buckets[("+Inf" if b == float("inf")
                                  else _metrics._fmt(b))])
                   for b in bounds]
            series.add_hist(t, float(sample.get("count", 0.0)),
                            float(sample.get("sum", 0.0)), cum, self.tiers)
        else:
            series.add_scalar(t, float(sample.get("value", 0.0)),
                              self.tiers)
        return True

    # -- background thread ----------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "TimeSeriesStore":
        if self.running:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="tsdb-sampler")
        self._thread.start()
        record_event("tsdb.start", interval_s=self.interval_s,
                     tiers=[t.to_json() for t in self.tiers])
        return self

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            self.sample()

    def stop(self):
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5)
        self._thread = None
        record_event("tsdb.stop", samples=self._samples)

    # -- query API ------------------------------------------------------------

    def _tier_index(self, window_s: float,
                    step_s: Optional[float] = None) -> int:
        """The finest tier that both covers ``window_s`` and (when
        given) has step >= the requested ``step_s``; falls back to the
        coarsest tier when nothing covers the window."""
        for i, tier in enumerate(self.tiers):
            if step_s is not None and tier.step_s < step_s * (1 - 1e-9):
                continue
            if tier.coverage_s >= window_s:
                return i
        return len(self.tiers) - 1

    def _select(self, family: str, labels: Optional[Dict[str, str]]):
        return [(dict(key[1]), s) for key, s in self._series.items()
                if key[0] == family and _labels_match(key[1], labels)]

    def range(self, family: str, *, window_s: float,
              step_s: Optional[float] = None,
              labels: Optional[Dict[str, str]] = None,
              now: Optional[float] = None) -> dict:
        """Raw points per matching series over the trailing window, at
        the tier resolution chosen for (window, step)."""
        t = self._clock() if now is None else now
        idx = self._tier_index(window_s, step_s)
        cutoff = t - float(window_s)
        out = []
        kind = None
        with self._lock:
            for lbls, series in self._select(family, labels):
                kind = kind or series.kind
                ring = series.rings[idx]
                if series.kind == "histogram":
                    pts = [[p[0], p[1]] for p in ring if p[0] >= cutoff]
                else:
                    pts = [[p[0], p[1]] for p in ring if p[0] >= cutoff]
                out.append({"labels": lbls, "points": pts})
        return {"family": family, "kind": kind,
                "window_s": float(window_s),
                "step_s": self.tiers[idx].step_s, "series": out}

    def rate(self, family: str, *, window_s: float,
             step_s: Optional[float] = None,
             labels: Optional[Dict[str, str]] = None,
             now: Optional[float] = None) -> dict:
        """Counter -> per-second rate series with reset detection: a
        drop in the cumulative value reads as a restart, contributing
        ``new_value`` (the counter restarted from zero), never a
        negative rate. Histogram series rate over their observation
        counts. The top-level ``rate`` sums the per-series window
        rates — offered load for a family like
        ``serving_requests_total``."""
        t = self._clock() if now is None else now
        idx = self._tier_index(window_s, step_s)
        cutoff = t - float(window_s)
        out = []
        total_rate = 0.0
        with self._lock:
            for lbls, series in self._select(family, labels):
                ring = series.rings[idx]
                pts = [p for p in ring if p[0] >= cutoff]
                rate_pts = []
                win_delta = 0.0
                for prev, cur in zip(pts, pts[1:]):
                    dv = cur[1] - prev[1]
                    if dv < 0:            # counter reset
                        dv = cur[1]
                    dt = cur[0] - prev[0]
                    if dt > 0:
                        rate_pts.append([cur[0], dv / dt])
                    win_delta += max(0.0, dv)
                span = pts[-1][0] - pts[0][0] if len(pts) >= 2 else 0.0
                series_rate = win_delta / span if span > 0 else 0.0
                total_rate += series_rate
                out.append({"labels": lbls, "points": rate_pts,
                            "rate": series_rate})
        return {"family": family, "window_s": float(window_s),
                "step_s": self.tiers[idx].step_s, "rate": total_rate,
                "series": out}

    def quantile_over_time(self, family: str, q: float, *,
                           window_s: float,
                           labels: Optional[Dict[str, str]] = None,
                           now: Optional[float] = None) -> dict:
        """The q-quantile of a histogram family's observations that
        landed inside the trailing window, from cumulative-bucket
        deltas with linear interpolation inside the chosen bucket (the
        Prometheus ``histogram_quantile`` recipe, over history). A
        counter reset inside the window degrades to the latest absolute
        counts."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        t = self._clock() if now is None else now
        idx = self._tier_index(window_s)
        cutoff = t - float(window_s)
        agg: Optional[List[float]] = None
        bounds: Optional[List[float]] = None
        count = 0.0
        with self._lock:
            for _lbls, series in self._select(family, labels):
                if series.kind != "histogram" or series.bounds is None:
                    continue
                ring = series.rings[idx]
                pts = [p for p in ring if p[0] >= cutoff]
                if not pts:
                    continue
                first, last = pts[0], pts[-1]
                dc = [c1 - c0 for c0, c1 in zip(first[3], last[3])]
                if any(d < -1e-9 for d in dc):
                    dc = list(last[3])     # reset inside the window
                if bounds is None:
                    bounds = list(series.bounds)
                    agg = [0.0] * len(bounds)
                if list(series.bounds) != bounds:
                    continue               # incomparable bucket layout
                for i, d in enumerate(dc):
                    agg[i] += max(0.0, d)
                count += max(0.0, last[1] - first[1])
        if not agg or agg[-1] <= 0:
            return {"family": family, "q": q, "window_s": float(window_s),
                    "count": 0.0, "value": None}
        total = agg[-1]
        target = q * total
        value = None
        for i, cum in enumerate(agg):
            if cum >= target:
                if math.isinf(bounds[i]):
                    # observations beyond the largest finite bound:
                    # report that bound (the honest floor)
                    value = bounds[i - 1] if i > 0 else 0.0
                    break
                lo = bounds[i - 1] if i > 0 else 0.0
                prev = agg[i - 1] if i > 0 else 0.0
                width = cum - prev
                frac = (target - prev) / width if width > 0 else 1.0
                value = lo + frac * (bounds[i] - lo)
                break
        return {"family": family, "q": q, "window_s": float(window_s),
                "count": count, "value": value}

    def max_over_time(self, family: str, *, window_s: float,
                      labels: Optional[Dict[str, str]] = None,
                      now: Optional[float] = None) -> dict:
        """The max raw sample folded into any point of the window
        (downsampling keeps per-bucket maxima, so a coarser tier does
        not lose gauge spikes)."""
        t = self._clock() if now is None else now
        idx = self._tier_index(window_s)
        cutoff = t - float(window_s)
        best = None
        per_series = []
        with self._lock:
            for lbls, series in self._select(family, labels):
                if series.kind == "histogram":
                    continue
                ring = series.rings[idx]
                vals = [p[2] for p in ring if p[0] >= cutoff]
                if not vals:
                    continue
                m = max(vals)
                per_series.append({"labels": lbls, "max": m})
                best = m if best is None else max(best, m)
        return {"family": family, "window_s": float(window_s),
                "value": best, "series": per_series}

    def families(self) -> List[str]:
        with self._lock:
            return sorted({key[0] for key in self._series})

    def describe(self) -> dict:
        with self._lock:
            n_points = sum(s.n_points() for s in self._series.values())
            return {
                "tiers": [t.to_json() for t in self.tiers],
                "interval_s": self.interval_s,
                "max_series": self.max_series,
                "series": len(self._series),
                "points": n_points,
                "samples": self._samples,
                "last_sample": self._last_sample,
                "running": self.running,
                "families": sorted({key[0] for key in self._series}),
            }

    def debug_query(self, *, family=None, window_s=None, step_s=None,
                    op: str = "range", q=None,
                    labels: Optional[Dict[str, str]] = None) -> dict:
        """One ``/debug/timeseries`` query against the store — the
        shared dispatch behind the backend's AND the router's endpoint
        (one grammar at every vantage: no ``family`` → ``describe()``;
        ``op`` = range | rate | quantile | max, ``quantile`` reads
        ``q``). Raises ValueError on an unknown op — the HTTP layer
        owns the status code, the store owns the grammar."""
        if family is None:
            return self.describe()
        window = float(window_s) if window_s is not None else 600.0
        if op == "rate":
            return self.rate(family, window_s=window, step_s=step_s,
                             labels=labels)
        if op == "quantile":
            return self.quantile_over_time(
                family, float(q if q is not None else 0.99),
                window_s=window, labels=labels)
        if op == "max":
            return self.max_over_time(family, window_s=window,
                                      labels=labels)
        if op == "range":
            return self.range(family, window_s=window, step_s=step_s,
                              labels=labels)
        raise ValueError(
            f"op must be range|rate|quantile|max, got {op!r}")

    # -- snapshot / restore ---------------------------------------------------

    def snapshot(self) -> dict:
        """One atomic JSON document of every ring (and the SLO engine's
        store-owned windows) — what the telemetry exporter snapshot and
        the warm-restart path carry."""
        with self._lock:
            series = []
            for (family, lkey), s in self._series.items():
                series.append({
                    "family": family,
                    "labels": dict(lkey),
                    "kind": s.kind,
                    "bounds": (["+Inf" if math.isinf(b) else b
                                for b in s.bounds]
                               if s.bounds is not None else None),
                    "rings": [[list(p) for p in ring]
                              for ring in s.rings],
                })
            return {
                "version": SNAPSHOT_VERSION,
                "time": self._clock(),
                "tiers": [t.to_json() for t in self.tiers],
                "samples": self._samples,
                "series": series,
                "slo": {name: [list(p) for p in d]
                        for name, d in self._slo_series.items()},
            }

    def restore(self, doc: dict) -> bool:
        """Atomically replace the store's state from a snapshot
        document (tier layouts must match point-for-point restore; a
        mismatched snapshot re-buckets through the normal downsampling
        path). Returns False on an unusable document — restore is
        best-effort, never fatal."""
        try:
            if not isinstance(doc, dict) or \
                    int(doc.get("version", -1)) != SNAPSHOT_VERSION:
                return False
            same_tiers = [Tier(float(t["step_s"]), int(t["capacity"]))
                          for t in doc.get("tiers", [])] == list(self.tiers)
            new_series: Dict = {}
            for sd in doc.get("series", []):
                family = sd["family"]
                lkey = _labels_key(sd.get("labels") or {})
                kind = sd.get("kind") or "gauge"
                bounds = sd.get("bounds")
                if bounds is not None:
                    bounds = sorted(_parse_bound(str(b)) for b in bounds)
                series = _Series(kind, self.tiers, bounds)
                rings = sd.get("rings") or []
                if same_tiers:
                    for ring, pts in zip(series.rings, rings):
                        for p in pts:
                            ring.append(list(p))
                else:
                    # replay the finest preserved ring through the
                    # store's own downsampling
                    for pts in rings[:1]:
                        for p in pts:
                            if kind == "histogram":
                                series.add_hist(p[0], p[1], p[2],
                                                list(p[3]), self.tiers)
                            else:
                                series.add_scalar(p[0], p[1], self.tiers)
                if len(new_series) < self.max_series:
                    new_series[(family, lkey)] = series
            new_slo = {}
            for name, pts in (doc.get("slo") or {}).items():
                old = self._slo_series.get(name)
                maxlen = old.maxlen if old is not None else max(
                    16, len(pts))
                d = deque(maxlen=maxlen)
                for p in pts:
                    d.append(tuple(p))
                new_slo[name] = d
            with self._lock:
                self._series = new_series
                # re-cap restored SLO windows onto any deques already
                # handed to a live engine: the engine keeps its object,
                # so refill in place rather than swapping the dict
                for name, d in new_slo.items():
                    live = self._slo_series.get(name)
                    if live is not None:
                        live.clear()
                        live.extend(d)
                    else:
                        self._slo_series[name] = d
        except Exception:  # noqa: BLE001 — a bad snapshot restores nothing
            return False
        tm = _tsdb_metrics_or_none()
        if tm is not None:
            tm.restores_total.inc()
        record_event("tsdb.restore", series=len(self._series))
        return True


# -- process-global store (federation snapshot + zero-config consumers) -------

_STORE: Optional[TimeSeriesStore] = None
_store_lock = threading.Lock()


def set_timeseries_store(store: Optional[TimeSeriesStore]) -> None:
    """Publish a store as the process default (ModelServer does on
    start) so the federation snapshot and zero-config consumers can
    read history without plumbing."""
    global _STORE
    with _store_lock:
        _STORE = store


def get_timeseries_store() -> Optional[TimeSeriesStore]:
    return _STORE


def timeseries_index() -> Optional[dict]:
    """This process's store snapshot, or None — what the federation
    snapshot embeds (never creates a store as a side effect, never
    raises)."""
    store = get_timeseries_store()
    if store is None:
        return None
    try:
        return store.snapshot()
    except Exception:  # noqa: BLE001 — telemetry never fails the caller
        return None


def store_from_snapshot(doc: dict) -> Optional[TimeSeriesStore]:
    """Rebuild a queryable store from a snapshot document (the
    aggregator answers fleet history queries against these). None when
    the document is unusable."""
    try:
        tiers = tuple(Tier(float(t["step_s"]), int(t["capacity"]))
                      for t in doc.get("tiers", [])) or None
    except (TypeError, ValueError, KeyError):
        tiers = None
    store = TimeSeriesStore(registries=[], tiers=tiers,
                            interval_s=1.0, max_series=4096)
    return store if store.restore(doc) else None


__all__ = [
    "DEFAULT_TIERS",
    "ENV_TSDB_INTERVAL_S",
    "ENV_TSDB_MAX_SERIES",
    "ENV_TSDB_TIERS",
    "SNAPSHOT_VERSION",
    "Tier",
    "TimeSeriesStore",
    "TsdbMetrics",
    "get_timeseries_store",
    "get_tsdb_metrics",
    "resolve_tiers",
    "sampling_enabled",
    "set_sampling_enabled",
    "set_timeseries_store",
    "store_from_snapshot",
    "timeseries_index",
]
