"""Performance anomaly sentinel: rolling baselines → detectors → incidents.

The SLO engine (slo.py) answers "are we breaking our promises?" against
*declared* objectives; nothing answers "did behavior change?" when no
objective exists — a step that quietly got 40% slower, a p99 that
doubled but still clears the rule, a recompile storm, HBM creeping
toward OOM. This module is the change detector:

- **probes** extract one scalar sample per evaluator tick from the live
  metric registries (counter rates, histogram mean/quantile deltas,
  gauge values) — deltas, not cumulative values, so a long-lived
  process's history can't dilute a fresh regression;
- a **rolling baseline** per detector (windowed median + MAD over the
  accepted samples — robust statistics, so the baseline itself ignores
  outliers) turns each sample into a robust z-score;
- an **ok → suspect → firing** state machine with hysteresis: one
  anomalous sample makes a detector *suspect* (and arms the host stack
  sampler's high-rate window), only ``fire_after`` consecutive
  anomalous samples make it *fire*, and only ``clear_after`` clean
  samples close it — a single jittery tick can neither page nor flap.
  While suspect/firing the baseline is FROZEN, so the anomaly can't
  teach itself into the baseline and self-resolve;
- on firing the sentinel opens an **incident bundle** (incidents.py):
  detector verdict + registry scrape + flight dump + span slice + host
  flames + (hook-provided) device profile, atomically on disk — the
  first capture happens DURING the anomaly, not after a human notices.

Built-in detectors (:func:`default_detectors`): train step-time
regression, serving p99 regression, generation TTFT regression,
recompile storm, admission queue buildup, data starvation,
live-array-bytes / HBM monotonic growth (leak heuristic).

Everything is scrapeable: ``anomaly_state{detector=}`` /
``anomaly_score{detector=}`` gauges, ``anomaly_transitions_total``,
``sentinel_ticks_total`` + ``anomaly_firing_ticks_total`` (the
``anomaly-firing`` burn-rate rule's total/bad pair), and
``incident_bundles_total{detector=}`` from the incident pipeline.

The evaluator follows slo.py's :class:`HealthEngine` pattern: a
background daemon thread, ``tick()`` callable on demand under one lock,
registries resolved per tick, injectable clock for deterministic tests.

The probe/baseline machinery is a reuse surface, not just this engine's
internals: ``serving/overload.py``'s AIMD concurrency controller feeds
a :class:`HistogramQuantileProbe` (serving p99) into a
:class:`RollingBaseline` with the same frozen-while-degraded discipline
to decide when the effective admission limit must shrink.
"""

from __future__ import annotations

import statistics
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.observability import metrics as _metrics
from deeplearning4j_tpu.observability.flightrecorder import record_event
from deeplearning4j_tpu.observability.slo import (
    _doc_map,
    _parse_bound,
)

STATE_OK = "ok"
STATE_SUSPECT = "suspect"
STATE_FIRING = "firing"
_STATE_NUM = {STATE_OK: 0, STATE_SUSPECT: 1, STATE_FIRING: 2}

# robust-z scale: MAD * 1.4826 estimates sigma for normal data
_MAD_SIGMA = 1.4826


# -- probes: families doc -> one scalar sample per tick -----------------------


class Probe:
    """One stateful sample extractor. ``sample(families)`` returns the
    tick's scalar or None when this tick carries no information for the
    detector (no new observations, counter reset, family absent)."""

    def sample(self, families: Dict[str, dict],
               t: Optional[float] = None) -> Optional[float]:
        """``t`` is the tick's clock reading (the Sentinel's injectable
        clock) so rate probes stay deterministic under a test clock;
        None falls back to ``time.monotonic()``."""
        raise NotImplementedError

    def describe(self) -> dict:
        return {"probe": type(self).__name__}


def _match(labels: dict, match: Tuple[Tuple[str, str], ...]) -> bool:
    return all(str(labels.get(k, "")) == v for k, v in match)


class CounterRateProbe(Probe):
    """delta(counter) / delta(t) in events/second over the tick; a
    negative delta (process restart / registry reset) re-anchors and
    yields None for that tick. While the family is absent (or has no
    matching series yet — lazily-registered counters appear at first
    use), the tick carries no information and the anchor is dropped, so
    the first appearance re-anchors instead of reading the whole
    cumulative count as one tick's delta (a spurious rate spike that
    would flip a ceiling detector to suspect)."""

    def __init__(self, metric: str, match: Dict[str, str] = ()):
        self.metric = metric
        self.match = tuple(sorted(dict(match or {}).items()))
        self._prev: Optional[Tuple[float, float]] = None  # (t, value)

    def _value(self, families) -> Optional[float]:
        fam = families.get(self.metric)
        if fam is None or fam.get("type") not in ("counter", "gauge"):
            return None
        vals = [s["value"] for s in fam.get("samples", [])
                if _match(s.get("labels", {}), self.match)]
        if not vals:
            return None
        return float(sum(vals))

    def sample(self, families, t=None) -> Optional[float]:
        if t is None:
            t = time.monotonic()
        v = self._value(families)
        if v is None:
            self._prev = None  # family absent: re-anchor on appearance
            return None
        prev, self._prev = self._prev, (t, v)
        if prev is None:
            return None
        dt = t - prev[0]
        dv = v - prev[1]
        if dt <= 0 or dv < 0:
            return None
        return dv / dt

    def describe(self) -> dict:
        return {"probe": "counter_rate", "metric": self.metric,
                "unit": "events/s"}


class _HistDeltaProbe(Probe):
    """Shared delta machinery over one histogram family: per tick the
    probe sees (bucket-count deltas, sum delta, count delta) summed over
    matching label sets."""

    def __init__(self, metric: str, match: Dict[str, str] = (),
                 min_count: int = 1):
        self.metric = metric
        self.match = tuple(sorted(dict(match or {}).items()))
        self.min_count = int(min_count)
        self._prev: Optional[Tuple[Dict[float, float], float, float]] = None

    def _cum(self, families):
        fam = families.get(self.metric)
        if fam is None or fam.get("type") != "histogram":
            return None
        buckets: Dict[float, float] = {}
        total_sum = total_n = 0.0
        for s in fam.get("samples", []):
            if not _match(s.get("labels", {}), self.match):
                continue
            total_sum += float(s.get("sum", 0.0))
            total_n += float(s.get("count", 0))
            for k, v in s.get("buckets", {}).items():
                b = _parse_bound(k)
                buckets[b] = buckets.get(b, 0.0) + float(v)
        return buckets, total_sum, total_n

    def _delta(self, families):
        cum = self._cum(families)
        if cum is None:
            return None
        if self._prev is None:
            self._prev = cum
            return None
        buckets, total_sum, total_n = cum
        pb, ps, pn = self._prev
        if pn == 0 and not pb and buckets:
            # the family's first samples appeared since the empty anchor:
            # the whole current state IS the delta from zero
            pb = {b: 0.0 for b in buckets}
        dn = total_n - pn
        if dn < 0 or set(buckets) != set(pb) or \
                any(buckets[b] < pb[b] for b in buckets):
            # counter reset or bucket-layout change (fresh registry):
            # nothing trustworthy this tick; re-anchor
            self._prev = cum
            return None
        if dn < self.min_count:
            # too few new observations to judge — HOLD the anchor so a
            # low-traffic phase accumulates toward min_count instead of
            # being discarded tick by tick (a sparse but real regression
            # must still produce samples)
            return None
        self._prev = cum
        db = {b: buckets[b] - pb[b] for b in buckets}
        return db, total_sum - ps, dn


class HistogramMeanProbe(_HistDeltaProbe):
    """Mean observation over the tick: delta(_sum)/delta(_count) — the
    step-time regression signal (mean host step seconds this tick)."""

    def sample(self, families, t=None) -> Optional[float]:
        d = self._delta(families)
        if d is None:
            return None
        _, dsum, dn = d
        return dsum / dn

    def describe(self) -> dict:
        return {"probe": "histogram_mean", "metric": self.metric,
                "unit": "mean observation/tick"}


class HistogramQuantileProbe(_HistDeltaProbe):
    """Quantile estimate from bucket-count deltas over the tick,
    reported as the upper bound of the bucket containing the quantile
    (the resolution histograms give; +Inf clamps to the largest finite
    bound * 2 so the score stays finite)."""

    def __init__(self, metric: str, q: float = 0.99,
                 match: Dict[str, str] = (), min_count: int = 1):
        super().__init__(metric, match, min_count)
        if not 0.0 < q < 1.0:
            raise ValueError(f"q must be in (0, 1), got {q}")
        self.q = float(q)

    def sample(self, families, t=None) -> Optional[float]:
        d = self._delta(families)
        if d is None:
            return None
        db, _, dn = d
        want = self.q * dn
        finite = sorted(b for b in db if b != float("inf"))
        # the exposition's bucket counts are CUMULATIVE, so db[b] (a
        # delta of cumulatives) is already "observations <= b this
        # tick" — compare directly, never re-sum across bounds
        for b in finite:
            if db[b] >= want:
                return b
        return (finite[-1] * 2.0) if finite else None

    def describe(self) -> dict:
        return {"probe": "histogram_quantile", "metric": self.metric,
                "q": self.q, "unit": "bucket upper bound"}


class GaugeProbe(Probe):
    """Current value of a gauge (or counter level), summed over matching
    label sets; None while the family has no samples."""

    def __init__(self, metric: str, match: Dict[str, str] = ()):
        self.metric = metric
        self.match = tuple(sorted(dict(match or {}).items()))

    def sample(self, families, t=None) -> Optional[float]:
        fam = families.get(self.metric)
        if fam is None:
            return None
        samples = [s for s in fam.get("samples", [])
                   if _match(s.get("labels", {}), self.match)]
        if not samples:
            return None
        return float(sum(s["value"] for s in samples))

    def describe(self) -> dict:
        return {"probe": "gauge", "metric": self.metric, "unit": "value"}


# -- rolling baseline ---------------------------------------------------------


class RollingBaseline:
    """Windowed median + MAD over accepted samples. Robust: up to half
    the window can be junk before the median moves, so the baseline
    learns "normal" without learning the anomaly."""

    def __init__(self, window: int = 64):
        if window < 4:
            raise ValueError(f"window must be >= 4, got {window}")
        self._vals: deque = deque(maxlen=window)

    def add(self, x: float) -> None:
        self._vals.append(float(x))

    def __len__(self) -> int:
        return len(self._vals)

    def median(self) -> float:
        return float(statistics.median(self._vals)) if self._vals else 0.0

    def mad(self) -> float:
        if not self._vals:
            return 0.0
        med = self.median()
        return float(statistics.median(abs(v - med) for v in self._vals))

    def score(self, x: float, *, rel_floor: float = 0.05,
              abs_floor: float = 0.0) -> float:
        """Robust z of ``x`` against the window. The scale gets a floor
        of ``rel_floor * |median|`` — an ultra-stable series (MAD 0)
        must not turn microscopic jitter into infinite scores — plus an
        optional absolute ``abs_floor`` in the probe's own unit, the
        only meaningful scale when the window learned a flat zero."""
        med = self.median()
        scale = _MAD_SIGMA * self.mad()
        floor = max(rel_floor * abs(med), abs_floor, 1e-12)
        return (x - med) / max(scale, floor)

    def degenerate(self, eps: float = 1e-9) -> bool:
        """True while the window carries no scale information — median
        AND MAD both ~0 (a series that idled at 0 through warmup). A
        robust z against such a window is meaningless: the 1e-12 floor
        would turn any positive sample into an astronomical score."""
        return abs(self.median()) <= eps and self.mad() <= eps

    def to_json(self) -> dict:
        return {"n": len(self._vals), "median": self.median(),
                "mad": self.mad(),
                "window": self._vals.maxlen}


# -- detector -----------------------------------------------------------------


class Detector:
    """One named anomaly detector: probe + judgement + state machine.

    ``mode``:

    - ``"baseline"`` — anomalous when the robust z-score of the tick's
      sample is >= ``threshold`` AND the sample exceeds the baseline
      median by ``min_increase`` (relative) — regressions only, a
      *faster* step never pages;
    - ``"ceiling"`` — anomalous when the sample >= ``threshold``
      (absolute; for boolean gauges like ``train_data_starved`` and
      rate ceilings like a recompile storm);
    - ``"growth"`` — anomalous while the sample grows
      tick-over-tick; fires only when the sustained streak's total
      growth reaches ``threshold`` (fractional; the leak heuristic —
      monotonic AND meaningfully so). Real leaks are steppy
      (allocator-chunk growth), so up to ``plateau_tolerance``
      consecutive non-decreasing plateau ticks HOLD the streak and
      growth anchor instead of resetting them; a longer plateau (or
      any decrease) counts as clean.

    Hysteresis: ``fire_after`` consecutive anomalous ticks to fire
    (>= 2 means one jittery sample can never fire), ``clear_after``
    consecutive clean ticks to close. ``min_history`` baseline samples
    must accumulate before a baseline detector judges at all — a
    fresh process can't fire on its own warmup.

    ``scale_floor`` (baseline mode) is an absolute lower bound on the
    robust-z scale, in the probe's own unit. When it is 0 (default) and
    the learned baseline is *degenerate* (median and MAD both ~0 — a
    gauge that idled at 0 through warmup), the detector skips judgement
    and keeps feeding the baseline instead: a z-score against a ~0
    scale is meaningless, and first real traffic after an idle warmup
    must re-teach the baseline, not open an incident. Set it > 0 to
    keep judging off an idle baseline with a unit-appropriate scale
    (e.g. 1 request of queue depth).
    """

    def __init__(self, name: str, probe: Probe, *,
                 mode: str = "baseline", threshold: float = 8.0,
                 min_increase: float = 0.25, min_abs: float = 0.0,
                 baseline_window: int = 64, min_history: int = 8,
                 fire_after: int = 3, clear_after: int = 3,
                 plateau_tolerance: int = 2, scale_floor: float = 0.0,
                 description: str = ""):
        if mode not in ("baseline", "ceiling", "growth"):
            raise ValueError(f"unknown detector mode {mode!r}")
        if fire_after < 2:
            raise ValueError(
                f"fire_after must be >= 2 (hysteresis: one jittery sample "
                f"must not fire), got {fire_after}")
        if clear_after < 1:
            raise ValueError(f"clear_after must be >= 1, got {clear_after}")
        self.name = name
        self.probe = probe
        self.mode = mode
        self.threshold = float(threshold)
        self.min_increase = float(min_increase)
        self.min_abs = float(min_abs)
        self.scale_floor = float(scale_floor)
        self.min_history = int(min_history)
        self.fire_after = int(fire_after)
        self.clear_after = int(clear_after)
        self.description = description
        self.baseline = RollingBaseline(baseline_window)
        self.state = STATE_OK
        self.last_sample: Optional[float] = None
        self.last_score = 0.0
        self._anom_streak = 0
        self._clean_streak = 0
        self._growth_prev: Optional[float] = None
        self._growth_start: Optional[float] = None
        self._plateau_run = 0
        self.plateau_tolerance = int(plateau_tolerance)
        self.transitions: List[dict] = []

    # -- judgement -----------------------------------------------------------

    def _judge(self, x: float) -> Tuple[Optional[bool], float]:
        """(anomalous | None while unjudgeable, score)."""
        if self.mode == "ceiling":
            score = x / self.threshold if self.threshold else 0.0
            return x >= self.threshold, score
        if self.mode == "growth":
            prev, self._growth_prev = self._growth_prev, x
            if prev is None:
                return None, 0.0
            grew = x > prev * (1.0 + 1e-6) and x > self.min_abs
            if grew:
                self._plateau_run = 0
                if self._growth_start is None:
                    # anchor at the first POSITIVE level: fractional
                    # growth from a zero start is undefined, and a leak
                    # that begins at 0 bytes must still be able to fire
                    self._growth_start = prev if prev > 0 else x
                total = x / self._growth_start - 1.0
                return True, (total / self.threshold
                              if self.threshold else 0.0)
            flat = x >= prev * (1.0 - 1e-6)
            if flat and self._growth_start is not None and \
                    self._plateau_run < self.plateau_tolerance:
                # real-world leaks are steppy (allocator-chunk growth):
                # a bounded run of non-decreasing plateau ticks carries
                # no information — HOLD the anchor and the streak
                # instead of restarting the fire_after count, or a leak
                # growing every few ticks could never fire
                self._plateau_run += 1
                return None, ((x / self._growth_start - 1.0)
                              / self.threshold if self.threshold else 0.0)
            # decreased, or plateaued past tolerance: the growth stopped
            self._plateau_run = 0
            self._growth_start = None
            return False, 0.0
        # baseline mode
        if len(self.baseline) < self.min_history:
            self.baseline.add(x)
            return None, 0.0
        if self.scale_floor <= 0.0 and self.baseline.degenerate():
            # the window learned a flat zero (series idled through
            # warmup) and no absolute scale was configured: unjudgeable
            # — keep feeding the baseline so it re-learns "normal"
            # under real traffic instead of scoring it ~1e12
            self.baseline.add(x)
            return None, 0.0
        score = self.baseline.score(x, abs_floor=self.scale_floor)
        med = self.baseline.median()
        anomalous = (score >= self.threshold
                     and x >= med * (1.0 + self.min_increase)
                     and x >= self.min_abs)
        return anomalous, score

    def _growth_fire_ok(self) -> bool:
        """growth mode's extra fire gate: the sustained streak must add
        up to at least ``threshold`` fractional growth."""
        if self.mode != "growth":
            return True
        start, x = self._growth_start, self.last_sample
        return bool(start is not None and x is not None
                    and x >= start * (1.0 + self.threshold))

    # -- state machine -------------------------------------------------------

    def observe(self, families, t: float) -> Optional[str]:
        """One tick: sample, judge, advance. Returns the new state on a
        transition, else None."""
        x = self.probe.sample(families, t)
        if x is None:
            return None  # no information: streaks and state hold
        self.last_sample = x
        anomalous, score = self._judge(x)
        self.last_score = score
        if anomalous is None:
            return None
        new = self.state
        if anomalous:
            self._clean_streak = 0
            self._anom_streak += 1
            if self.state == STATE_OK:
                new = STATE_SUSPECT
            elif self.state == STATE_SUSPECT and \
                    self._anom_streak >= self.fire_after and \
                    self._growth_fire_ok():
                new = STATE_FIRING
        else:
            self._anom_streak = 0
            if self.state == STATE_SUSPECT:
                new = STATE_OK
            elif self.state == STATE_FIRING:
                self._clean_streak += 1
                if self._clean_streak >= self.clear_after:
                    new = STATE_OK
            # only clean samples observed while already ok feed the
            # baseline — suspect/firing samples never do, and neither
            # does the clean run that closes an incident (self.state is
            # the PRE-transition state here): the baseline stays frozen
            # until the detector has fully returned to ok
            if self.mode == "baseline" and self.state == STATE_OK:
                self.baseline.add(x)
        if new != self.state:
            old, self.state = self.state, new
            if new == STATE_OK:
                self._clean_streak = 0
            tr = {"t": t, "from": old, "to": new, "sample": x,
                  "score": round(score, 3)}
            self.transitions.append(tr)
            del self.transitions[:-32]
            return new
        return None

    def verdict(self) -> dict:
        """The self-contained judgement document the incident bundle
        embeds: what fired, against what baseline, by how much."""
        return {
            "detector": self.name,
            "description": self.description,
            "mode": self.mode,
            "state": self.state,
            "observed": self.last_sample,
            "score": round(self.last_score, 3),
            "threshold": self.threshold,
            "scale_floor": self.scale_floor,
            "baseline": self.baseline.to_json(),
            "probe": self.probe.describe(),
            "fire_after": self.fire_after,
            "clear_after": self.clear_after,
            "transitions": list(self.transitions[-8:]),
        }


# -- built-in detectors -------------------------------------------------------


def default_detectors(*, fire_after: int = 3, clear_after: int = 3,
                      min_history: int = 8) -> List[Detector]:
    """The eight built-ins over the standard telemetry families. All are
    quiet until their probe has real data AND the baseline has
    ``min_history`` accepted samples — a fresh process can't fire
    during its own warmup."""
    k = dict(fire_after=fire_after, clear_after=clear_after,
             min_history=min_history)
    return [
        Detector(
            "train_step_time_regression",
            HistogramMeanProbe("train_step_seconds", min_count=4),
            mode="baseline", threshold=8.0, min_increase=0.25,
            description="Mean host step wall-time this tick rose far "
                        "above its rolling baseline.", **k),
        Detector(
            "serving_p99_regression",
            HistogramQuantileProbe("serving_request_latency_seconds",
                                   q=0.99, min_count=8),
            mode="baseline", threshold=8.0, min_increase=0.5,
            description="Serving request p99 (bucket-resolved) rose far "
                        "above its rolling baseline.", **k),
        Detector(
            "generation_ttft_regression",
            HistogramQuantileProbe("generation_ttft_seconds",
                                   q=0.99, min_count=4),
            mode="baseline", threshold=8.0, min_increase=0.5,
            description="Streaming-generation time-to-first-token p99 "
                        "(bucket-resolved) rose far above its rolling "
                        "baseline: prefill is queueing behind decode or "
                        "slots are saturated.", **k),
        Detector(
            "recompile_storm",
            CounterRateProbe("runtime_jit_compiles_total"),
            mode="ceiling", threshold=0.5,
            description="Sustained XLA recompiles (>= 0.5/s): bucket "
                        "misses are compiling on the hot path.", **k),
        Detector(
            "recompile_after_warmup",
            CounterRateProbe("warmup_recompiles_after_warm_total"),
            mode="ceiling", threshold=0.05,
            description="Serving planes that declared themselves warm "
                        "are compiling under traffic — the warmup "
                        "manifest no longer covers the live shape mix "
                        "(or warmup was skipped). The invariant is "
                        "zero; any sustained rate pages.", **k),
        Detector(
            "serving_queue_buildup",
            GaugeProbe("serving_queue_depth"),
            mode="baseline", threshold=8.0, min_increase=1.0, min_abs=8.0,
            # scale_floor deliberately 0: a server that idled through
            # warmup learns a degenerate all-zero baseline, and the
            # first traffic ramp then RE-TEACHES it instead of opening
            # an incident on normal load. The cost is a bounded blind
            # window (until the window median goes positive) for a
            # buildup that starts from idle — during which real queue
            # pathology still surfaces via serving_p99_regression and
            # the SLO latency burn rules. Operators who prefer absolute
            # judgement off an idle baseline set scale_floor=1.0 (one
            # queue slot) on their own detector list.
            description="Admission queue depth far above its rolling "
                        "baseline: arrivals outpace dispatch.", **k),
        Detector(
            "train_data_starvation",
            GaugeProbe("train_data_starved"),
            mode="ceiling", threshold=1.0,
            description="The input pipeline dominates step wall-time "
                        "(train_data_starved held at 1).", **k),
        Detector(
            "live_array_bytes_leak",
            GaugeProbe("runtime_live_array_bytes"),
            mode="growth", threshold=0.10, fire_after=max(fire_after, 6),
            clear_after=clear_after, min_history=min_history,
            description="Live jax array bytes growing monotonically "
                        "(>= 10% sustained): buffers are leaking.", ),
        Detector(
            "hbm_bytes_leak",
            GaugeProbe("runtime_device_memory_bytes",
                       match={"stat": "bytes_in_use"}),
            mode="growth", threshold=0.10, fire_after=max(fire_after, 6),
            clear_after=clear_after, min_history=min_history,
            description="Device bytes-in-use growing monotonically "
                        "(>= 10% sustained): HBM is leaking toward "
                        "OOM.", ),
    ]


def default_fleet_detectors(*, fire_after: int = 3, clear_after: int = 3,
                            min_history: int = 8) -> List[Detector]:
    """The router's detector set over its own instrument bundle: fleet
    p99 regression at the front door (queueing + retries + network
    included — the client's view, not one backend's), ejection storms
    (backends churning in and out of the routing table), and a
    sustained retry-budget exhaustion rate (failovers being refused —
    the fleet is one backend loss away from hard errors)."""
    k = dict(fire_after=fire_after, clear_after=clear_after,
             min_history=min_history)
    return [
        Detector(
            "fleet_p99_regression",
            HistogramQuantileProbe("router_request_latency_seconds",
                                   q=0.99, min_count=8),
            mode="baseline", threshold=8.0, min_increase=0.5,
            description="Router-vantage request p99 (bucket-resolved) "
                        "rose far above its rolling baseline — the "
                        "fleet as the client sees it.", **k),
        Detector(
            "fleet_ejection_storm",
            CounterRateProbe("router_ejections_total"),
            mode="ceiling", threshold=0.2,
            description="Sustained backend ejections (>= 0.2/s): the "
                        "routing table is churning, capacity is "
                        "flapping.", **k),
        Detector(
            "fleet_retry_budget_exhaustion",
            CounterRateProbe("router_retry_budget_exhausted_total"),
            mode="ceiling", threshold=0.1,
            description="Failovers being refused for lack of retry "
                        "budget (>= 0.1/s sustained): failures are "
                        "outrunning the budget's deposit rate.", **k),
    ]


# -- sentinel metric family ---------------------------------------------------


class SentinelMetrics:
    """The sentinel's own exposition — detector states/scores, tick
    counters (the ``anomaly-firing`` burn-rate rule's total/bad pair),
    the incident pipeline's counters, and the host sampler's meter."""

    def __init__(self, registry: Optional[_metrics.MetricsRegistry] = None):
        r = registry if registry is not None else _metrics.default_registry()
        self.registry = r
        self.anomaly_state = r.gauge(
            "anomaly_state", "Detector state: 0=ok 1=suspect 2=firing.",
            ("detector",))
        self.anomaly_score = r.gauge(
            "anomaly_score", "Latest robust anomaly score per detector "
            "(baseline mode: robust z vs the rolling median+MAD; "
            "ceiling: value/threshold; growth: growth/threshold).",
            ("detector",))
        self.anomaly_transitions_total = r.counter(
            "anomaly_transitions_total", "Detector state transitions by "
            "destination.", ("detector", "to"))
        self.sentinel_ticks_total = r.counter(
            "ticks_total", "Sentinel evaluation passes (the "
            "anomaly-firing burn-rate rule's total).",
            namespace="sentinel")
        self.anomaly_firing_ticks_total = r.counter(
            "anomaly_firing_ticks_total", "Sentinel passes that found at "
            "least one detector firing (the anomaly-firing burn-rate "
            "rule's bad events).")
        self.incident_bundles_total = r.counter(
            "incident_bundles_total", "Incident bundles opened, by the "
            "detector that fired.", ("detector",))
        self.incidents_open = r.gauge(
            "incidents_open", "Incidents currently open.")
        self.hostsampler_samples_total = r.counter(
            "hostsampler_samples_total", "Host stack sampler passes "
            "(each folds every live thread's stack once).")
        self.hostsampler_stacks = r.gauge(
            "hostsampler_stacks", "Distinct folded stacks currently "
            "held by the host stack sampler.")


_sentinel_metrics: Optional[SentinelMetrics] = None
_sm_lock = threading.Lock()


def get_sentinel_metrics() -> SentinelMetrics:
    global _sentinel_metrics
    if _sentinel_metrics is None:
        with _sm_lock:
            if _sentinel_metrics is None:
                _sentinel_metrics = SentinelMetrics()
    return _sentinel_metrics


def _drop_sentinel_metrics():
    global _sentinel_metrics
    _sentinel_metrics = None


_metrics.register_reset_hook(_drop_sentinel_metrics)


# -- engine -------------------------------------------------------------------


class Sentinel:
    """Evaluate detectors on a cadence; open/close incidents on firing.

    ``registries``: metric registries to read (None = the process
    default, resolved per tick). ``incidents``: an
    :class:`~deeplearning4j_tpu.observability.incidents.IncidentManager`
    (or None — detect-only). ``sampler``: a
    :class:`~deeplearning4j_tpu.observability.hostsampler.HostStackSampler`
    whose high-rate window is armed on suspect and whose flames land in
    the bundle. ``clock`` is injectable for deterministic tests.
    """

    def __init__(self, detectors: Optional[Sequence[Detector]] = None, *,
                 registries: Optional[Sequence] = None,
                 interval_s: float = 10.0,
                 incidents=None, sampler=None,
                 clock: Optional[Callable[[], float]] = None,
                 arm_window_ticks: int = 6):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.detectors = list(detectors) if detectors is not None \
            else default_detectors()
        names = [d.name for d in self.detectors]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate detector names in {names}")
        self._registries = list(registries) if registries is not None else None
        self.interval_s = float(interval_s)
        self.incidents = incidents
        self.sampler = sampler
        self.arm_window_ticks = int(arm_window_ticks)
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._open_incidents: Dict[str, str] = {}  # detector -> incident id

    def _resolve_registries(self):
        if self._registries is not None:
            return self._registries
        return [_metrics.default_registry()]

    # -- evaluation ----------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> dict:
        """One evaluation pass; returns :meth:`verdicts`.

        The engine lock covers the state machines only; incident bundle
        capture (a second registry scrape plus disk writes) runs after
        it is released, so ``verdicts()``/``states()`` and the next tick
        never stall behind capture I/O. Verdict documents are snapshotted
        at transition time, under the lock."""
        actions: List[Tuple[str, str, dict]] = []
        with self._lock:
            t = self._clock() if now is None else now
            regs = self._resolve_registries()
            families = _doc_map(regs)
            sm = get_sentinel_metrics() if _metrics.enabled() else None
            any_firing = False
            for det in self.detectors:
                transition = det.observe(families, t)
                if transition is not None:
                    record_event("anomaly.transition", detector=det.name,
                                 to=transition, sample=det.last_sample,
                                 score=round(det.last_score, 3))
                    if sm is not None:
                        sm.anomaly_transitions_total.inc(
                            detector=det.name, to=transition)
                    if transition == STATE_SUSPECT and \
                            self.sampler is not None:
                        # dense flames over the (possibly) anomalous
                        # window, ready by firing time
                        self.sampler.arm(
                            self.arm_window_ticks * self.interval_s)
                    elif transition == STATE_FIRING:
                        if self.incidents is not None:
                            # pending marker, placed under the lock: a
                            # concurrent tick() observing firing->ok
                            # before the deferred open below registers
                            # its id must still queue the close
                            self._open_incidents.setdefault(det.name, "")
                        actions.append(("open", det.name, det.verdict()))
                    elif transition == STATE_OK and \
                            det.name in self._open_incidents:
                        actions.append(("close", det.name, det.verdict()))
                if det.state == STATE_FIRING:
                    any_firing = True
                if sm is not None:
                    sm.anomaly_state.set(_STATE_NUM[det.state],
                                         detector=det.name)
                    sm.anomaly_score.set(det.last_score, detector=det.name)
            if sm is not None:
                sm.sentinel_ticks_total.inc()
                if any_firing:
                    sm.anomaly_firing_ticks_total.inc()
            result = self._verdicts_locked(t)
        for kind, name, verdict in actions:
            if kind == "open":
                self._open_incident(name, verdict)
            else:
                self._close_incident(name, verdict)
        return result

    def _open_incident(self, detector_name: str, verdict: dict):
        if self.incidents is None:
            return
        with self._lock:
            if self._open_incidents.get(detector_name, ""):
                return  # a real bundle is already open
            if detector_name not in self._open_incidents:
                return  # a racing close consumed the pending marker
        iid = None
        try:
            if self.sampler is not None:
                # keep the high-rate window open through the capture
                self.sampler.arm(self.arm_window_ticks * self.interval_s)
            iid = self.incidents.open_incident(
                verdict, registries=self._resolve_registries(),
                sampler=self.sampler)
            with self._lock:
                if detector_name in self._open_incidents:
                    self._open_incidents[detector_name] = iid
                    iid = None  # registered; nothing to roll back
            if iid is not None:
                # the detector cleared while the capture ran (a racing
                # close popped the marker): close the fresh bundle now
                # instead of leaking it open forever
                self.incidents.close_incident(iid, resolution=verdict)
        except Exception:  # noqa: BLE001 — capture failure must not
            with self._lock:  # stop detection (or the evaluator thread)
                if self._open_incidents.get(detector_name) == "":
                    del self._open_incidents[detector_name]

    def _close_incident(self, detector_name: str, resolution: dict):
        with self._lock:
            iid = self._open_incidents.pop(detector_name, None)
        if not iid or self.incidents is None:
            return  # "" = open in flight; it will close its own bundle
        try:
            self.incidents.close_incident(iid, resolution=resolution)
        except Exception:  # noqa: BLE001
            pass

    # -- rendering -----------------------------------------------------------

    def states(self) -> Dict[str, str]:
        with self._lock:
            return {d.name: d.state for d in self.detectors}

    def verdicts(self) -> dict:
        with self._lock:
            return self._verdicts_locked(self._clock())

    def _verdicts_locked(self, t: float) -> dict:
        worst = STATE_OK
        rows = []
        for d in self.detectors:
            if _STATE_NUM[d.state] > _STATE_NUM[worst]:
                worst = d.state
            rows.append(d.verdict())
        return {"status": worst, "evaluated_at": t,
                "interval_s": self.interval_s,
                "open_incidents": {k: v for k, v
                                   in self._open_incidents.items() if v},
                "detectors": rows}

    # -- background thread ---------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "Sentinel":
        if self.running:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="anomaly-sentinel")
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the sentinel must survive
                pass           # a bad tick; the next one retries

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
