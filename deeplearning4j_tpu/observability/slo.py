"""SLO / burn-rate health engine: the layer that turns counters into
"is this server healthy?".

PR 3 gave every layer one telemetry spine; nothing consumed it. This
module evaluates *declarative SLO rules* against the registries' live
counters and histogram buckets and answers with an alert state per rule:

- **availability** rules: a good-events ratio objective (e.g. 99.9% of
  ``serving_requests_total`` not 429/5xx);
- **latency** rules: a quantile objective expressed through histogram
  buckets (e.g. 99% of ``serving_request_latency_seconds`` ≤ 0.25 s —
  the threshold snaps to the nearest bucket bound at or above it).

Alerting is classic multi-window burn rate (the SRE-workbook recipe):
with error budget ``1 - objective``, the burn rate over a window is
``error_rate / budget``; a rule *breaches* when BOTH the short and long
window of any configured pair burn faster than the pair's threshold
(fast 5m/1h at 14.4x and slow 30m/6h at 6x by default). Short windows
make alerts resolve quickly; long windows stop one blip from paging.

Each rule runs an :class:`AlertState` machine —
``ok → pending → firing → resolved → ok`` — driven by a background
evaluator thread (:class:`HealthEngine`), with every transition counted
in the ``slo_*`` metric family and recorded to the flight recorder
(``slo.transition`` events), so the post-mortem timeline contains the
alert history alongside the faults that caused it.

``time_scale`` multiplies every rule duration (windows, for/hold), so
the same production rule file runs in CI at milliseconds-scale windows.

CLI: ``python -m deeplearning4j_tpu.observability.slo --check rules.json``
validates a rule file offline (unknown metric names, malformed
objectives, overlapping windows) and exits non-zero on problems.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import re
import sys
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.observability import metrics as _metrics
from deeplearning4j_tpu.observability.flightrecorder import (
    get_flight_recorder,
    record_event,
)

# -- alert states -------------------------------------------------------------

STATE_OK = "ok"
STATE_PENDING = "pending"
STATE_FIRING = "firing"
STATE_RESOLVED = "resolved"
_STATE_NUM = {STATE_OK: 0, STATE_PENDING: 1, STATE_FIRING: 2,
              STATE_RESOLVED: 3}


# -- rule model ---------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class BurnWindow:
    """One (short, long) burn-rate window pair; the rule breaches when
    both windows burn at >= ``burn`` times the error-budget rate."""

    short_s: float
    long_s: float
    burn: float

    def label(self) -> str:
        return f"{_dur(self.short_s)}/{_dur(self.long_s)}"


# The SRE-workbook page-worthy defaults: 14.4x over 5m/1h (2% of a
# 30-day budget in one hour) and 6x over 30m/6h.
DEFAULT_WINDOWS = (BurnWindow(300.0, 3600.0, 14.4),
                   BurnWindow(1800.0, 21600.0, 6.0))


@dataclasses.dataclass(frozen=True)
class Selector:
    """Which samples of a metric family a rule reads: the family name
    plus optional per-label regex filters (fullmatch semantics)."""

    metric: str
    match: Tuple[Tuple[str, str], ...] = ()

    def matches(self, labels: Dict[str, str]) -> bool:
        for key, pattern in self.match:
            if not re.fullmatch(pattern, str(labels.get(key, ""))):
                return False
        return True

    @classmethod
    def from_json(cls, d: dict) -> "Selector":
        match = tuple(sorted((k, v) for k, v in (d.get("match") or {}).items()))
        return cls(metric=d["metric"], match=match)

    def to_json(self) -> dict:
        out: dict = {"metric": self.metric}
        if self.match:
            out["match"] = dict(self.match)
        return out


@dataclasses.dataclass(frozen=True)
class SLORule:
    """One declarative objective.

    ``kind="availability"``: ``objective`` is the target good ratio;
    ``total``/``bad`` select counter samples. ``kind="latency"``:
    ``objective`` is the quantile, ``threshold_s`` the bound it must
    stay under, ``histogram`` the latency family.

    Durations (``windows``, ``for_s``, ``resolve_hold_s``) are canonical
    production values; the engine's ``time_scale`` shrinks them for
    tests, so the same rule file ships everywhere.
    """

    name: str
    kind: str                    # "availability" | "latency"
    objective: float
    total: Optional[Selector] = None
    bad: Optional[Selector] = None
    histogram: Optional[Selector] = None
    threshold_s: Optional[float] = None
    windows: Tuple[BurnWindow, ...] = DEFAULT_WINDOWS
    for_s: float = 120.0         # breach must hold this long before firing
    resolve_hold_s: float = 300.0  # resolved lingers this long before ok

    @property
    def error_budget(self) -> float:
        return 1.0 - self.objective

    def metric_names(self) -> List[str]:
        out = []
        for sel in (self.total, self.bad, self.histogram):
            if sel is not None:
                out.append(sel.metric)
        return out

    def to_json(self) -> dict:
        out: dict = {"name": self.name, "kind": self.kind,
                     "objective": self.objective,
                     "windows": [dataclasses.asdict(w) for w in self.windows],
                     "for_s": self.for_s,
                     "resolve_hold_s": self.resolve_hold_s}
        if self.kind == "availability":
            out["total"] = self.total.to_json()
            out["bad"] = self.bad.to_json()
        else:
            out["histogram"] = self.histogram.to_json()
            out["threshold_s"] = self.threshold_s
        return out


def _dur(seconds: float) -> str:
    if seconds >= 3600 and seconds % 3600 == 0:
        return f"{int(seconds // 3600)}h"
    if seconds >= 60 and seconds % 60 == 0:
        return f"{int(seconds // 60)}m"
    return f"{seconds:g}s"


# -- rule parsing + validation ------------------------------------------------

_ALLOWED_RULE_KEYS = {"name", "kind", "objective", "total", "bad",
                      "histogram", "threshold_s", "windows", "for_s",
                      "resolve_hold_s"}

# Built-in metric families a rule file may reference without a live
# process (serving bundle + the lazy default-registry bundles). The
# runtime collector's families are listed statically: instantiating it
# offline would hook jax.monitoring as a side effect.
_RUNTIME_FAMILIES = (
    "runtime_device_memory_bytes", "runtime_live_arrays",
    "runtime_live_array_bytes", "runtime_jit_compiles_total",
    "runtime_jit_compile_seconds", "runtime_transfers_total",
    "runtime_transfer_bytes_total", "runtime_collections_total",
)


def known_metric_names(extra: Sequence[str] = ()) -> set:
    """Every metric family the built-in bundles can expose — the
    validation vocabulary for offline ``--check``."""
    names = set(_RUNTIME_FAMILIES) | set(extra)
    reg = _metrics.MetricsRegistry()
    _metrics.TrainingMetrics(reg)
    _metrics.ResilienceMetrics(reg)
    _metrics.CheckpointMetrics(reg)
    # the cold-start plane (compile_cache_* / warmup_* families —
    # runtime/compilecache.py + serving/warmstart.py): the
    # recompile-after-warmup burn-rate rule validates offline
    _metrics.WarmstartMetrics(reg)
    # the runtime concurrency-sanitizer families (analysis/lockcheck.py):
    # the sanitizer-violation burn-rate rule validates offline
    _metrics.SanitizerMetrics(reg)
    SLOMetrics(reg)
    from deeplearning4j_tpu.observability.federation import ClusterMetrics
    from deeplearning4j_tpu.observability.reqlog import ReqLogMetrics
    from deeplearning4j_tpu.observability.sentinel import SentinelMetrics
    from deeplearning4j_tpu.serving.cache import CacheMetrics
    from deeplearning4j_tpu.serving.metrics import ServingMetrics
    from deeplearning4j_tpu.serving.router import RouterMetrics

    ServingMetrics(reg)
    # the caching-tier cache_* / cache_prefix_* families
    # (serving/cache.py + serving/prefixkv.py): the cache hit-rate and
    # stale-serve burn-rate rules validate offline
    CacheMetrics(reg)
    # the fleet-router router_* families (serving/router.py): the
    # router-availability / retry-budget burn-rate rules validate
    # offline like every other plane's
    RouterMetrics(reg)
    # the supervisor-side cluster_* families (federation aggregator):
    # rule files over the federated registry validate offline too
    ClusterMetrics(reg)
    # the anomaly sentinel + incident pipeline families (sentinel.py):
    # the anomaly-firing burn-rate rule reads these
    SentinelMetrics(reg)
    # the request-ledger + tail-trace-retention families (reqlog.py)
    ReqLogMetrics(reg)
    # the traffic-replay + game-day drill families (resilience/replay.py
    # + resilience/gameday.py): the gameday-gate-breach burn-rate rule
    # validates offline
    from deeplearning4j_tpu.resilience.gameday import GameDayMetrics
    from deeplearning4j_tpu.resilience.replay import ReplayMetrics

    ReplayMetrics(reg)
    GameDayMetrics(reg)
    # the historical-telemetry tier (observability/timeseries.py +
    # observability/usage.py): tsdb_* sampler health, usage_* account
    # bookkeeping, and the capacity_* tick pair the
    # capacity-headroom-exhausted burn-rate rule consumes
    from deeplearning4j_tpu.observability.timeseries import TsdbMetrics
    from deeplearning4j_tpu.observability.usage import (
        CapacityMetrics,
        UsageMetrics,
    )

    TsdbMetrics(reg)
    UsageMetrics(reg)
    CapacityMetrics(reg)
    # the fleet autoscaler's autoscaler_* families
    # (serving/autoscaler.py): the autoscaler-flapping and
    # fleet-underprovisioned burn-rate rules validate offline
    from deeplearning4j_tpu.serving.autoscaler import AutoscalerMetrics

    AutoscalerMetrics(reg)
    names.update(i.name for i in reg.instruments())
    return names


def _validate_selector(d, where: str, errors: List[str],
                       known: Optional[set]) -> Optional[Selector]:
    if not isinstance(d, dict) or not isinstance(d.get("metric"), str) \
            or not d.get("metric"):
        errors.append(f"{where}: expected {{'metric': <name>, "
                      f"'match': {{label: regex}}?}}, got {d!r}")
        return None
    if known is not None and d["metric"] not in known:
        errors.append(f"{where}: unknown metric name {d['metric']!r}")
    match = d.get("match") or {}
    if not isinstance(match, dict):
        errors.append(f"{where}: 'match' must be a dict of label->regex")
        return None
    for k, v in match.items():
        try:
            re.compile(str(v))
        except re.error as e:
            errors.append(f"{where}: bad regex for label {k!r}: {e}")
    try:
        return Selector.from_json(d)
    except Exception as e:  # noqa: BLE001 - report, keep validating
        errors.append(f"{where}: {e}")
        return None


def _validate_windows(ws, where: str, errors: List[str]
                      ) -> Tuple[BurnWindow, ...]:
    if ws is None:
        return DEFAULT_WINDOWS
    if not isinstance(ws, list) or not ws:
        errors.append(f"{where}: 'windows' must be a non-empty list")
        return DEFAULT_WINDOWS
    out, seen = [], set()
    for i, w in enumerate(ws):
        tag = f"{where}.windows[{i}]"
        if not isinstance(w, dict):
            errors.append(f"{tag}: expected an object, got {w!r}")
            continue
        try:
            short_s = float(w["short_s"])
            long_s = float(w["long_s"])
            burn = float(w["burn"])
        except (KeyError, TypeError, ValueError):
            errors.append(f"{tag}: needs numeric short_s, long_s, burn")
            continue
        if short_s <= 0 or long_s <= 0 or burn <= 0:
            errors.append(f"{tag}: short_s/long_s/burn must be > 0")
            continue
        if short_s >= long_s:
            errors.append(f"{tag}: overlapping window: short_s "
                          f"({short_s:g}) must be < long_s ({long_s:g})")
            continue
        if (short_s, long_s) in seen:
            errors.append(f"{tag}: overlapping window: duplicate pair "
                          f"({short_s:g}s, {long_s:g}s)")
            continue
        seen.add((short_s, long_s))
        out.append(BurnWindow(short_s, long_s, burn))
    return tuple(out) if out else DEFAULT_WINDOWS


def validate_rules_doc(doc, known: Optional[set] = None
                       ) -> Tuple[List[SLORule], List[str]]:
    """Validate a rules document (``{"rules": [...]}`` or a bare list);
    returns (parsed rules, error strings). A rule with errors is
    dropped from the parsed list."""
    errors: List[str] = []
    raw = doc.get("rules") if isinstance(doc, dict) else doc
    if not isinstance(raw, list):
        return [], ["rules document must be {'rules': [...]} or a list"]
    rules: List[SLORule] = []
    names = set()
    for i, rd in enumerate(raw):
        where = (f"rules[{i}]" if not isinstance(rd, dict) or not rd.get("name")
                 else f"rule {rd['name']!r}")
        n_before = len(errors)
        if not isinstance(rd, dict):
            errors.append(f"{where}: expected an object, got {rd!r}")
            continue
        unknown = set(rd) - _ALLOWED_RULE_KEYS
        if unknown:
            errors.append(f"{where}: unknown keys {sorted(unknown)}")
        name = rd.get("name")
        if not isinstance(name, str) or not name:
            errors.append(f"{where}: 'name' must be a non-empty string")
            name = f"<rules[{i}]>"
        if name in names:
            errors.append(f"{where}: duplicate rule name")
        names.add(name)
        kind = rd.get("kind")
        if kind not in ("availability", "latency"):
            errors.append(f"{where}: 'kind' must be 'availability' or "
                          f"'latency', got {kind!r}")
            continue
        # a malformed objective must not mask selector/window problems:
        # record it and keep validating the rest of the rule
        objective = None
        try:
            objective = float(rd["objective"])
        except (KeyError, TypeError, ValueError):
            errors.append(f"{where}: malformed objective: 'objective' must "
                          "be a number")
        if objective is not None and not 0.0 < objective < 1.0:
            errors.append(f"{where}: malformed objective: must be in (0, 1) "
                          f"exclusive, got {objective!r} (an objective of 1.0 "
                          "has zero error budget — burn rate is undefined)")
            objective = None
        total = bad = hist = None
        threshold_s = None
        if kind == "availability":
            if "histogram" in rd or "threshold_s" in rd:
                errors.append(f"{where}: availability rules take "
                              "'total'/'bad', not 'histogram'/'threshold_s'")
            total = _validate_selector(rd.get("total"), f"{where}.total",
                                       errors, known)
            bad = _validate_selector(rd.get("bad"), f"{where}.bad",
                                     errors, known)
        else:
            if "total" in rd or "bad" in rd:
                errors.append(f"{where}: latency rules take 'histogram'/"
                              "'threshold_s', not 'total'/'bad'")
            hist = _validate_selector(rd.get("histogram"),
                                      f"{where}.histogram", errors, known)
            try:
                threshold_s = float(rd["threshold_s"])
            except (KeyError, TypeError, ValueError):
                errors.append(f"{where}: malformed objective: latency rules "
                              "need a numeric 'threshold_s'")
                threshold_s = None
            if threshold_s is not None and not threshold_s > 0:
                errors.append(f"{where}: malformed objective: threshold_s "
                              f"must be > 0, got {threshold_s!r}")
                threshold_s = None
        windows = _validate_windows(rd.get("windows"), where, errors)
        for_s = rd.get("for_s", 120.0)
        hold_s = rd.get("resolve_hold_s", 300.0)
        for key, val in (("for_s", for_s), ("resolve_hold_s", hold_s)):
            if not isinstance(val, (int, float)) or val < 0:
                errors.append(f"{where}: {key} must be a number >= 0")
        if len(errors) > n_before:
            continue
        rules.append(SLORule(
            name=name, kind=kind, objective=objective, total=total, bad=bad,
            histogram=hist, threshold_s=threshold_s, windows=windows,
            for_s=float(for_s), resolve_hold_s=float(hold_s)))
    return rules, errors


def load_rules(path: str, known: Optional[set] = None) -> List[SLORule]:
    """Load + validate a rules JSON file; raises ValueError listing every
    problem. ``known=None`` skips metric-name vocabulary checking (the
    engine accepts rules over user-registered families)."""
    with open(path) as fh:
        doc = json.load(fh)
    rules, errors = validate_rules_doc(doc, known=known)
    if errors:
        raise ValueError(f"{path}: " + "; ".join(errors))
    return rules


def default_serving_rules() -> List[SLORule]:
    """The rules a ``ModelServer`` evaluates when none are supplied —
    availability (non-429/5xx ratio) and p99 latency against the serving
    bundle. Mirrored by ``observability/example_rules.json``."""
    return [
        SLORule(
            name="serving-availability", kind="availability",
            objective=0.999,
            total=Selector("serving_requests_total"),
            bad=Selector("serving_requests_total",
                         match=(("code", "429|5.."),)),
            windows=DEFAULT_WINDOWS, for_s=120.0, resolve_hold_s=300.0),
        SLORule(
            name="serving-latency-p99", kind="latency",
            objective=0.99, threshold_s=0.25,
            histogram=Selector("serving_request_latency_seconds"),
            windows=DEFAULT_WINDOWS, for_s=120.0, resolve_hold_s=300.0),
    ]


def default_fleet_rules() -> List[SLORule]:
    """The rules a ``FleetRouter`` evaluates over its own registry plus
    the federated scrape when none are supplied: fleet availability
    (requests the router refused outright — sheds the backends never
    saw), fleet p99 at the router vantage (queueing + retries + network
    included), retry-budget burn, and ejection churn. All four are
    mirrored by ``observability/example_rules.json``."""
    return [
        SLORule(
            name="fleet-availability", kind="availability",
            objective=0.999,
            total=Selector("router_requests_total"),
            bad=Selector("router_shed_total"),
            windows=DEFAULT_WINDOWS, for_s=120.0, resolve_hold_s=300.0),
        SLORule(
            name="fleet-latency-p99", kind="latency",
            objective=0.99, threshold_s=0.5,
            histogram=Selector("router_request_latency_seconds"),
            windows=DEFAULT_WINDOWS, for_s=120.0, resolve_hold_s=300.0),
        SLORule(
            name="fleet-retry-budget-burn", kind="availability",
            objective=0.99,
            total=Selector("router_requests_total"),
            bad=Selector("router_retry_budget_exhausted_total"),
            windows=(BurnWindow(300.0, 3600.0, 10.0),
                     BurnWindow(1800.0, 21600.0, 4.0)),
            for_s=60.0, resolve_hold_s=300.0),
        SLORule(
            name="fleet-ejection-churn", kind="availability",
            objective=0.99,
            total=Selector("router_probes_total"),
            bad=Selector("router_ejections_total"),
            windows=(BurnWindow(300.0, 3600.0, 10.0),),
            for_s=60.0, resolve_hold_s=300.0),
    ]


# -- slo metric family --------------------------------------------------------


class SLOMetrics:
    """The engine's own exposition: rule state, live burn rates, and a
    transition counter — health is scrapeable, not just pollable."""

    def __init__(self, registry: Optional[_metrics.MetricsRegistry] = None):
        r = registry if registry is not None else _metrics.default_registry()
        self.registry = r
        ns = "slo"
        self.state = r.gauge(
            "state", "Alert state per rule: 0=ok 1=pending 2=firing "
            "3=resolved.", ("rule",), namespace=ns)
        self.burn_rate = r.gauge(
            "burn_rate", "Error-budget burn rate per rule and window "
            "(1.0 = burning exactly the budget).", ("rule", "window"),
            namespace=ns)
        self.transitions_total = r.counter(
            "transitions_total", "Alert state transitions by rule and "
            "destination state.", ("rule", "to"), namespace=ns)


_slo_metrics: Optional[SLOMetrics] = None
_slo_lock = threading.Lock()


def get_slo_metrics() -> SLOMetrics:
    global _slo_metrics
    if _slo_metrics is None:
        with _slo_lock:
            if _slo_metrics is None:
                _slo_metrics = SLOMetrics()
    return _slo_metrics


def _drop_slo_metrics():
    global _slo_metrics
    _slo_metrics = None


_metrics.register_reset_hook(_drop_slo_metrics)


# -- sampling helpers ---------------------------------------------------------


def _doc_map(registries) -> Dict[str, dict]:
    doc = _metrics.render_json_multi(registries)
    return {m["name"]: m for m in doc["metrics"]}


def _counter_sum(families: Dict[str, dict], sel: Selector) -> float:
    fam = families.get(sel.metric)
    if fam is None or fam["type"] not in ("counter", "gauge"):
        return 0.0
    return float(sum(s["value"] for s in fam["samples"]
                     if sel.matches(s["labels"])))


def _parse_bound(key: str) -> float:
    return float("inf") if key == "+Inf" else float(key)


def _hist_good_total(families: Dict[str, dict], sel: Selector,
                     threshold_s: float) -> Tuple[float, float]:
    """(observations <= threshold bucket, total observations) summed over
    the matching label sets. The threshold snaps to the smallest bucket
    bound at or above it (an off-bucket threshold degrades gracefully to
    the next coarser bound rather than failing)."""
    fam = families.get(sel.metric)
    if fam is None or fam["type"] != "histogram":
        return 0.0, 0.0
    good = total = 0.0
    for s in fam["samples"]:
        if not sel.matches(s["labels"]):
            continue
        total += s["count"]
        bounds = sorted((_parse_bound(k) for k in s["buckets"]),)
        chosen = next((b for b in bounds
                       if b >= threshold_s * (1.0 - 1e-9)), float("inf"))
        good += s["buckets"][
            "+Inf" if chosen == float("inf") else _metrics._fmt(chosen)]
    return good, total


# -- engine -------------------------------------------------------------------


@dataclasses.dataclass
class _RuleRuntime:
    """Mutable evaluator state for one rule."""

    rule: SLORule
    samples: deque                       # (t, bad, total) cumulative
    state: str = STATE_OK
    since: float = 0.0                   # when the current state began
    pending_since: float = 0.0
    resolved_at: float = 0.0
    burns: Dict[str, Dict[str, float]] = dataclasses.field(
        default_factory=dict)
    transitions: List[dict] = dataclasses.field(default_factory=list)
    last_bad: float = 0.0
    last_total: float = 0.0


class HealthEngine:
    """Evaluate SLO rules on a cadence; drive alert state machines.

    ``registries``: the metric registries to read (None = the live
    process-global default registry, resolved per tick so registry
    resets in tests are honored). ``time_scale`` multiplies every rule
    duration; ``interval_s`` is the evaluator cadence (real seconds,
    never scaled — callers pick a cadence matching their scale).
    ``clock`` is injectable for deterministic tests.

    Thread-safe: ``tick()`` may be called from the background thread and
    on demand (the ``/debug/health`` handler does) under one lock.
    """

    def __init__(self, rules: Sequence[SLORule], *,
                 registries: Optional[Sequence] = None,
                 interval_s: float = 10.0, time_scale: float = 1.0,
                 clock: Optional[Callable[[], float]] = None,
                 snapshot_every_s: float = 30.0,
                 max_samples: int = 4096, store=None):
        if time_scale <= 0:
            raise ValueError(f"time_scale must be > 0, got {time_scale}")
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.rules = list(rules)
        self._registries = list(registries) if registries is not None else None
        self.interval_s = interval_s
        self.time_scale = time_scale
        self.snapshot_every_s = snapshot_every_s
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_snapshot: Optional[float] = None
        # With a TimeSeriesStore armed, each rule's cumulative
        # (t, bad, total) window lives in a store-owned deque
        # (store.slo_series) instead of a parallel private one: same
        # object type, same maxlen, identical evaluator semantics — but
        # the history rides the store's snapshot/restore, so burn-rate
        # windows survive a warm restart.
        self._store = store
        self._runtimes = {
            r.name: _RuleRuntime(
                rule=r,
                samples=(store.slo_series(r.name,
                                          self._retention(r, max_samples))
                         if store is not None else
                         deque(maxlen=self._retention(r, max_samples))))
            for r in self.rules
        }

    def _retention(self, rule: SLORule, cap: int) -> int:
        longest = max((w.long_s for w in rule.windows), default=0.0)
        need = int(longest * self.time_scale / self.interval_s) + 8
        return max(16, min(cap, need))

    def _resolve_registries(self):
        if self._registries is not None:
            return self._registries
        return [_metrics.default_registry()]

    # -- evaluation ----------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> dict:
        """One evaluation pass; returns :meth:`health`. Safe to call
        concurrently with the background thread."""
        with self._lock:
            t = self._clock() if now is None else now
            families = _doc_map(self._resolve_registries())
            sm = get_slo_metrics() if _metrics.enabled() else None
            for rt in self._runtimes.values():
                self._eval_rule(rt, families, t, sm)
            if self.snapshot_every_s and (
                    self._last_snapshot is None
                    or t - self._last_snapshot >= self.snapshot_every_s):
                self._last_snapshot = t
                try:
                    get_flight_recorder().snapshot_registries(
                        self._resolve_registries())
                except Exception:  # noqa: BLE001 — snapshots are best-effort
                    pass
            return self._health_locked(t)

    def _sample(self, rule: SLORule, families) -> Tuple[float, float]:
        if rule.kind == "availability":
            return (_counter_sum(families, rule.bad),
                    _counter_sum(families, rule.total))
        good, total = _hist_good_total(families, rule.histogram,
                                       rule.threshold_s)
        return total - good, total

    @staticmethod
    def _window_delta(samples, t: float, window: float
                      ) -> Tuple[float, float]:
        """(bad delta, total delta) between now and the newest sample at
        least ``window`` old (falling back to the oldest sample while
        history is still shorter than the window)."""
        latest = samples[-1]
        anchor = samples[0]
        for s in samples:
            if s[0] <= t - window:
                anchor = s
            else:
                break
        return latest[1] - anchor[1], latest[2] - anchor[2]

    def _burn(self, rt: _RuleRuntime, t: float, window: float) -> float:
        bad_d, total_d = self._window_delta(rt.samples, t, window)
        if total_d <= 0:
            return 0.0
        err_rate = max(0.0, bad_d) / total_d
        return err_rate / rt.rule.error_budget

    def _eval_rule(self, rt: _RuleRuntime, families, t: float, sm):
        rule = rt.rule
        bad, total = self._sample(rule, families)
        rt.last_bad, rt.last_total = bad, total
        # Retention is sized for interval_s cadence, but tick() also runs
        # on demand (every /debug/health request): faster-than-cadence
        # ticks REPLACE the newest sample instead of appending, or a 1 Hz
        # health poller would evict the history the 6 h window needs and
        # silently shrink every long window to minutes.
        if rt.samples and t - rt.samples[-1][0] < 0.5 * self.interval_s:
            rt.samples[-1] = (t, bad, total)
        else:
            rt.samples.append((t, bad, total))
        breach = False
        burns: Dict[str, Dict[str, float]] = {}
        for w in rule.windows:
            bs = self._burn(rt, t, w.short_s * self.time_scale)
            bl = self._burn(rt, t, w.long_s * self.time_scale)
            burns[w.label()] = {"short": bs, "long": bl,
                                "threshold": w.burn}
            if bs >= w.burn and bl >= w.burn:
                breach = True
            if sm is not None:
                sm.burn_rate.set(bs, rule=rule.name,
                                 window=_dur(w.short_s))
                sm.burn_rate.set(bl, rule=rule.name, window=_dur(w.long_s))
        rt.burns = burns
        self._advance(rt, breach, t, sm)

    def _advance(self, rt: _RuleRuntime, breach: bool, t: float, sm):
        rule = rt.rule
        state = rt.state
        new = state
        if breach:
            if state in (STATE_OK, STATE_RESOLVED):
                new = STATE_PENDING
                rt.pending_since = t
            elif state == STATE_PENDING and \
                    t - rt.pending_since >= rule.for_s * self.time_scale:
                new = STATE_FIRING
        else:
            if state == STATE_PENDING:
                new = STATE_OK
            elif state == STATE_FIRING:
                new = STATE_RESOLVED
                rt.resolved_at = t
            elif state == STATE_RESOLVED and \
                    t - rt.resolved_at >= rule.resolve_hold_s * self.time_scale:
                new = STATE_OK
        if new != state:
            rt.state = new
            rt.since = t
            tr = {"t": t, "from": state, "to": new,
                  "burns": {k: round(v["short"], 3)
                            for k, v in rt.burns.items()}}
            rt.transitions.append(tr)
            del rt.transitions[:-64]  # bounded history per rule
            record_event("slo.transition", rule=rule.name, **{
                "from": state, "to": new, "burns": tr["burns"]})
            if sm is not None:
                sm.transitions_total.inc(rule=rule.name, to=new)
        if sm is not None:
            sm.state.set(_STATE_NUM[rt.state], rule=rule.name)

    # -- rendering -----------------------------------------------------------

    def health(self) -> dict:
        with self._lock:
            return self._health_locked(self._clock())

    def _health_locked(self, t: float) -> dict:
        worst = STATE_OK
        rules = []
        for rt in self._runtimes.values():
            rule = rt.rule
            if _STATE_NUM[rt.state] > _STATE_NUM[worst]:
                worst = rt.state
            rules.append({
                "name": rule.name, "kind": rule.kind, "state": rt.state,
                "objective": rule.objective,
                "error_budget": rule.error_budget,
                "threshold_s": rule.threshold_s,
                "since": rt.since,
                "bad": rt.last_bad, "total": rt.last_total,
                "windows": [
                    dict(dataclasses.asdict(w),
                         **rt.burns.get(w.label(),
                                        {"short": 0.0, "long": 0.0}))
                    for w in rule.windows
                ],
                "for_s": rule.for_s,
                "transitions": list(rt.transitions[-16:]),
            })
        return {"status": worst, "time_scale": self.time_scale,
                "interval_s": self.interval_s, "evaluated_at": t,
                "rules": rules}

    def render_text(self) -> str:
        h = self.health()
        lines = [f"status: {h['status']}"]
        for r in h["rules"]:
            burn = " ".join(
                f"burn({_dur(w['short_s'])}/{_dur(w['long_s'])})="
                f"{w['short']:.2f}/{w['long']:.2f}(x{w['burn']:g})"
                for w in r["windows"])
            lines.append(
                f"{r['name']:<28} {r['state'].upper():<9} "
                f"objective={r['objective']:g} bad={r['bad']:g}/"
                f"{r['total']:g} {burn}")
        return "\n".join(lines) + "\n"

    def states(self) -> Dict[str, str]:
        with self._lock:
            return {name: rt.state for name, rt in self._runtimes.items()}

    # -- background thread ---------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "HealthEngine":
        if self.running:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="slo-evaluator")
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the evaluator must survive
                pass           # a transient bad sample; next tick retries

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# -- process-default engine (UIServer's /health reads it) ---------------------

_default_engine: Optional[HealthEngine] = None


def set_default_engine(engine: Optional[HealthEngine]):
    """Publish an engine as the process default (ModelServer does on
    start) so zero-config consumers — UIServer's /health page — can
    render current SLO states."""
    global _default_engine
    _default_engine = engine


def get_default_engine() -> Optional[HealthEngine]:
    return _default_engine


# -- CLI ----------------------------------------------------------------------


def check_rules_file(path: str, extra_known: Sequence[str] = ()
                     ) -> Tuple[int, List[str]]:
    """Validate one rules file; returns (n valid rules, errors)."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, ValueError) as e:
        return 0, [f"cannot read {path}: {e}"]
    rules, errors = validate_rules_doc(
        doc, known=known_metric_names(extra_known))
    return len(rules), errors


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.observability.slo",
        description="SLO rule-file tools")
    ap.add_argument("--check", metavar="RULES_JSON", required=True,
                    help="validate a rules file offline; non-zero exit on "
                         "any problem")
    ap.add_argument("--known", default="",
                    help="comma-separated extra metric names to accept "
                         "(user-registered families)")
    args = ap.parse_args(argv)
    extra = [n for n in args.known.split(",") if n]
    n, errors = check_rules_file(args.check, extra_known=extra)
    for e in errors:
        print(f"error: {e}", file=sys.stderr)
    if errors:
        print(f"{args.check}: {len(errors)} problem(s)", file=sys.stderr)
        return 1
    print(f"ok: {n} rule(s) valid")
    return 0


if __name__ == "__main__":
    sys.exit(main())
