"""Request ledger: one always-on lifecycle record per served request.

Metrics aggregate, spans sample, the flight ring evicts — none of them
answers "what happened to request X?" hours later. This module does: a
bounded in-memory ring of compact per-request lifecycle records, cheap
enough (~a dict build + deque append under one lock) to record for
EVERY request the admission plane sees, indexed by correlation id so
``GET /debug/requests/<correlation-id>`` resolves in O(1).

One record carries the whole story of one request:

- identity: correlation id, plane (``predict`` | ``generation``),
  model/version, priority class, tenant;
- admission: ``admitted`` or ``shed:<reason>`` — sheds get records too,
  so "why did my request 429?" is answerable after the fact;
- timings: start/end (wall-anchored), end-to-end latency, queue wait,
  TTFT, prefill seconds, decode-step count + decode-seconds rollup;
- placement: decode slot / batch rows + bucket (stamped post-hoc by the
  ParallelInference worker for predict, by the scheduler for
  generation);
- caching: a ``cache`` field (``hit`` / ``stale`` / ``miss`` /
  ``bypass`` / ``prefix_hit``) annotated by the response cache and the
  prefix-KV store, plus ``prefix_len`` on prefix hits — so
  ``/debug/requests`` answers "was request X served from cache, and
  how much prefill did it skip?";
- outcome: ``ok`` / ``error`` / ``shed`` / ``preempted`` / ``deadline``
  / ``cancelled`` / ``rejected``, HTTP status, finish reason, deadline
  slack (negative = the deadline was missed);
- ``trace_retained``: the tail sampler's retention reason when this
  request's span tree was kept in the tracer ring (None = ledger record
  only — the common case for fast, healthy traffic).

The ledger drives **tail-based trace sampling** (trace.py
:class:`~deeplearning4j_tpu.observability.trace.TailSampler`):
``begin()`` stages the request's spans, ``finish()`` feeds the
retention policy the outcome + latency and stamps the decision on the
record. Everything is scrapeable: ``reqlog_records_total{plane,
outcome}``, ``reqlog_evictions_total``, ``reqlog_open_requests``, and
``trace_retained_total{reason}`` / ``trace_retained_spans_total`` /
``reqlog_trace_dropped_total`` from the sampler's decisions.

Federation: the per-worker telemetry snapshot embeds a bounded recent
window of records (``recent()``), so the supervisor-side
``GET /cluster/debug/requests/<id>`` finds a request on whichever
worker served it; the sentinel's incident bundles embed
:func:`postmortem` — the worst requests of the anomaly window with
their retained span trees.

``set_ledger_enabled(False)`` is the kill switch ``bench.py reqtrace``
prices the plane with (begin/annotate/finish become no-ops).

Stdlib only; safe to import from any layer.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional

from deeplearning4j_tpu.observability import metrics as _metrics
from deeplearning4j_tpu.observability import trace as _trace

# outcomes rendered in HELP text / validated nowhere on purpose: the
# ledger records what the serving layer says happened; the bounded
# vocabulary below is what the built-in planes emit
OUTCOMES = ("ok", "error", "failed", "shed", "preempted", "deadline",
            "cancelled", "rejected")

ENV_REQLOG_CAPACITY = "DL4J_TPU_REQLOG_CAPACITY"


class ReqLogMetrics:
    """The ledger + tail-retention exposition families (on the process
    default registry, like the sentinel's)."""

    def __init__(self, registry: Optional[_metrics.MetricsRegistry] = None):
        r = registry if registry is not None else _metrics.default_registry()
        self.registry = r
        self.records_total = r.counter(
            "reqlog_records_total",
            "Request-ledger lifecycle records finished, by serving plane "
            "and outcome (ok | error | failed | shed | preempted | "
            "deadline | cancelled | rejected).", ("plane", "outcome"))
        self.evictions_total = r.counter(
            "reqlog_evictions_total",
            "Ledger records evicted from the bounded ring (oldest "
            "first); their staged spans, if any, are dropped with "
            "them.")
        self.open_requests = r.gauge(
            "reqlog_open_requests",
            "Ledger records currently open (begun, not yet finished).")
        self.trace_retained_total = r.counter(
            "trace_retained_total",
            "Requests whose staged span tree the tail sampler KEPT in "
            "the tracer ring, by retention reason (outcome name | slow "
            "| sampled).", ("reason",))
        self.trace_retained_spans_total = r.counter(
            "trace_retained_spans_total",
            "Spans promoted from tail-sampling staging into the tracer "
            "ring across all retained requests.")
        self.trace_dropped_total = r.counter(
            "reqlog_trace_dropped_total",
            "Requests whose staged spans were dropped at completion "
            "(fast, healthy, and not the deterministic 1-in-N sample).")


_reqlog_metrics: Optional[ReqLogMetrics] = None
_rm_lock = threading.Lock()


def get_reqlog_metrics() -> ReqLogMetrics:
    global _reqlog_metrics
    if _reqlog_metrics is None:
        with _rm_lock:
            if _reqlog_metrics is None:
                _reqlog_metrics = ReqLogMetrics()
    return _reqlog_metrics


def _drop_reqlog_metrics():
    global _reqlog_metrics
    _reqlog_metrics = None


_metrics.register_reset_hook(_drop_reqlog_metrics)


class RequestLedger:
    """Bounded ring of per-request lifecycle records, indexed by
    correlation id (the newest record wins the index — a retry reusing
    its id is a new server-side pass; the older pass stays in the ring
    until evicted)."""

    def __init__(self, capacity: int = 2048, *,
                 sampler: Optional[_trace.TailSampler] = None,
                 tracer: Optional[_trace.Tracer] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.sampler = sampler
        # where this ledger's retained spans land; None = the process
        # ring. A router running in the same process as its backends
        # (tests, benches) needs its OWN ring or their spans interleave.
        self.tracer = tracer
        self._lock = threading.Lock()
        self._ring: deque = deque()
        self._index: Dict[str, dict] = {}
        self._open = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- lifecycle -----------------------------------------------------------

    def begin(self, cid: str, *, plane: str, model: str,
              priority: Optional[str] = None, tenant: Optional[str] = None,
              **fields) -> Optional[dict]:
        """Open one record (and stage the request's spans for tail
        sampling); returns the live record, or None when the ledger is
        disabled. Extra ``fields`` merge into the record.

        A ``begin`` for a cid whose record is still OPEN merges into it
        instead of opening a second one — the HTTP layer begins the
        record before its root span opens and the scheduler's submit
        enriches the same record moments later. A cid whose previous
        record already finished gets a fresh record (a client retry is
        a new server-side pass; the index points at the newest)."""
        if not _ENABLED:
            return None
        with self._lock:
            prev = self._index.get(cid)
            if prev is not None and prev.get("state") == "open":
                for k, v in dict(priority=priority, tenant=tenant,
                                 **fields).items():
                    if v is not None:
                        prev[k] = v
                rec, evicted, open_now = prev, None, self._open
            else:
                # t_start is the wall-anchored monotonic clock (interval
                # math); t_wall is the true wall clock of arrival — trace
                # export needs an absolute arrival time that survives
                # cross-process merge (federated export sorts workers'
                # records by it)
                rec = {"cid": cid, "plane": plane, "model": model,
                       "priority": priority, "tenant": tenant,
                       "state": "open", "t_start": _trace.now(),
                       "t_wall": time.time(),
                       "t_end": None, "latency_s": None, "outcome": None,
                       "status": None, "admission": None,
                       "trace_retained": None}
                rec.update(fields)
                evicted = None
                if len(self._ring) >= self.capacity:
                    evicted = self._ring.popleft()
                    if self._index.get(evicted["cid"]) is evicted:
                        del self._index[evicted["cid"]]
                    if evicted.get("state") == "open":
                        self._open -= 1
                self._ring.append(rec)
                self._index[cid] = rec
                self._open += 1
                open_now = self._open
        m = _reqlog_metrics_or_none()
        if m is not None:
            if evicted is not None:
                m.evictions_total.inc()
            m.open_requests.set(open_now)
        if self.sampler is not None:
            if evicted is not None and evicted.get("state") == "open":
                # its spans can never be decided through finish() now
                self.sampler.discard(evicted["cid"])
            self.sampler.begin(cid)
        return rec

    def annotate(self, cid: str, **fields) -> None:
        """Merge fields into an open record (no-op for unknown ids and
        finished records — a late annotation must not mutate a record
        whose outcome is already sealed; telemetry never fails the
        serving path)."""
        if not _ENABLED:
            return
        with self._lock:
            rec = self._index.get(cid)
            if rec is not None and rec.get("state") == "open":
                rec.update(fields)

    def finish(self, cid: str, *, outcome: str,
               status: Optional[int] = None, **fields) -> Optional[dict]:
        """Close one record: stamp outcome/latency/deadline-slack, run
        the tail sampler's retention decision, count the metrics.
        Returns the record (None for unknown ids / disabled ledger)."""
        if not _ENABLED:
            return None
        t_end = _trace.now()
        with self._lock:
            rec = self._index.get(cid)
            if rec is None or rec.get("state") != "open":
                return None
            rec.update(fields)
            rec["state"] = "done"
            rec["outcome"] = outcome
            if status is not None:
                rec["status"] = status
            rec["t_end"] = t_end
            latency = max(0.0, t_end - rec["t_start"])
            rec["latency_s"] = round(latency, 6)
            deadline_s = rec.get("deadline_s")
            if deadline_s is not None:
                rec["deadline_slack_s"] = round(float(deadline_s) - latency,
                                                6)
            self._open -= 1
            open_now = self._open
        reason, n_spans = (None, 0)
        if self.sampler is not None:
            reason, n_spans = self.sampler.finish(
                cid, outcome=outcome, latency_s=latency,
                tracer=self.tracer)
            with self._lock:
                rec["trace_retained"] = reason
        m = _reqlog_metrics_or_none()
        if m is not None:
            m.records_total.inc(plane=rec.get("plane", "?"), outcome=outcome)
            m.open_requests.set(open_now)
            if self.sampler is not None:
                if reason is not None:
                    m.trace_retained_total.inc(reason=reason)
                    m.trace_retained_spans_total.inc(n_spans)
                else:
                    m.trace_dropped_total.inc()
        sink = _USAGE_SINK
        if sink is not None:
            try:
                sink(dict(rec))
            except Exception:  # noqa: BLE001 — metering never fails serving
                pass
        return rec

    def record(self, cid: str, *, plane: str, model: str, outcome: str,
               status: Optional[int] = None, **fields) -> Optional[dict]:
        """One-shot begin+finish for requests that never opened a
        stream/slot (pre-submit sheds and validation rejects) — the
        admission outcome is still answerable by correlation id."""
        if self.begin(cid, plane=plane, model=model, **fields) is None:
            return None
        return self.finish(cid, outcome=outcome, status=status)

    def amend(self, cid: str, **fields) -> Optional[dict]:
        """Merge fields into a record regardless of state — post-hoc
        enrichment computed AFTER completion (a stitch-time critical
        path needs the backend's half, fetched on demand). Unlike
        ``annotate`` this never gates on openness; it must not be used
        from the request path."""
        if not _ENABLED:
            return None
        with self._lock:
            rec = self._index.get(cid)
            if rec is None:
                return None
            rec.update(fields)
            return dict(rec)

    # -- read surface --------------------------------------------------------

    def get(self, cid: str) -> Optional[dict]:
        with self._lock:
            rec = self._index.get(cid)
            return dict(rec) if rec is not None else None

    def query(self, *, outcome: Optional[str] = None,
              tenant: Optional[str] = None, model: Optional[str] = None,
              plane: Optional[str] = None,
              min_latency_s: Optional[float] = None,
              limit: int = 100) -> List[dict]:
        """Newest-first filtered records (the ``/debug/requests``
        list). Open records match latency filters by their age so an
        in-flight straggler is findable while it hangs."""
        with self._lock:
            snap = list(self._ring)
        out: List[dict] = []
        now = _trace.now()
        for rec in reversed(snap):
            if outcome is not None and rec.get("outcome") != outcome:
                continue
            if tenant is not None and rec.get("tenant") != tenant:
                continue
            if model is not None and rec.get("model") != model:
                continue
            if plane is not None and rec.get("plane") != plane:
                continue
            if min_latency_s is not None:
                lat = rec.get("latency_s")
                if lat is None:
                    lat = max(0.0, now - rec.get("t_start", now))
                if lat < min_latency_s:
                    continue
            out.append(dict(rec))
            if len(out) >= max(1, int(limit)):
                break
        return out

    def recent(self, limit: int = 256) -> List[dict]:
        """Newest-first window for the federation snapshot."""
        with self._lock:
            snap = list(self._ring)[-max(1, int(limit)):]
        return [dict(r) for r in reversed(snap)]

    def export_trace(self, *, window_s: Optional[float] = None,
                     plane: Optional[str] = None,
                     model: Optional[str] = None,
                     limit: Optional[int] = None) -> dict:
        """Turn a ledger window into a replayable, payload-scrubbed
        trace (``GET /debug/requests?format=trace``) — see
        :func:`trace_from_records` for the row schema. ``window_s``
        keeps only requests that arrived within the trailing window;
        ``limit`` keeps the newest N arrivals."""
        with self._lock:
            snap = [dict(r) for r in self._ring]
        if window_s is not None:
            cutoff = time.time() - float(window_s)
            snap = [r for r in snap
                    if (r.get("t_wall") or r.get("t_start", 0.0)) >= cutoff]
        if limit is not None:
            snap = snap[-max(1, int(limit)):]
        return trace_from_records(snap, plane=plane, model=model)

    def describe(self) -> dict:
        with self._lock:
            return {"capacity": self.capacity, "records": len(self._ring),
                    "open": self._open,
                    "staged": (self.sampler.staged_count()
                               if self.sampler is not None else 0)}


# -- trace export -------------------------------------------------------------

# the ONLY keys a trace row may carry: identity + timing + shape, never
# payload bytes. ``payload_shape`` is a shape descriptor (list of ints
# for a single array, {name: shape} for dict features, [prompt_len] for
# generation); replay synthesizes inputs from it.
TRACE_ROW_FIELDS = ("plane", "model", "arrival_offset_s", "priority",
                    "tenant", "payload_shape", "deadline_s", "stream",
                    "max_new_tokens")

TRACE_VERSION = 1


def trace_from_records(records: Iterable[dict], *,
                       plane: Optional[str] = None,
                       model: Optional[str] = None) -> dict:
    """Build a replayable trace from ledger records (this process's
    ring, or a cross-worker merge from federation snapshots). Rows are
    sorted by absolute arrival wall-time and reduced to
    :data:`TRACE_ROW_FIELDS` — payload bytes never leave the ledger;
    the replay driver synthesizes inputs from ``payload_shape``.
    Arrival offsets are relative to the first kept arrival, so a trace
    is position-independent and can be replayed any time, anywhere."""
    kept = []
    for rec in records:
        if plane is not None and rec.get("plane") != plane:
            continue
        if model is not None and rec.get("model") != model:
            continue
        t = rec.get("t_wall")
        if t is None:
            t = rec.get("t_start")
        if t is None:
            continue
        kept.append((float(t), rec))
    kept.sort(key=lambda pair: pair[0])
    t0 = kept[0][0] if kept else None
    rows: List[dict] = []
    for t, rec in kept:
        shape = rec.get("payload_shape")
        if shape is None and rec.get("prompt_len") is not None:
            shape = [int(rec["prompt_len"])]
        row = {"plane": rec.get("plane"), "model": rec.get("model"),
               "arrival_offset_s": round(t - t0, 6),
               "priority": rec.get("priority"),
               "tenant": rec.get("tenant"),
               "payload_shape": shape,
               "deadline_s": rec.get("deadline_s"),
               "stream": bool(rec.get("stream", False))}
        if rec.get("max_new_tokens") is not None:
            row["max_new_tokens"] = int(rec["max_new_tokens"])
        rows.append(row)
    return {"version": TRACE_VERSION, "kind": "dl4j_tpu_trace",
            "t0_wall": t0, "count": len(rows),
            "duration_s": (round(kept[-1][0] - t0, 6) if kept else 0.0),
            "rows": rows}


# -- process-global ledger ----------------------------------------------------

_LEDGER: Optional[RequestLedger] = None
_ledger_lock = threading.Lock()
_ENABLED = True
_USAGE_SINK: Optional[Callable[[dict], None]] = None


def set_usage_sink(fn: Optional[Callable[[dict], None]]) -> None:
    """Install ``fn(sealed_record)`` to receive every finished ledger
    record (the usage meter's feed — both serving planes finish through
    the ledger, so metering sees predict and generation uniformly).
    One sink per process; None uninstalls. The sink runs outside the
    ledger lock and its exceptions are swallowed."""
    global _USAGE_SINK
    _USAGE_SINK = fn


def get_usage_sink() -> Optional[Callable[[dict], None]]:
    return _USAGE_SINK


def set_ledger_enabled(flag: bool) -> None:
    """Kill switch for the always-on ledger + tail-staging plane (the
    ``bench.py reqtrace`` gate prices it against this)."""
    global _ENABLED
    _ENABLED = bool(flag)


def ledger_enabled() -> bool:
    return _ENABLED


def get_request_ledger(create: bool = False) -> Optional[RequestLedger]:
    """The process request ledger; ``create=True`` makes one when none
    exists (capacity from ``DL4J_TPU_REQLOG_CAPACITY``, default 2048)
    and installs the process tail sampler so staged spans route."""
    global _LEDGER
    with _ledger_lock:
        if _LEDGER is None and create:
            import os

            try:
                cap = int(os.environ.get(ENV_REQLOG_CAPACITY) or 2048)
            except ValueError:
                cap = 2048
            _LEDGER = RequestLedger(
                cap, sampler=_trace.get_tail_sampler(create=True))
        return _LEDGER


def set_request_ledger(ledger: Optional[RequestLedger]) -> None:
    global _LEDGER
    with _ledger_lock:
        _LEDGER = ledger


def _reqlog_metrics_or_none() -> Optional[ReqLogMetrics]:
    try:
        if not _metrics.enabled():
            return None
        return get_reqlog_metrics()
    except Exception:  # noqa: BLE001 — metrics never fail the ledger
        return None


def request_index(limit: int = 256) -> List[dict]:
    """This process's recent ledger records, or [] — what the federation
    snapshot embeds (never creates a ledger as a side effect, never
    raises)."""
    ledger = get_request_ledger()
    if ledger is None:
        return []
    try:
        return ledger.recent(limit)
    except Exception:  # noqa: BLE001 — telemetry never fails the caller
        return []


def request_detail(cid: str) -> Optional[dict]:
    """One request's ledger record + retained span tree (Chrome-format
    included) — the ``/debug/requests/<id>`` body. None when the id is
    unknown to both the ledger and the tracer ring."""
    ledger = get_request_ledger()
    rec = ledger.get(cid) if ledger is not None else None
    spans = _trace.get_tracer().spans(trace_id=cid)
    if rec is None and not spans:
        return None
    return {
        "record": rec,
        "trace": {
            "retained": bool(spans),
            "reason": rec.get("trace_retained") if rec is not None else None,
            "span_count": len(spans),
            "spans": [s.to_json() for s in spans],
            "chrome": (_trace.to_chrome_trace(spans) if spans else None),
        },
    }


def postmortem(window_s: float = 180.0, limit: int = 8) -> dict:
    """The worst requests of the trailing window, retained span trees
    attached — what the sentinel's incident bundles embed (bad outcomes
    first, then by latency, newest-first tiebreak). Never raises."""
    try:
        ledger = get_request_ledger()
        if ledger is None:
            return {"window_seconds": window_s, "count": 0, "requests": []}
        cutoff = _trace.now() - float(window_s)
        with ledger._lock:
            rows = [dict(r) for r in ledger._ring
                    if (r.get("t_end") or r.get("t_start", 0.0)) >= cutoff]
        bad = frozenset(("error", "failed", "shed", "preempted", "deadline"))
        rows.sort(key=lambda r: (
            r.get("outcome") in bad, r.get("latency_s") or 0.0,
            r.get("t_start", 0.0)), reverse=True)
        rows = rows[:max(1, int(limit))]
        tracer = _trace.get_tracer()
        out = []
        for rec in rows:
            spans = tracer.spans(trace_id=rec["cid"])
            out.append({"record": rec,
                        "spans": [s.to_json() for s in spans]})
        return {"window_seconds": window_s, "count": len(out),
                "requests": out}
    except Exception:  # noqa: BLE001 — a bundle artifact, never a crash
        return {"window_seconds": window_s, "count": 0, "requests": [],
                "error": "postmortem failed"}


__all__ = [
    "OUTCOMES",
    "TRACE_ROW_FIELDS",
    "TRACE_VERSION",
    "ReqLogMetrics",
    "RequestLedger",
    "trace_from_records",
    "get_reqlog_metrics",
    "get_request_ledger",
    "ledger_enabled",
    "postmortem",
    "request_detail",
    "request_index",
    "set_ledger_enabled",
    "set_request_ledger",
]
