"""Partition-spec policy tables (↔ the ENTIRE L5 scaleout layer of the
reference, SURVEY §2.6).

ref: ParallelWrapper (P1 param averaging), gradient sharing
(EncodedGradientsAccumulator/EncodingHandler, P2), SharedTrainingMaster +
VoidParameterServer over Aeron (P4/P5). On TPU none of that user-space
machinery exists: parallelism is a *placement policy* — a pytree of
NamedShardings handed to pjit — and XLA emits the ICI/DCN collectives.
The replacement table (SURVEY §2.6):

- P1/P2/P3/P4 (data parallel, any flavour)  → batch P('data'), params
  replicated; gradient all-reduce inserted by XLA (exact, synchronous —
  supersedes threshold-compressed async sharing).
- P11 (FSDP/ZeRO)                           → params/opt-state sharded on
  'fsdp' axis; all-gather on use, reduce-scatter on grads, from the same
  spec table.
- P7 (tensor parallel)                      → per-layer specs on 'model'
  axis (dense kernels alternating column/row split).
- P9 (sequence parallel / ring attention)   → 'seq' axis (kernels/ring_attention).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.runtime.device import (
    FSDP_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    data_like_axes,
)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_spec(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over all data-like axes present."""
    axes = data_like_axes(mesh)
    return NamedSharding(mesh, P(axes if axes else None))


def data_parallel_plan(mesh: Mesh):
    """P1–P4 equivalent: replicated state, batch-sharded data.

    Returns (state_sharding, batch_sharding) usable as pjit prefix pytrees
    for (TrainState, batch dict).
    """
    return replicated(mesh), batch_spec(mesh)


def _fsdp_spec_for(shape, fsdp_size: int, min_shard_elems: int) -> P:
    """Shard the largest divisible dim on the fsdp axis; tiny params stay
    replicated (same policy XLA's weight-update sharding paper uses —
    sharding a 10-element bias costs more in collectives than it saves)."""
    if not shape or int(np.prod(shape)) < min_shard_elems:
        return P()
    # Prefer the largest dimension divisible by the axis size.
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if shape[i] % fsdp_size == 0 and shape[i] >= fsdp_size:
            spec = [None] * len(shape)
            spec[i] = FSDP_AXIS
            return P(*spec)
    return P()


def fsdp_plan(mesh: Mesh, params_template: Any, *, min_shard_elems: int = 1024):
    """P11 equivalent (ZeRO-3-style): per-leaf param sharding pytree.

    Apply the same sharding to optimizer state by tree-prefix (opt state
    mirrors params structure under every updater in train/updaters.py).
    Returns (params_sharding_tree, batch_sharding).
    """
    fsdp_size = dict(zip(mesh.axis_names, mesh.devices.shape)).get(FSDP_AXIS, 1)
    if fsdp_size == 1:
        return jax.tree_util.tree_map(lambda _: replicated(mesh), params_template), batch_spec(mesh)
    shardings = jax.tree_util.tree_map(
        lambda p: NamedSharding(mesh, _fsdp_spec_for(p.shape, fsdp_size, min_shard_elems)),
        params_template,
    )
    return shardings, batch_spec(mesh)


def train_state_sharding(mesh: Mesh, ts_template, params_sharding=None):
    """Build a sharding pytree matching a TrainState.

    params follow ``params_sharding`` (default replicated); optimizer state
    mirrors the params sharding (every updater in train/updaters.py keeps
    state as {name: params-shaped tree} — exactly the ZeRO trick: sharded
    params ⇒ sharded Adam m/v for free); model_state, step, rng replicated.
    """
    rep = replicated(mesh)
    if params_sharding is None:
        return rep  # prefix pytree: everything replicated

    from deeplearning4j_tpu.train.trainer import TrainState

    def mirror(tree):
        """Apply params' per-leaf shardings to a params-shaped tree."""
        ps_leaves = jax.tree_util.tree_flatten(params_sharding)[0]
        t_leaves, t_def = jax.tree_util.tree_flatten(tree)
        if len(ps_leaves) == len(t_leaves):
            return jax.tree_util.tree_unflatten(t_def, ps_leaves)
        return jax.tree_util.tree_map(lambda _: rep, tree)

    if isinstance(ts_template.opt_state, dict):
        opt_sh = {k: mirror(v) for k, v in ts_template.opt_state.items()}
    else:
        opt_sh = jax.tree_util.tree_map(lambda _: rep, ts_template.opt_state)

    return TrainState(
        params=params_sharding,
        model_state=jax.tree_util.tree_map(lambda _: rep, ts_template.model_state),
        opt_state=opt_sh,
        step=rep,
        rng=rep,
    )


# --- tensor-parallel layer spec table (P7) ---------------------------------

# Megatron-style alternating split for transformer blocks: qkv/up-proj
# column-split (output dim on 'model'), attn-out/down-proj row-split
# (input dim on 'model'); embeddings vocab-split. Used by models/bert.py.
TP_RULES = [
    # (param path substring, PartitionSpec factory by rank)
    ("attention/qkv", lambda r: P(*([None] * (r - 1) + [MODEL_AXIS]))),
    ("attention/out", lambda r: P(*([MODEL_AXIS] + [None] * (r - 1)))),
    ("mlp/up", lambda r: P(*([None] * (r - 1) + [MODEL_AXIS]))),
    ("mlp/down", lambda r: P(*([MODEL_AXIS] + [None] * (r - 1)))),
    ("embedding", lambda r: P(MODEL_AXIS, *([None] * (r - 1)))),
]


def tp_spec_for_path(path: str, rank: int) -> P:
    for sub, factory in TP_RULES:
        if sub in path:
            return factory(rank)
    return P()


# Megatron split table keyed on the framework's own param names (models/bert.py
# layout): qkv & FFN-up column-split, attn-out & FFN-down row-split, word
# embedding vocab-split. Biases of column-split weights follow the split.
_BERT_TP_TABLE = {
    "Wq": -1, "Wk": -1, "Wv": -1, "W1": -1,   # column (last dim on 'model')
    "bq": 0, "bk": 0, "bv": 0, "b1": 0,        # 1-d biases of column splits
    "Wo": 0, "W2": 0,                           # row (first dim on 'model')
    "word": 0,                                  # vocab split
}


def tensor_parallel_plan(mesh: Mesh, params_template: Any, *,
                         table: Optional[dict] = None):
    """P7 equivalent: per-leaf Megatron-style sharding tree for transformer
    params (matches models/bert.py param naming). Leaves whose split dim is
    not divisible by the 'model' axis size stay replicated — GSPMD then
    still produces a correct program, just without that split.

    Returns (params_sharding_tree, batch_sharding).
    """
    table = table if table is not None else _BERT_TP_TABLE
    tp_size = dict(zip(mesh.axis_names, mesh.devices.shape)).get(MODEL_AXIS, 1)

    def spec_for(path, leaf):
        if tp_size == 1:
            return NamedSharding(mesh, P())
        key = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        dim = table.get(key)
        if dim is None:
            return NamedSharding(mesh, P())
        dim = dim % leaf.ndim
        if leaf.shape[dim] % tp_size != 0:
            return NamedSharding(mesh, P())
        spec = [None] * leaf.ndim
        spec[dim] = MODEL_AXIS
        return NamedSharding(mesh, P(*spec))

    shardings = jax.tree_util.tree_map_with_path(spec_for, params_template)
    return shardings, batch_spec(mesh)


def expert_parallel_plan(mesh: Mesh, params_template: Any):
    """P10 equivalent: MoE expert-stacked weights sharded on the 'expert'
    axis (falling back to 'model' when no expert axis is in the mesh).

    Every param whose tree path contains an MoEBlock layer and whose
    leading dim is the expert count shards that dim; everything else
    replicates. GSPMD then turns the dispatch/combine einsums of
    nn/layers/moe.py into the all-to-all collectives — the expert
    "parameter server" without a server. Returns
    (params_sharding_tree, batch_sharding).
    """
    from deeplearning4j_tpu.runtime.device import EXPERT_AXIS

    axis = EXPERT_AXIS if EXPERT_AXIS in mesh.axis_names else (
        MODEL_AXIS if MODEL_AXIS in mesh.axis_names else None)
    rep = replicated(mesh)
    if axis is None:
        return jax.tree_util.tree_map(lambda _: rep, params_template), \
            batch_spec(mesh)
    size = mesh.shape[axis]

    def is_moe_group(node) -> bool:
        """Structural detection (names are user-chosen): an MoE param dict
        carries a router plus expert-stacked FFN weights whose leading dim
        is the expert count."""
        if not isinstance(node, dict):
            return False
        if not {"Wg", "W1", "W2", "b1", "b2"} <= set(node):
            return False
        w1 = node["W1"]
        return (getattr(w1, "ndim", 0) == 3
                and getattr(node["Wg"], "ndim", 0) == 2
                and w1.shape[0] == node["Wg"].shape[-1])

    def walk(node):
        if is_moe_group(node):
            out = {}
            for k, leaf in node.items():
                if k in ("W1", "W2", "b1", "b2") and leaf.shape[0] % size == 0:
                    out[k] = NamedSharding(
                        mesh, P(axis, *([None] * (leaf.ndim - 1))))
                else:
                    out[k] = rep
            return out
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        return rep

    return walk(params_template), batch_spec(mesh)
