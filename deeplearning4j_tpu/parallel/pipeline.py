"""Pipeline parallelism (P8): GPipe-style microbatched stage pipeline.

ref: ABSENT in the reference (SURVEY §2.6 P8) — DL4J has no pipeline
parallelism at all. This is a TPU-native capability line-item: stages are
laid out on a `stage` mesh axis, activations flow stage→stage over ICI via
`lax.ppermute`, and microbatches fill the pipeline GPipe-style. The whole
schedule — forward and the reverse (backward) pipeline jax.grad derives from
it — is ONE compiled XLA program; there is no host-side scheduler thread
(contrast: the reference's ParallelWrapper runs a Java thread per device
even for plain data parallelism).

Design (the scan/ppermute pipeline from the public scaling-book recipe):

- Stage parameters are *stacked* on a leading axis of size S sharded over
  `stage` — each device holds its own stage's slice (this is also exactly
  how repeated transformer blocks are naturally stored: a scanned-over
  params pytree).
- The per-device program runs T = n_micro + S - 1 ticks. On tick t, the
  device holding stage s computes microbatch m = t - s (bubble ticks
  compute garbage that is masked out), then the activation ring-shifts one
  hop toward stage s+1.
- Outputs are collected on the last stage and broadcast with a masked psum.

Bubble fraction is (S-1)/T — choose n_micro >> S. 1F1B-style scheduling
(smaller activation footprint) is a later optimization; memory here is
bounded by jax.checkpoint on the stage fn if needed.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.sequence import shard_map
from deeplearning4j_tpu.runtime.device import STAGE_AXIS, data_like_axes


def stack_stage_params(per_stage_params: list) -> Any:
    """Stack a list of identically-structured stage param pytrees along a
    new leading axis (the axis sharded over `stage`)."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *per_stage_params)


def stage_params_sharding(mesh: Mesh, stacked_params: Any):
    """NamedSharding pytree putting each stage's slice on its device."""
    def spec(leaf):
        return NamedSharding(mesh, P(STAGE_AXIS, *([None] * (leaf.ndim - 1))))

    return jax.tree_util.tree_map(spec, stacked_params)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    n_microbatches: int,
    stage_axis: str = STAGE_AXIS,
    checkpoint_stage: bool = True,
) -> jax.Array:
    """Run ``x`` [B, ...] through S pipelined stages; returns [B, ...out].

    ``stage_fn(params_s, x_mb) -> y_mb`` applies ONE stage to ONE
    microbatch; every stage must map activations of the same shape
    (classic GPipe restriction for the stacked layout). B must divide into
    ``n_microbatches`` equal microbatches.

    Differentiable: jax.grad through this runs the reverse pipeline
    (ppermute transposes to the opposite ring direction).
    """
    if stage_axis not in mesh.axis_names:
        # No stage axis: plain sequential scan over stages (single device).
        def seq_step(h, p):
            return stage_fn(p, h), None

        out, _ = lax.scan(seq_step, x, stacked_params)
        return out

    n_stages = mesh.shape[stage_axis]
    b = x.shape[0]
    # Batch composes with data-like axes: each data-replica pipelines only
    # its own batch shard (no duplicated FLOPs when mesh has data/fsdp axes).
    batch_axes = data_like_axes(mesh)
    dp = int(np.prod([mesh.shape[a] for a in batch_axes])) if batch_axes else 1
    if b % (dp * n_microbatches) != 0:
        raise ValueError(
            f"batch {b} not divisible into {n_microbatches} microbatches "
            f"per data shard (data-axis product {dp})")
    leading = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if leading != n_stages:
        raise ValueError(
            f"stacked params leading dim {leading} != stage axis size {n_stages}")
    fn = jax.checkpoint(stage_fn) if checkpoint_stage else stage_fn

    params_spec = jax.tree_util.tree_map(
        lambda leaf: P(stage_axis, *([None] * (leaf.ndim - 1))), stacked_params)
    x_spec = P(batch_axes if batch_axes else None)

    def per_device(params_local, x_all):
        # params_local: [1, ...] (this device's stage); x_all: this data
        # shard's batch (replicated across the stage axis).
        params_me = jax.tree_util.tree_map(lambda a: a[0], params_local)
        stage = lax.axis_index(stage_axis)
        b_local = x_all.shape[0]
        mb = b_local // n_microbatches
        xs = x_all.reshape(n_microbatches, mb, *x_all.shape[1:])
        n_ticks = n_microbatches + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            state, outputs = carry
            # stage 0 feeds microbatch t (repeats the last real microbatch
            # during drain ticks); other stages consume what arrived from
            # the previous stage.
            feed = xs[jnp.minimum(t, n_microbatches - 1)]
            x_in = jnp.where(stage == 0, feed, state)
            y = fn(params_me, x_in)
            m_out = t - (n_stages - 1)
            valid = (stage == n_stages - 1) & (m_out >= 0)
            outputs = lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(valid, y, lax.dynamic_index_in_dim(
                    outputs, jnp.maximum(m_out, 0), 0, keepdims=False)),
                jnp.maximum(m_out, 0), 0)
            state = lax.ppermute(y, stage_axis, perm)
            return (state, outputs), None

        out0 = jnp.zeros((n_microbatches, mb, *x_all.shape[1:]), x_all.dtype)
        # Bubble carry starts from real (finite) data, not zeros: the
        # masked-out garbage still flows through fn's VJP under jax.grad,
        # and 0-cotangent × inf/nan primal would poison param grads.
        (_, outputs), _ = lax.scan(
            tick, (xs[0], out0), jnp.arange(n_ticks))
        # Only the last stage holds real outputs; masked psum broadcasts.
        outputs = lax.psum(
            jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            stage_axis)
        return outputs.reshape(b_local, *x_all.shape[1:])

    fn_sm = shard_map(
        per_device, mesh,
        in_specs=(params_spec, x_spec),
        out_specs=x_spec,
    )
    return fn_sm(stacked_params, x)
