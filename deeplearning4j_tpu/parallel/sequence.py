"""Sequence/context parallelism: ring attention + Ulysses all-to-all (P9).

ref: the reference has NO sequence parallelism (SURVEY §2.6 P9 / §5.7) —
its longest-sequence story is truncated BPTT (a memory trick) and O(T²)
attention layers. These are the TPU-native capability line-items the build
adds as first-class:

- **Ring attention** (`ring_attention`): Q/K/V sharded on the sequence axis
  over a `seq` mesh axis laid on the ICI ring. Each device keeps its local
  Q shard and online-softmax state; KV (+key-mask) shards rotate around the
  ring via `lax.ppermute`, one hop per step, n_seq steps total. Peak memory
  per chip is O(T/n · D) and the ppermute of the *next* block is issued
  before the current block's compute so XLA's latency-hiding scheduler
  overlaps ICI transfer with MXU work. Causal blocks that are fully masked
  (source shard strictly in the future) skip their matmuls via lax.cond.
- **Ulysses** (`ulysses_attention`): all-to-all scatters heads / gathers
  sequence so each device runs *full-sequence* attention on H/n heads (the
  flash kernel applies locally), then the inverse all-to-all restores
  sequence sharding. Cheaper than the ring when heads ≥ seq shards; requires
  H % n == 0.

Both are pure functions of globally-shaped arrays, built on shard_map over a
Mesh — they compose with jit/pjit/grad like any other op, and the identical
program runs on the 8-virtual-CPU-device test mesh (SURVEY §4 test pattern)
and a real slice.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:  # jax >= 0.6: top-level export, replication check renamed check_vma
    from jax import shard_map as _shard_map
    _SHARD_MAP_KW = {"check_vma": False}
except ImportError:  # jax 0.4/0.5: experimental module, check_rep
    from jax.experimental.shard_map import shard_map as _shard_map
    _SHARD_MAP_KW = {"check_rep": False}


def shard_map(f, mesh, in_specs, out_specs):
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **_SHARD_MAP_KW)

from deeplearning4j_tpu.kernels.flash_attention import (
    flash_attention,
    reference_attention,
)
from deeplearning4j_tpu.runtime.device import SEQ_AXIS

_NEG_INF = -1e30


def _ring_partial(q, k, v, km, q_off, k_off, *, scale, causal, m, l, acc):
    """Online-softmax update of (m, l, acc) with one KV block.

    q [B,H,Tq,D], k/v [B,H,Tk,D], km [B,Tk] or None; q_off/k_off are the
    global sequence offsets of the blocks (for causal masking).
    """
    s = jnp.einsum("bhtd,bhsd->bhts", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    mask = jnp.ones(s.shape, bool)
    if km is not None:
        mask = mask & (km[:, None, None, :] > 0)
    if causal:
        t_idx = q_off + jnp.arange(q.shape[2])[:, None]
        s_idx = k_off + jnp.arange(k.shape[2])[None, :]
        mask = mask & (t_idx >= s_idx)[None, None]
    s = jnp.where(mask, s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[..., None]) * mask.astype(jnp.float32)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "bhts,bhsd->bhtd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def ring_attention(
    q, k, v, *, mesh: Mesh, causal: bool = False, scale: Optional[float] = None,
    key_mask=None, seq_axis: str = SEQ_AXIS,
):
    """Ring attention over the `seq` mesh axis. q/k/v [B,H,T,D] global.

    Sequence must divide evenly over the axis. Returns [B,H,T,D] with the
    same sequence sharding as the inputs.
    """
    if seq_axis not in mesh.axis_names:
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               key_mask=key_mask)
    n = mesh.shape[seq_axis]
    b, h, t, d = q.shape
    if t % n != 0:
        raise ValueError(f"seq len {t} not divisible by seq axis size {n}")
    scale = (d ** -0.5) if scale is None else scale
    has_mask = key_mask is not None
    chunk = t // n

    # Everything not on the seq axis is replicated from shard_map's view —
    # batch/model sharding composes outside via the enclosing pjit.
    qkv_spec = P(None, None, seq_axis, None)
    km_spec = P(None, seq_axis)

    def local(q_l, k_l, v_l, km_l):
        my = lax.axis_index(seq_axis)
        m0 = jnp.full((b, h, chunk), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, chunk), jnp.float32)
        a0 = jnp.zeros((b, h, chunk, d), jnp.float32)
        perm = [(i, (i + 1) % n) for i in range(n)]
        q_off = my * chunk

        def update(k_cur, v_cur, km_cur, i, m, l, acc):
            src = (my - i) % n  # who produced the block we currently hold
            k_off = src * chunk

            def compute(m, l, acc):
                return _ring_partial(
                    q_l, k_cur, v_cur, km_cur if has_mask else None,
                    q_off, k_off, scale=scale, causal=causal, m=m, l=l, acc=acc)

            if causal:
                # A block strictly in the future is fully masked: skip it.
                return lax.cond(
                    k_off > q_off + chunk - 1,
                    lambda m, l, acc: (m, l, acc),
                    compute, m, l, acc)
            return compute(m, l, acc)

        def step(carry, i):
            k_cur, v_cur, km_cur, m, l, acc = carry
            # Issue the rotation for the NEXT step first so ICI transfer
            # overlaps this step's matmuls. Only the mask actually in use
            # rides the ring.
            rot = (k_cur, v_cur, km_cur) if has_mask else (k_cur, v_cur)
            rot = jax.tree_util.tree_map(
                lambda x: lax.ppermute(x, seq_axis, perm), rot)
            k_nxt, v_nxt = rot[0], rot[1]
            km_nxt = rot[2] if has_mask else km_cur
            m, l, acc = update(k_cur, v_cur, km_cur, i, m, l, acc)
            return (k_nxt, v_nxt, km_nxt, m, l, acc), None

        km_l0 = km_l if has_mask else jnp.ones((b, chunk), jnp.float32)
        # n-1 rotate+compute steps, then the last received block computes
        # WITHOUT a trailing ppermute (its output would be discarded, and a
        # collective in a loop body can't be DCE'd — one free ICI hop saved).
        (k_f, v_f, km_f, m, l, acc), _ = lax.scan(
            step, (k_l, v_l, km_l0, m0, l0, a0), jnp.arange(n - 1))
        m, l, acc = update(k_f, v_f, km_f, n - 1, m, l, acc)
        out = acc / jnp.maximum(l[..., None], 1e-30)
        return out.astype(q.dtype)

    km_in = key_mask if has_mask else jnp.ones((b, t), jnp.float32)
    fn = shard_map(
        local, mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, km_spec),
        out_specs=qkv_spec,
    )
    return fn(q, k, v, km_in)


def ulysses_attention(
    q, k, v, *, mesh: Mesh, causal: bool = False, scale: Optional[float] = None,
    key_mask=None, seq_axis: str = SEQ_AXIS, use_flash: bool = True,
    block_q: int = 256, block_k: int = 256,
):
    """Ulysses-style SP: all-to-all head-scatter/seq-gather, local full-seq
    attention (flash kernel), inverse all-to-all. q/k/v [B,H,T,D] global."""
    if seq_axis not in mesh.axis_names:
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               key_mask=key_mask)
    n = mesh.shape[seq_axis]
    b, h, t, d = q.shape
    if h % n != 0:
        raise ValueError(f"heads {h} not divisible by seq axis size {n}")
    if t % n != 0:
        raise ValueError(f"seq len {t} not divisible by seq axis size {n}")
    scale = (d ** -0.5) if scale is None else scale
    has_mask = key_mask is not None

    qkv_spec = P(None, None, seq_axis, None)
    km_spec = P(None, seq_axis)

    def local(q_l, k_l, v_l, km_l):
        # [B, H, T/n, D] -> [B, H/n, T, D]: split heads across devices,
        # gather the full sequence (one fused ICI all-to-all).
        def scatter_heads(x):
            return lax.all_to_all(x, seq_axis, split_axis=1, concat_axis=2,
                                  tiled=True)

        def gather_heads(x):
            return lax.all_to_all(x, seq_axis, split_axis=2, concat_axis=1,
                                  tiled=True)

        qh, kh, vh = scatter_heads(q_l), scatter_heads(k_l), scatter_heads(v_l)
        km_full = lax.all_gather(km_l, seq_axis, axis=1, tiled=True) \
            if has_mask else None
        if use_flash:
            # Explicit backend: use_flash=True means the Pallas kernel, not
            # the auto-dispatch (which would route short sequences to XLA
            # and make this flag a no-op).
            out = flash_attention(qh, kh, vh, causal=causal, scale=scale,
                                  key_mask=km_full, block_q=block_q,
                                  block_k=block_k, backend="pallas")
        else:
            out = reference_attention(qh, kh, vh, causal=causal, scale=scale,
                                      key_mask=km_full)
        return gather_heads(out)

    km_in = key_mask if has_mask else jnp.ones((b, t), jnp.float32)
    fn = shard_map(
        local, mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, km_spec),
        out_specs=qkv_spec,
    )
    return fn(q, k, v, km_in)


def sequence_sharded_spec(mesh: Mesh, seq_axis: str = SEQ_AXIS) -> P:
    """PartitionSpec for [B,H,T,D] activations sharded on the seq axis."""
    if seq_axis not in mesh.axis_names:
        return P()
    return P(None, None, seq_axis, None)


# --- active sequence mesh -------------------------------------------------
# Layer configs are serializable dataclasses and cannot hold a Mesh; layers
# that opt into sequence parallelism (SelfAttention.sequence_parallel) pick
# the mesh up from this context at apply time.

import contextlib  # noqa: E402

_ACTIVE_SEQ_MESH: Optional[Mesh] = None


def set_sequence_mesh(mesh: Optional[Mesh]) -> None:
    global _ACTIVE_SEQ_MESH
    _ACTIVE_SEQ_MESH = mesh


def get_sequence_mesh() -> Optional[Mesh]:
    return _ACTIVE_SEQ_MESH


@contextlib.contextmanager
def sequence_mesh(mesh: Mesh):
    global _ACTIVE_SEQ_MESH
    prev = _ACTIVE_SEQ_MESH
    _ACTIVE_SEQ_MESH = mesh
    try:
        yield mesh
    finally:
        _ACTIVE_SEQ_MESH = prev


VALID_SP_IMPLS = ("ring", "ulysses")


def sharded_attention(q, k, v, *, impl: str, causal=False, scale=None,
                      key_mask=None):
    """Dispatch helper used by nn layers: ``impl`` in {"ring", "ulysses"};
    falls back to the flash kernel when no sequence mesh is active.

    NOTE (trace-time semantics): the active mesh is captured when the
    enclosing function is *traced*. If you jit a train/apply step yourself,
    enter ``sequence_mesh(mesh)`` before the first (compiling) call and keep
    the same mesh for the jit'd function's lifetime — a cached trace will
    not notice a later mesh change (standard JAX practice: meshes are
    trace-time constants, as with flax's mesh contexts)."""
    if impl not in VALID_SP_IMPLS:
        raise ValueError(
            f"unknown sequence_parallel impl {impl!r}; valid: {VALID_SP_IMPLS}")
    mesh = get_sequence_mesh()
    if mesh is None or SEQ_AXIS not in mesh.axis_names:
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               key_mask=key_mask)
    fn = {"ring": ring_attention, "ulysses": ulysses_attention}[impl]
    return fn(q, k, v, mesh=mesh, causal=causal, scale=scale, key_mask=key_mask)
