"""Parallelism layer (↔ L5 scaleout + parameter server, SURVEY §2.6).

Every reference strategy maps to a placement policy + XLA collectives:

- specs: partition-spec tables (DP P1–P4, FSDP P11, TP P7)
- sequence: ring attention + Ulysses all-to-all (P9 — new capability)
- pipeline: GPipe-style microbatched stage pipeline (P8 — new capability)
- inference: replicated-model serving with dynamic batching (P6)
"""

from deeplearning4j_tpu.parallel.specs import (
    batch_spec,
    data_parallel_plan,
    fsdp_plan,
    replicated,
    tensor_parallel_plan,
    train_state_sharding,
)
from deeplearning4j_tpu.parallel.sequence import (
    get_sequence_mesh,
    ring_attention,
    sequence_mesh,
    sequence_sharded_spec,
    set_sequence_mesh,
    sharded_attention,
    ulysses_attention,
)
from deeplearning4j_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_stage_params,
    stage_params_sharding,
)
from deeplearning4j_tpu.parallel.inference import (
    InferenceQueueFull,
    InferenceShutdown,
    ParallelInference,
    WorkerCrashError,
)

__all__ = [
    "batch_spec",
    "data_parallel_plan",
    "fsdp_plan",
    "replicated",
    "tensor_parallel_plan",
    "train_state_sharding",
    "ring_attention",
    "ulysses_attention",
    "sharded_attention",
    "sequence_mesh",
    "set_sequence_mesh",
    "get_sequence_mesh",
    "sequence_sharded_spec",
    "pipeline_apply",
    "stack_stage_params",
    "stage_params_sharding",
    "ParallelInference",
    "InferenceQueueFull",
    "InferenceShutdown",
    "WorkerCrashError",
]
