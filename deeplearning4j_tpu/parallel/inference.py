"""Parallel inference serving (P6).

ref: org.deeplearning4j.parallelism.ParallelInference — N model replicas on
N devices, a request queue, worker threads, and optional dynamic batching
(InferenceMode.BATCHED via BatchedInferenceObservable) (SURVEY §2.6 P6,
§3.5). TPU translation: the "replica" is one compiled executable placed per
device (compile once — PJRT executables are device-agnostic within a
platform); worker threads drain a shared queue; BATCHED mode coalesces
queued requests up to max_batch_size before dispatch, splitting results
back per caller.

The GIL is not a bottleneck: device execution releases it, so N host
threads keep N chips busy, same as the reference's Java worker threads.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class _Request:
    __slots__ = ("inputs", "event", "result", "error", "cancelled")

    def __init__(self, inputs):
        self.inputs = inputs
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.cancelled = False


class ParallelInference:
    """Replicated-model inference server (↔ ParallelInference builder).

    forward: (variables, features) -> outputs, pure (jit-compiled; one
    compilation per distinct input shape per device). ``mode``: "instant"
    dispatches each request alone; "batched" coalesces queued requests up
    to ``max_batch_size`` rows and pads the coalesced batch to a
    power-of-two bucket so compilation count stays bounded under traffic
    with varying request sizes. Features must be a single array whose
    non-leading dims agree across requests.

    Usage::

        pi = ParallelInference(lambda v, x: model.output(v, x),
                               variables, devices=jax.devices(),
                               mode="batched")
        y = pi.output(x)          # thread-safe, blocking
        pi.shutdown()
    """

    def __init__(
        self,
        forward: Callable[[Any, Any], Any],
        variables: Any,
        *,
        devices: Optional[Sequence] = None,
        mode: str = "instant",
        max_batch_size: int = 32,
        queue_limit: int = 256,
    ):
        if mode not in ("instant", "batched"):
            raise ValueError(f"mode {mode!r}; valid: instant|batched")
        self._devices = list(devices) if devices is not None else jax.devices()
        self._mode = mode
        self._max_batch = max_batch_size
        self._queue: "queue.Queue[Optional[_Request]]" = queue.Queue(queue_limit)
        self._state_lock = threading.Lock()  # orders enqueue vs shutdown
        self._fn = jax.jit(forward)
        # One replica of the variables per device (↔ model.clone() per GPU —
        # but here it's the same immutable buffers, transferred not cloned).
        self._replicas = [
            jax.device_put(variables, d) for d in self._devices
        ]
        self._workers: List[threading.Thread] = []
        self._running = True
        for i, dev in enumerate(self._devices):
            th = threading.Thread(
                target=self._worker, args=(i, dev), daemon=True,
                name=f"parallel-inference-{i}")
            th.start()
            self._workers.append(th)

    # -- client API --------------------------------------------------------

    def output(self, features, timeout: Optional[float] = None):
        """Blocking single-request inference (thread-safe).

        On timeout the request is marked cancelled — a worker that picks it
        up later skips it instead of computing a result nobody reads."""
        req = _Request(features)
        # Lock orders the running-check + enqueue against shutdown()'s
        # running-flip + sentinel enqueue: a request admitted here is
        # guaranteed to precede the sentinels in the FIFO, so workers
        # serve it before exiting.
        with self._state_lock:
            if not self._running:
                raise RuntimeError("ParallelInference is shut down")
            self._queue.put(req)
        if not req.event.wait(timeout):
            req.cancelled = True
            raise TimeoutError("inference request timed out")
        if req.error is not None:
            raise req.error
        return req.result

    def shutdown(self):
        """Stop accepting requests; pending queued requests are still served
        (FIFO: sentinels are enqueued behind them), then workers exit."""
        with self._state_lock:
            if not self._running:
                return
            self._running = False
            for _ in self._workers:
                self._queue.put(None)
        for th in self._workers:
            th.join(timeout=30)
        # Anything still queued after the workers died (crash path): fail it.
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not None:
                req.error = RuntimeError("server shut down before serving request")
                req.event.set()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()

    # -- workers -----------------------------------------------------------

    def _take_batch(self, carry: Optional[_Request]):
        """Collect the next batch. ``carry`` is a request taken off the
        queue last round that would have overflowed max_batch_size.
        Returns (batch, next_carry) — batch None means shutdown."""
        req = carry if carry is not None else self._queue.get()
        if req is None:
            return None, None
        batch = [req]
        if self._mode == "batched":
            rows = req.inputs.shape[0]
            while rows < self._max_batch:
                try:
                    nxt = self._queue.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._queue.put(None)  # keep shutdown signal for peers
                    break
                if nxt.cancelled:
                    continue
                if rows + nxt.inputs.shape[0] > self._max_batch:
                    return batch, nxt  # would overflow: starts next batch
                batch.append(nxt)
                rows += nxt.inputs.shape[0]
        return batch, None

    @staticmethod
    def _bucket(rows: int, cap: int) -> int:
        """Next power-of-two ≥ rows (≤ cap): bounds jit compilation count."""
        b = 1
        while b < rows:
            b *= 2
        return min(b, max(cap, rows))

    def _worker(self, idx: int, device):
        variables = self._replicas[idx]
        carry: Optional[_Request] = None
        while True:
            batch, carry = self._take_batch(carry)
            if batch is None:
                return
            batch = [r for r in batch if not r.cancelled]
            if not batch:
                continue
            try:
                sizes = [r.inputs.shape[0] for r in batch]
                rows = sum(sizes)
                feats = jnp.concatenate(
                    [jnp.asarray(r.inputs) for r in batch]) \
                    if len(batch) > 1 else jnp.asarray(batch[0].inputs)
                if self._mode == "batched":
                    bucket = self._bucket(rows, self._max_batch)
                    if bucket > rows:
                        pad = jnp.zeros((bucket - rows, *feats.shape[1:]),
                                        feats.dtype)
                        feats = jnp.concatenate([feats, pad])
                out = jax.device_get(
                    self._fn(variables, jax.device_put(feats, device)))
                offs = np.cumsum([0] + sizes)
                for r, lo, hi in zip(batch, offs[:-1], offs[1:]):
                    r.result = jax.tree_util.tree_map(
                        lambda a: a[int(lo):int(hi)], out)
                for r in batch:
                    r.event.set()
            except Exception as e:  # noqa: BLE001 — deliver to caller
                for r in batch:
                    r.error = e
                    r.event.set()
