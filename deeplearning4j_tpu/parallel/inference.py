"""Parallel inference serving (P6).

ref: org.deeplearning4j.parallelism.ParallelInference — N model replicas on
N devices, a request queue, worker threads, and optional dynamic batching
(InferenceMode.BATCHED via BatchedInferenceObservable) (SURVEY §2.6 P6,
§3.5). TPU translation: the "replica" is one compiled executable placed per
device (compile once — PJRT executables are device-agnostic within a
platform); worker threads drain a shared queue; BATCHED mode coalesces
queued requests up to max_batch_size before dispatch, splitting results
back per caller.

The GIL is not a bottleneck: device execution releases it, so N host
threads keep N chips busy, same as the reference's Java worker threads.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.observability import trace as _trace


class InferenceQueueFull(RuntimeError):
    """Raised by ``output()`` when the request queue is at ``queue_limit``.

    This is structured backpressure, not a bug: the server is saturated
    and the caller should shed/retry. The old behavior (block until a
    slot frees) held ``_state_lock`` through the blocking put, which
    ``shutdown()`` also needs — sustained overload deadlocked shutdown
    until the worker ``join(timeout=30)`` expired."""


class InferenceShutdown(RuntimeError):
    """Raised by ``output()`` when the replica set is shut down (or every
    worker thread is dead with no respawn budget left).

    Typed so callers fail FAST with a retryable signal instead of
    enqueueing into a queue nobody will ever drain and burning the full
    client timeout. The serving layer maps it to a retryable 503.
    ``workers_dead`` distinguishes "every worker died, respawn budget
    exhausted" (a real outage the circuit breaker must count) from an
    orderly ``shutdown()`` race (a drain, which it must not)."""

    def __init__(self, *args, workers_dead: bool = False):
        super().__init__(*args)
        self.workers_dead = workers_dead


class InferenceDeadlineExpired(RuntimeError):
    """Delivered to a request whose deadline expired while it was still
    QUEUED: the worker dropped it before dispatch instead of burning a
    batch slot computing a result nobody can use. The serving layer
    maps it to a 504 with the distinct ``DEADLINE_EXPIRED`` code."""


class WorkerCrashError(RuntimeError):
    """Delivered to the in-flight requests of a worker thread that died
    unexpectedly (bug, injected ``serving.worker_crash``): their batch
    was lost, but the failure is *retryable* — a replacement worker was
    respawned (or a peer still serves the queue)."""


class _InjectedWorkerCrash(BaseException):
    """``serving.worker_crash`` injection vehicle. BaseException so the
    per-batch ``except Exception`` delivery path cannot swallow it — it
    must escape the worker loop and kill the thread, exactly like an
    un-caught bug would."""


def _rows(inputs) -> int:
    """Leading-dim row count of a features pytree (single array or a
    dict of aligned arrays, e.g. BERT's {token_ids, segment_ids, mask})."""
    return jax.tree_util.tree_leaves(inputs)[0].shape[0]


class _Request:
    __slots__ = ("inputs", "event", "result", "error", "cancelled",
                 "trace", "t_enqueue", "deadline")

    def __init__(self, inputs):
        self.inputs = inputs
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.cancelled = False
        # (trace_id, parent_span_id) from the serving layer, or None;
        # the worker records batch/dispatch spans against it post-hoc.
        self.trace = None
        self.t_enqueue = 0.0
        # absolute monotonic deadline; a worker drops the request
        # pre-dispatch once it passes (None = never expires in queue)
        self.deadline = None


class ParallelInference:
    """Replicated-model inference server (↔ ParallelInference builder).

    forward: (variables, features) -> outputs, pure (jit-compiled; one
    compilation per distinct input shape per device). ``mode``: "instant"
    dispatches each request alone; "batched" coalesces queued requests up
    to ``max_batch_size`` rows and pads the coalesced batch to a
    power-of-two bucket so compilation count stays bounded under traffic
    with varying request sizes. Features are a single array — or a pytree
    of arrays sharing the leading batch dim (dict-feature models like
    BERT) — whose non-leading dims agree across requests.

    ``on_batch``: optional callback ``(n_requests, rows, bucket_rows,
    seconds)`` invoked after every device dispatch — the hook the serving
    layer uses for batch-occupancy and on-device-latency metrics.

    When the queue is at ``queue_limit``, ``output()`` raises
    :class:`InferenceQueueFull` instead of blocking (overload must shed,
    not wedge shutdown).

    **Worker supervision**: a worker thread that dies unexpectedly (a
    bug escaping the dispatch path, or the injected
    ``serving.worker_crash`` fault) fails every request it held with a
    retryable :class:`WorkerCrashError` — nothing is silently stranded —
    and is respawned on the same device (bounded by
    ``max_worker_respawns``; ``on_respawn(worker_idx)`` is the serving
    layer's metrics hook). With the budget exhausted and every worker
    dead, ``output()`` raises :class:`InferenceShutdown` immediately
    instead of enqueueing into a queue nobody drains.

    Usage::

        pi = ParallelInference(lambda v, x: model.output(v, x),
                               variables, devices=jax.devices(),
                               mode="batched")
        y = pi.output(x)          # thread-safe, blocking
        pi.shutdown()
    """

    def __init__(
        self,
        forward: Callable[[Any, Any], Any],
        variables: Any,
        *,
        devices: Optional[Sequence] = None,
        mode: str = "instant",
        max_batch_size: int = 32,
        queue_limit: int = 256,
        batch_wait_s: float = 0.0,
        on_batch: Optional[Callable[[int, int, int, float], None]] = None,
        on_expired: Optional[Callable[[int], None]] = None,
        max_worker_respawns: int = 8,
        on_respawn: Optional[Callable[[int], None]] = None,
    ):
        if mode not in ("instant", "batched"):
            raise ValueError(f"mode {mode!r}; valid: instant|batched")
        if batch_wait_s < 0:
            raise ValueError(f"batch_wait_s must be >= 0, got {batch_wait_s}")
        self._devices = list(devices) if devices is not None else jax.devices()
        self._mode = mode
        self._max_batch = max_batch_size
        # batched mode: how long a worker holding a partial batch waits
        # for more requests to coalesce before dispatching (0 = dispatch
        # what's there, the historical behavior). The brownout ladder's
        # first rung shrinks this back to 0 under overload — latency
        # headroom beats occupancy once the server is drowning.
        self._batch_wait_s = float(batch_wait_s)
        self._queue: "queue.Queue[Optional[_Request]]" = queue.Queue(queue_limit)
        self._state_lock = threading.Lock()  # orders enqueue vs shutdown
        self._on_batch = on_batch
        self._on_expired = on_expired
        self._on_respawn = on_respawn
        self._max_respawns = max_worker_respawns
        self._respawns = 0
        self._fn = jax.jit(forward)
        # One replica of the variables per device (↔ model.clone() per GPU —
        # but here it's the same immutable buffers, transferred not cloned).
        self._replicas = [
            jax.device_put(variables, d) for d in self._devices
        ]
        self._workers: List[threading.Thread] = []
        # Per-worker list of taken-but-undelivered requests: the crash
        # handler fails exactly these, so a dying worker never strands a
        # caller into its full timeout.
        self._inflight: List[List[_Request]] = [
            [] for _ in self._devices]
        self._running = True
        # flipped (under _state_lock) by the LAST worker's crash handler
        # when no respawn budget remains: output() must fail fast from
        # that instant — an is_alive() scan alone races the handler,
        # which is still a live thread while it drains the queue (and
        # two concurrently-crashing handlers would each see the other
        # alive, so the count below is decremented explicitly instead)
        self._dead = False
        self._live = len(self._devices)
        for i, dev in enumerate(self._devices):
            self._workers.append(self._spawn_worker(i, dev))

    # -- client API --------------------------------------------------------

    def output(self, features, timeout: Optional[float] = None,
               trace=None, deadline: Optional[float] = None):
        """Blocking single-request inference (thread-safe).

        On timeout the request is marked cancelled — a worker that picks it
        up later skips it instead of computing a result nobody reads.
        Raises :class:`InferenceQueueFull` when the queue is at
        ``queue_limit`` (never blocks while holding the state lock), and
        :class:`InferenceShutdown` — immediately, not after the timeout —
        when the replica set is shut down or every worker is dead.

        ``deadline``: absolute ``time.monotonic()`` instant after which
        the request is DEAD — a worker reaching it later drops it
        pre-dispatch with :class:`InferenceDeadlineExpired` instead of
        spending a batch slot on it (defaults to now + ``timeout``, so
        a timed request can never be dispatched past its own timeout).

        ``trace``: optional ``(trace_id, parent_span_id)`` correlation
        context — the worker records "serving.batch" (queue wait + batch
        assembly) and "serving.dispatch" (device execution) spans under
        it, so a request's time is attributable end to end."""
        # Validate here, in the caller's thread: malformed features that
        # raised in the worker's batch-collection path would kill the
        # worker and strand every request it held.
        try:
            _rows(features)
        except (IndexError, AttributeError, TypeError) as e:
            raise ValueError(
                "features must be a non-empty pytree of arrays with a "
                f"leading batch dim, got {type(features).__name__}") from e
        req = _Request(features)
        if deadline is not None:
            req.deadline = deadline
        elif timeout is not None:
            req.deadline = time.monotonic() + timeout
        if trace is not None and _trace.tracing_enabled():
            req.trace = trace
            req.t_enqueue = _trace.now()
        # Lock orders the running-check + enqueue against shutdown()'s
        # running-flip: a request admitted here is guaranteed to precede
        # the sentinels in the FIFO, so workers serve it before exiting.
        # The put must be non-blocking — a blocking put at queue_limit
        # would hold the lock shutdown() needs, deadlocking it under
        # sustained overload.
        with self._state_lock:
            if not self._running:
                raise InferenceShutdown("ParallelInference is shut down")
            if self._dead:
                # every worker died and the respawn budget is gone:
                # enqueueing would strand the caller for its full
                # timeout — fail fast and retryably instead. (The flag
                # is set under this lock before the dying worker drains
                # the queue, so no request can slip in between.)
                raise InferenceShutdown(
                    "ParallelInference has no live workers "
                    f"(respawn budget {self._max_respawns} exhausted)",
                    workers_dead=True)
            try:
                self._queue.put_nowait(req)
            except queue.Full:
                req = None
        if req is None:
            raise InferenceQueueFull(
                f"request queue full (queue_limit={self._queue.maxsize})")
        if not req.event.wait(timeout):
            req.cancelled = True
            raise TimeoutError("inference request timed out")
        if req.error is not None:
            raise req.error
        return req.result

    def set_batch_wait(self, seconds: float):
        """Adjust the batched-mode coalesce wait live (plain float
        assignment — workers read it per batch). The brownout ladder
        shrinks it to 0 under overload and restores it on recovery."""
        if seconds < 0:
            raise ValueError(f"batch_wait_s must be >= 0, got {seconds}")
        self._batch_wait_s = float(seconds)

    def shutdown(self):
        """Stop accepting requests; pending queued requests are still served
        (FIFO: sentinels are enqueued behind them), then workers exit."""
        with self._state_lock:
            if not self._running:
                return
            self._running = False
        # Sentinels go in OUTSIDE the lock: at queue_limit this put blocks
        # until workers drain (guaranteed progress — they only consume),
        # and no output() can slip in ahead since _running is already off.
        for _ in self._workers:
            self._queue.put(None)
        for th in self._workers:
            th.join(timeout=30)
        # Anything still queued after the workers died (crash path): fail it.
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not None:
                req.error = InferenceShutdown(
                    "shut down before serving request")
                req.event.set()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()

    # -- workers -----------------------------------------------------------

    def _spawn_worker(self, idx: int, device) -> threading.Thread:
        th = threading.Thread(
            target=self._worker, args=(idx, device), daemon=True,
            name=f"parallel-inference-{idx}")
        th.start()
        return th

    @property
    def worker_respawns(self) -> int:
        """Worker threads respawned after an unexpected death."""
        with self._state_lock:
            return self._respawns

    def alive_workers(self) -> int:
        return sum(th.is_alive() for th in self._workers)

    def _expire(self, r: _Request) -> bool:
        """True if ``r`` is dead — cancelled by its caller, or its
        deadline passed while it waited in the queue. Deadline-dropped
        requests get a typed :class:`InferenceDeadlineExpired` (their
        caller may still be waiting); both kinds count through the
        ``on_expired`` hook, which is exactly "batch slots saved by not
        dispatching dead work"."""
        if r.cancelled:
            self._count_expired(1)
            return True
        if r.deadline is not None and time.monotonic() >= r.deadline:
            r.error = InferenceDeadlineExpired(
                "deadline expired while queued; dropped before dispatch")
            r.event.set()
            self._count_expired(1)
            return True
        return False

    def _count_expired(self, n: int):
        if self._on_expired is not None:
            try:
                self._on_expired(n)
            except Exception:  # noqa: BLE001 — metrics never fail serving
                pass

    def _take_batch(self, carry: Optional[_Request],
                    held: List[_Request]):
        """Collect the next batch. ``carry`` is a request taken off the
        queue last round that would have overflowed max_batch_size.
        Every request taken off the queue is appended to ``held`` (the
        worker's in-flight ledger) the moment it leaves the queue, so a
        crash at ANY point fails it instead of stranding its caller.
        Returns (batch, next_carry) — batch None means shutdown."""
        req = carry if carry is not None else self._queue.get()
        if req is None:
            return None, None
        if req not in held:
            held.append(req)
        batch = [req]
        if self._mode == "batched":
            rows = _rows(req.inputs)
            wait_s = self._batch_wait_s
            wait_until = (time.monotonic() + wait_s) if wait_s > 0 else None
            while rows < self._max_batch:
                try:
                    nxt = self._queue.get_nowait()
                except queue.Empty:
                    # partial batch: optionally wait out the coalesce
                    # budget for stragglers before dispatching
                    if wait_until is None:
                        break
                    remaining = wait_until - time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        nxt = self._queue.get(timeout=remaining)
                    except queue.Empty:
                        break
                if nxt is None:
                    self._queue.put(None)  # keep shutdown signal for peers
                    break
                held.append(nxt)
                if self._expire(nxt):
                    continue
                if rows + _rows(nxt.inputs) > self._max_batch:
                    return batch, nxt  # would overflow: starts next batch
                batch.append(nxt)
                rows += _rows(nxt.inputs)
        return batch, None

    @staticmethod
    def _bucket(rows: int, cap: int) -> int:
        """Next power-of-two ≥ rows, clamped to the cap bucket when rows
        fit under it. In-cap traffic sees ≤ log2(cap)+1 programs; an
        oversized request (rows > cap, possible for direct callers —
        the serving layer rejects them) still pads to a power of two,
        so compilation count stays log-bounded, never one program per
        distinct row count."""
        b = 1
        while b < rows:
            b *= 2
        return min(b, cap) if rows <= cap else b

    def _worker(self, idx: int, device):
        """Thread entry: run the serve loop; an escape (bug or injected
        ``serving.worker_crash``) is a *crash* — fail what this worker
        held, then respawn."""
        try:
            self._worker_loop(idx, device)
        except BaseException as e:  # noqa: BLE001 — the supervision point
            self._handle_worker_crash(idx, device, e)

    def _worker_loop(self, idx: int, device):
        from deeplearning4j_tpu.resilience.faults import (
            POINT_SERVING_WORKER_CRASH,
            get_fault_injector,
        )

        variables = self._replicas[idx]
        carry: Optional[_Request] = None
        while True:
            held = self._inflight[idx]
            held.clear()
            if carry is not None:
                held.append(carry)
            batch, carry = self._take_batch(carry, held)
            if batch is None:
                return
            # drop dead requests BEFORE dispatch: a request whose caller
            # gave up (or whose deadline already expired) must not
            # occupy batch rows — under overload that waste compounds
            batch = [r for r in batch if not self._expire(r)]
            if not batch:
                continue
            inj = get_fault_injector()
            if inj.enabled and \
                    inj.fire(POINT_SERVING_WORKER_CRASH) is not None:
                # mid-flight thread death, deterministically: the batch
                # is taken, the caller is waiting — exactly the moment a
                # real crash hurts most
                raise _InjectedWorkerCrash(
                    f"injected serving.worker_crash in worker {idx}")
            try:
                sizes = [_rows(r.inputs) for r in batch]
                rows = sum(sizes)
                if len(batch) > 1:
                    feats = jax.tree_util.tree_map(
                        lambda *xs: jnp.concatenate(
                            [jnp.asarray(x) for x in xs]),
                        *[r.inputs for r in batch])
                else:
                    feats = jax.tree_util.tree_map(
                        jnp.asarray, batch[0].inputs)
                bucket = rows
                if self._mode == "batched":
                    bucket = self._bucket(rows, self._max_batch)
                    if bucket > rows:
                        feats = jax.tree_util.tree_map(
                            lambda a: jnp.concatenate(
                                [a, jnp.zeros((bucket - rows, *a.shape[1:]),
                                              a.dtype)]),
                            feats)
                traced = [r for r in batch if r.trace is not None]
                t0 = time.monotonic()
                td0 = _trace.now() if traced else 0.0
                out = jax.device_get(
                    self._fn(variables, jax.device_put(feats, device)))
                td1 = _trace.now() if traced else 0.0
                self._record_telemetry(traced, feats, out, device,
                                       len(batch), rows, bucket, td0, td1)
                if self._on_batch is not None:
                    try:
                        self._on_batch(len(batch), rows, bucket,
                                       time.monotonic() - t0)
                    except Exception:  # noqa: BLE001 — metrics never fail serving
                        pass
                offs = np.cumsum([0] + sizes)
                for r, lo, hi in zip(batch, offs[:-1], offs[1:]):
                    r.result = jax.tree_util.tree_map(
                        lambda a: a[int(lo):int(hi)], out)
                for r in batch:
                    r.event.set()
            except Exception as e:  # noqa: BLE001 — deliver to caller
                for r in batch:
                    r.error = e
                    r.event.set()

    def _handle_worker_crash(self, idx: int, device, exc: BaseException):
        """A worker thread died outside the delivery path. Respawn it
        (budget permitting) FIRST — so a retrying caller finds a live
        worker — then fail every undelivered request it held with a
        retryable :class:`WorkerCrashError`."""
        respawned = False
        with self._state_lock:
            # swap the ledger BEFORE spawning: the replacement worker
            # starts from a fresh (empty) list, so it cannot clear the
            # crashed worker's held requests out from under this handler
            held, self._inflight[idx] = self._inflight[idx], []
            self._live -= 1
            if self._running and self._respawns < self._max_respawns:
                self._respawns += 1
                self._workers[idx] = self._spawn_worker(idx, device)
                self._live += 1
                respawned = True
            # explicit count, not an is_alive() scan: two handlers
            # crashing concurrently each still see the OTHER's thread
            # alive (it is — running its handler), but exactly one of
            # them decrements the count to zero
            last_worker = self._live == 0
            if last_worker:
                # flag first (same lock output() enqueues under), THEN
                # drain below: a request either raced in before the flag
                # — caught by the drain — or fail-fasts at output()
                self._dead = True
        err = WorkerCrashError(
            f"inference worker {idx} died ({exc!r}); its in-flight batch "
            "was lost" + ("; a replacement worker was respawned — retry"
                          if respawned else
                          "; no respawn budget left"))
        failed = 0
        for r in held:
            if not r.event.is_set():
                r.error = err
                r.event.set()
                failed += 1
        if last_worker:
            # this was the LAST worker and nothing replaced it: requests
            # already queued have no one to ever serve them — fail them
            # now (retryably) instead of letting them burn their full
            # client timeouts. output() fail-fasts new arrivals (the
            # _dead flag is already up); this drain covers the ones
            # that beat the death.
            dead_err = InferenceShutdown(
                f"inference worker {idx} died with no respawn budget; "
                "queued request will never be served", workers_dead=True)
            while True:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    break
                if req is None:
                    self._queue.put(None)  # keep shutdown's sentinel
                    break
                if not req.event.is_set():
                    req.error = dead_err
                    req.event.set()
                    failed += 1
        try:
            from deeplearning4j_tpu.observability.flightrecorder import (
                record_event,
            )

            record_event("serving.worker_crash", worker=idx,
                         device=str(device), error=repr(exc)[:200],
                         failed_requests=failed, respawned=respawned)
        except Exception:  # noqa: BLE001 — telemetry never blocks recovery
            pass
        if respawned and self._on_respawn is not None:
            try:
                self._on_respawn(idx)
            except Exception:  # noqa: BLE001 — metrics never fail serving
                pass

    def _record_telemetry(self, traced, feats, out, device, n_requests,
                          rows, bucket, td0, td1):
        """Post-dispatch spans + transfer counters; never fails serving."""
        try:
            from deeplearning4j_tpu.observability import metrics as _obsm
            from deeplearning4j_tpu.observability import runtime as _obsr

            if _obsm.enabled():
                nbytes = sum(getattr(a, "nbytes", 0)
                             for a in jax.tree_util.tree_leaves(feats))
                _obsr.record_transfer("h2d", nbytes)
                _obsr.record_transfer("d2h", sum(
                    getattr(a, "nbytes", 0)
                    for a in jax.tree_util.tree_leaves(out)))
            ledger = None
            if traced:
                from deeplearning4j_tpu.observability import (
                    reqlog as _reqlog,
                )

                ledger = _reqlog.get_request_ledger()
            for r in traced:
                trace_id, parent = r.trace
                b = _trace.record_span(
                    "serving.batch", trace_id=trace_id, parent_id=parent,
                    start=r.t_enqueue, end=td0, rows=rows, bucket=bucket,
                    n_requests=n_requests)
                _trace.record_span(
                    "serving.dispatch", trace_id=trace_id,
                    parent_id=b.span_id, start=td0, end=td1,
                    device=str(device))
                if ledger is not None:
                    # the placement facts only this layer knows land on
                    # the request's ledger record: how long it queued
                    # and which padded batch served it
                    ledger.annotate(
                        trace_id,
                        queue_wait_s=round(max(0.0, td0 - r.t_enqueue), 6),
                        batch_rows=rows, batch_bucket=bucket,
                        dispatch_s=round(max(0.0, td1 - td0), 6))
        except Exception:  # noqa: BLE001 — telemetry never fails serving
            pass
