"""Scripted game-days: replay + timed fault acts + gated verdicts.

A **game-day** is a rehearsed outage: replay recorded traffic
(resilience/replay.py) at speed S against a live ``ModelServer`` or
``FleetRouter`` while a script of timed **acts** injures the fleet —
fault-matrix entries (``serving.latency``, ``serving.error``,
``checkpoint.corrupt``, ``collective.stall``, … via the deterministic
injector in resilience/faults.py), backend SIGKILL (any callable
hook — a subprocess ``proc.kill()``, the supervisor's slot murder),
router-target drain/readmit — and then judges the run against
declarative **gates**:

- ``critical_failures`` — zero critical-class client-visible failures
  (the non-negotiable one: a drill that hurts critical traffic fails
  whatever else went right);
- ``availability`` — client-observed ok-ratio ≥ the SLO;
- ``mttr`` — kill→first-subsequent-success within budget;
- ``p99`` — client-observed tail latency within budget;
- ``recompiles`` — zero ``warmup_recompiles_after_warm_total`` growth
  in the fleet scrape (a drill must not thaw the compile caches).

Gates are evaluated from the replay driver's OWN client-side ledger —
what users saw, not what the fleet claims — and then cross-checked
against the fleet's federated metrics scrape (``reconciliation`` in
the report: the fleet must have served at least every success the
clients observed; a mismatch means telemetry is lying). Acts may be
plain dicts (the JSON script grammar, see :meth:`GameDay.from_script`)
or built programmatically; non-serializable acts (SIGKILL) bind
through named **hooks**.

Every run emits a ``gameday.*`` flight trail (start / act / gate /
report / complete), ``gameday_*`` metric families, and a post-run
report artifact: per-act verdicts, gate table, worst requests of the
run, incident bundles the fleet opened while the drill ran, and the
client-vs-fleet reconciliation. ``DL4J_TPU_GAMEDAY_REPORT_DIR`` (or
``report_dir=``) makes the runner write the artifact to disk.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Sequence

from deeplearning4j_tpu.observability import metrics as _metrics
from deeplearning4j_tpu.observability.flightrecorder import record_event
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience import replay as _replay

ENV_GAMEDAY_REPORT_DIR = "DL4J_TPU_GAMEDAY_REPORT_DIR"

ACT_KINDS = ("fault", "clear_faults", "kill", "drain", "readmit", "call",
             "spawn_pressure")
GATE_KINDS = ("critical_failures", "availability", "mttr", "p99",
              "recompiles", "fleet_health", "autoscaler")

# counter families the fleet scrape sums for reconciliation + the
# recompile gate (whichever exist on the target; a router federates
# its backends' serving_* under the same names)
_SCRAPE_FAMILIES = ("serving_requests_total", "router_requests_total",
                    "generation_requests_total",
                    "warmup_recompiles_after_warm_total")


class GameDayMetrics:
    """Game-day exposition families (process default registry)."""

    def __init__(self, registry: Optional[_metrics.MetricsRegistry] = None):
        r = registry if registry is not None else _metrics.default_registry()
        self.registry = r
        self.runs_total = r.counter(
            "gameday_runs_total",
            "Game-day drills completed, by verdict (pass | fail).",
            ("verdict",))
        self.acts_total = r.counter(
            "gameday_acts_total",
            "Scripted acts fired across all drills, by kind (fault | "
            "clear_faults | kill | drain | readmit | call).", ("kind",))
        self.gates_total = r.counter(
            "gameday_gates_total",
            "Gate evaluations across all drills, by result (pass | "
            "breach) — the gameday-gate-breach burn rule's "
            "numerator/denominator pair.", ("result",))


_gameday_metrics: Optional[GameDayMetrics] = None
_gm_lock = threading.Lock()


def get_gameday_metrics() -> GameDayMetrics:
    global _gameday_metrics
    if _gameday_metrics is None:
        with _gm_lock:
            if _gameday_metrics is None:
                _gameday_metrics = GameDayMetrics()
    return _gameday_metrics


def _drop_gameday_metrics():
    global _gameday_metrics
    _gameday_metrics = None


_metrics.register_reset_hook(_drop_gameday_metrics)


def _gameday_metrics_or_none() -> Optional[GameDayMetrics]:
    try:
        if not _metrics.enabled():
            return None
        return get_gameday_metrics()
    except Exception:  # noqa: BLE001 — metrics never fail the drill
        return None


# -- acts ---------------------------------------------------------------------


class Act:
    """One timed step of the script. ``at_s`` is the offset from run
    start (in REPLAY time — already speed-scaled, like everything the
    clients see). Kinds:

    - ``fault``: install ``spec`` (the ``DL4J_TPU_FAULTS`` grammar,
      e.g. ``"serving.latency@1x40:0.05"``) on the process fault
      injector — injures in-process targets; subprocess backends arm
      theirs via their own environment at spawn;
    - ``clear_faults``: swap in a fresh empty injector;
    - ``kill`` / ``call``: invoke ``fn`` (a subprocess ``.kill()``,
      the supervisor's slot murder, any chaos callable); ``kill`` is
      the act MTTR gates anchor to by default;
    - ``drain`` / ``readmit``: ``POST /admin/<kind>/<backend>`` on
      ``admin_url`` (default: the run's target URL — the router);
    - ``spawn_pressure``: ``POST /admin/autoscaler/pressure`` — inject
      ``duration_s`` of synthetic overload into the router's attached
      autoscaler, so a drill can assert the fleet scales out under
      pressure and back in after it clears (the ``autoscaler`` gate).
    """

    def __init__(self, at_s: float, kind: str, *,
                 name: Optional[str] = None, spec: Optional[str] = None,
                 fn: Optional[Callable[[], object]] = None,
                 backend: Optional[str] = None,
                 admin_url: Optional[str] = None,
                 duration_s: Optional[float] = None):
        if kind not in ACT_KINDS:
            raise ValueError(f"unknown act kind {kind!r} "
                             f"(one of {ACT_KINDS})")
        if kind == "fault" and not spec:
            raise ValueError("fault act needs spec=")
        if kind in ("kill", "call") and fn is None:
            raise ValueError(f"{kind} act needs fn= (or a hook name in "
                             "the script form)")
        if kind in ("drain", "readmit") and not backend:
            raise ValueError(f"{kind} act needs backend=")
        if kind == "spawn_pressure":
            duration_s = 10.0 if duration_s is None else float(duration_s)
            if duration_s <= 0:
                raise ValueError("spawn_pressure act needs duration_s "
                                 f"> 0, got {duration_s}")
        self.at_s = float(at_s)
        self.kind = kind
        self.name = name or f"{kind}@{self.at_s:g}s"
        self.spec = spec
        self.fn = fn
        self.backend = backend
        self.admin_url = admin_url
        self.duration_s = duration_s
        self.t_fired: Optional[float] = None  # monotonic, stamped on fire
        self.error: Optional[str] = None

    def fire(self, default_admin_url: str) -> None:
        try:
            if self.kind == "fault":
                inj = _faults.get_fault_injector()
                for kw in _faults.parse_fault_spec(self.spec):
                    inj.plan(**kw)
            elif self.kind == "clear_faults":
                _faults.set_fault_injector(_faults.FaultInjector())
            elif self.kind in ("kill", "call"):
                self.fn()
            elif self.kind == "spawn_pressure":
                url = (self.admin_url or default_admin_url).rstrip("/")
                req = urllib.request.Request(
                    f"{url}/admin/autoscaler/pressure"
                    f"?duration_s={self.duration_s:g}", data=b"")
                with urllib.request.urlopen(req, timeout=10.0) as r:
                    r.read()
            else:  # drain / readmit
                url = (self.admin_url or default_admin_url).rstrip("/")
                req = urllib.request.Request(
                    f"{url}/admin/{self.kind}/{self.backend}", data=b"")
                with urllib.request.urlopen(req, timeout=10.0) as r:
                    r.read()
        except Exception as e:  # noqa: BLE001 — the drill reports it
            self.error = f"{type(e).__name__}: {e}"[:200]
        self.t_fired = time.monotonic()

    def describe(self) -> dict:
        out = {"name": self.name, "kind": self.kind, "at_s": self.at_s,
               "spec": self.spec, "backend": self.backend,
               "fired": self.t_fired is not None, "error": self.error}
        if self.duration_s is not None:
            out["duration_s"] = self.duration_s
        return out


class Gate:
    """One pass/fail criterion. ``scope`` is ``"run"`` (the whole
    client ledger) or an act name (results sent at/after that act
    fired — "did the fleet stay healthy from the kill onward").
    Thresholds: ``max_count`` (critical_failures), ``min_ratio``
    (availability), ``max_s`` (mttr / p99), ``max_count``
    (recompiles); ``act`` names the anchor act for ``mttr`` (default:
    the first ``kill`` act). ``fleet_health`` polls the router's
    ``/debug/health`` after the drill and breaches on any FIRING fleet
    SLO rule — the server-side cross-check of what the client-ledger
    gates measured from the outside. ``autoscaler`` judges the decision
    ledger from ``/debug/autoscaler``: the fleet must have scaled out
    within ``max_s`` of the anchor ``spawn_pressure`` act firing, and
    (unless ``require_scale_in=False``) scaled back in after the
    pressure window cleared."""

    def __init__(self, kind: str, *, name: Optional[str] = None,
                 scope: str = "run", act: Optional[str] = None,
                 max_count: int = 0, min_ratio: float = 0.99,
                 max_s: float = 5.0, require_scale_in: bool = True):
        if kind not in GATE_KINDS:
            raise ValueError(f"unknown gate kind {kind!r} "
                             f"(one of {GATE_KINDS})")
        self.kind = kind
        self.scope = scope
        self.act = act
        self.name = name or (kind if scope == "run"
                             else f"{kind}:{scope}")
        self.max_count = int(max_count)
        self.min_ratio = float(min_ratio)
        self.max_s = float(max_s)
        self.require_scale_in = bool(require_scale_in)

    def evaluate(self, results: Sequence[dict],
                 acts: Sequence[Act], fleet: dict,
                 health: Optional[dict] = None,
                 autoscaler: Optional[dict] = None) -> dict:
        if self.kind == "autoscaler":
            return self._evaluate_autoscaler(acts, autoscaler)
        if self.kind == "fleet_health":
            # judged from the router's own SLO federation, not the
            # client ledger: the two views must agree for a pass
            if health is None or not isinstance(health.get("rules"),
                                                list):
                return self._verdict(False, None,
                                     "fleet health endpoint "
                                     "unavailable")
            firing = sorted(r.get("name", "?")
                            for r in health["rules"]
                            if r.get("state") == "firing")
            return self._verdict(not firing, firing or 0,
                                 "no firing fleet rules")
        window = results
        if self.scope != "run":
            anchor = _act_named(acts, self.scope)
            if anchor is None or anchor.t_fired is None:
                return self._verdict(False, None,
                                     f"scope act {self.scope!r} never "
                                     "fired")
            window = [r for r in results if r["t_send"] >= anchor.t_fired]
        if self.kind == "critical_failures":
            bad = [r for r in window if r.get("priority") == "critical"
                   and r["outcome"] != "ok"]
            return self._verdict(len(bad) <= self.max_count, len(bad),
                                 f"<= {self.max_count}")
        if self.kind == "availability":
            if not window:
                return self._verdict(False, None, "no requests in scope")
            ok = sum(1 for r in window if r["outcome"] == "ok")
            ratio = ok / len(window)
            return self._verdict(ratio >= self.min_ratio, round(ratio, 6),
                                 f">= {self.min_ratio}")
        if self.kind == "p99":
            p99 = _replay.summarize(window)["latency_p99_s"]
            if p99 is None:
                return self._verdict(False, None, "no successes in scope")
            return self._verdict(p99 <= self.max_s, p99,
                                 f"<= {self.max_s}s")
        if self.kind == "mttr":
            anchor = (_act_named(acts, self.act) if self.act
                      else _first_kill(acts))
            if anchor is None or anchor.t_fired is None:
                return self._verdict(False, None,
                                     "no fired kill act to anchor MTTR")
            mttr = _replay.first_success_after(results, anchor.t_fired)
            if mttr is None:
                return self._verdict(False, None,
                                     "no success after the kill")
            return self._verdict(mttr <= self.max_s, round(mttr, 3),
                                 f"<= {self.max_s}s")
        # recompiles: judged from the fleet scrape, not the client view
        n = fleet.get("warmup_recompiles_after_warm_total")
        if n is None:
            # zero-sample families drop out of federated scrapes, so a
            # healthy scrape that shows traffic but no recompile family
            # means the counter never incremented; only a scrape that
            # saw nothing at all is unjudgeable
            if not fleet.get("_scrape_errors") and any(
                    not k.startswith("_") for k in fleet):
                n = 0.0
            else:
                return self._verdict(False, None,
                                     "fleet scrape unavailable")
        return self._verdict(n <= self.max_count, n,
                             f"<= {self.max_count}")

    def _evaluate_autoscaler(self, acts: Sequence[Act],
                             autoscaler: Optional[dict]) -> dict:
        """Judged from the autoscaler's own decision ledger (fetched
        via ``/debug/autoscaler`` — router and drill share one
        process-local monotonic clock, so act ``t_fired`` stamps and
        ledger ``mono`` stamps are directly comparable)."""
        if autoscaler is None or not isinstance(
                autoscaler.get("ledger"), list):
            return self._verdict(False, None,
                                 "autoscaler ledger unavailable")
        anchor = (_act_named(acts, self.act) if self.act
                  else _first_of(acts, "spawn_pressure"))
        if anchor is None or anchor.t_fired is None:
            return self._verdict(False, None,
                                 "no fired spawn_pressure act to "
                                 "anchor the autoscaler gate")
        ledger = autoscaler["ledger"]
        outs = [e["mono"] - anchor.t_fired for e in ledger
                if e.get("action") in ("scale_out", "page_in")
                and isinstance(e.get("mono"), (int, float))
                and e["mono"] >= anchor.t_fired]
        out_after_s = round(min(outs), 3) if outs else None
        out_ok = out_after_s is not None and out_after_s <= self.max_s
        pressure_end = anchor.t_fired + (anchor.duration_s or 0.0)
        scaled_in = any(e.get("action") == "scale_in"
                        and isinstance(e.get("mono"), (int, float))
                        and e["mono"] >= pressure_end for e in ledger)
        in_ok = scaled_in if self.require_scale_in else True
        budget = f"scale_out <= {self.max_s}s" + (
            " and scale_in after pressure clears"
            if self.require_scale_in else "")
        return self._verdict(out_ok and in_ok,
                             {"scale_out_after_s": out_after_s,
                              "scaled_in": scaled_in}, budget)

    def _verdict(self, passed: bool, value, budget: str) -> dict:
        return {"gate": self.name, "kind": self.kind, "scope": self.scope,
                "passed": bool(passed), "value": value, "budget": budget}


def _act_named(acts: Sequence[Act], name: str) -> Optional[Act]:
    for a in acts:
        if a.name == name:
            return a
    return None


def _first_kill(acts: Sequence[Act]) -> Optional[Act]:
    return _first_of(acts, "kill")


def _first_of(acts: Sequence[Act], kind: str) -> Optional[Act]:
    for a in acts:
        if a.kind == kind:
            return a
    return None


# -- fleet scrape -------------------------------------------------------------


def scrape_fleet_counters(urls: Sequence[str],
                          families: Sequence[str] = _SCRAPE_FAMILIES
                          ) -> dict:
    """Sum the named counter families across ``/metrics?format=json``
    scrapes of each URL (a router URL federates its whole fleet in one
    scrape). Unreachable targets are recorded, not raised — a drill
    that killed its last backend must still produce a report."""
    totals: Dict[str, float] = {}
    errors: List[str] = []
    for url in urls:
        try:
            req = urllib.request.Request(
                url.rstrip("/") + "/metrics?format=json")
            with urllib.request.urlopen(req, timeout=10.0) as r:
                doc = json.loads(r.read())
        except Exception as e:  # noqa: BLE001 — report, don't crash
            errors.append(f"{url}: {type(e).__name__}: {e}"[:200])
            continue
        for fam in doc.get("metrics", []):
            if fam.get("name") in families \
                    and fam.get("type") == "counter":
                totals[fam["name"]] = totals.get(fam["name"], 0.0) + sum(
                    s.get("value", 0.0) for s in fam.get("samples", []))
    out = dict(totals)
    out["_scrape_errors"] = errors
    return out


def fetch_fleet_health(url: str) -> Optional[dict]:
    """One ``GET /debug/health`` against the drill target (a router
    answers at fleet scope). None when unreachable — the fleet_health
    gate turns that into a breach, not a crash."""
    try:
        req = urllib.request.Request(url.rstrip("/") + "/debug/health")
        with urllib.request.urlopen(req, timeout=10.0) as r:
            doc = json.loads(r.read())
        return doc if isinstance(doc, dict) else None
    except Exception:  # noqa: BLE001 — report, don't crash
        return None


def fetch_autoscaler(url: str) -> Optional[dict]:
    """One ``GET /debug/autoscaler`` against the drill target — the
    decision ledger the ``autoscaler`` gate judges and the report
    attaches. None when unreachable or no autoscaler is attached; the
    gate turns that into a breach, not a crash."""
    try:
        req = urllib.request.Request(
            url.rstrip("/") + "/debug/autoscaler")
        with urllib.request.urlopen(req, timeout=10.0) as r:
            doc = json.loads(r.read())
        return doc if isinstance(doc, dict) else None
    except Exception:  # noqa: BLE001 — report, don't crash
        return None


def fetch_incident_index(urls: Sequence[str]) -> List[dict]:
    """Merge ``/debug/incidents`` indexes (a router URL already
    federates its backends'); unreachable targets are skipped."""
    merged: List[dict] = []
    for url in urls:
        try:
            req = urllib.request.Request(
                url.rstrip("/") + "/debug/incidents")
            with urllib.request.urlopen(req, timeout=10.0) as r:
                doc = json.loads(r.read())
        except Exception:  # noqa: BLE001 — a dead target has no bundles
            continue
        merged.extend(doc.get("incidents", []))
    return merged


# -- the runner ---------------------------------------------------------------


class GameDay:
    """One scripted drill: replay ``trace`` against ``base_url`` at
    ``speed`` while firing ``acts`` at their offsets, then judge
    ``gates`` and emit the report artifact."""

    def __init__(self, base_url: str, trace: dict, *,
                 acts: Sequence = (), gates: Sequence = (),
                 name: str = "gameday",
                 speed: Optional[float] = None,
                 clients: Optional[int] = None,
                 max_retries: int = 3, timeout_s: float = 30.0,
                 token_read_delay_s: float = 0.0,
                 fallback_shape=None,
                 report_dir: Optional[str] = None,
                 scrape_urls: Optional[Sequence[str]] = None,
                 incident_urls: Optional[Sequence[str]] = None):
        self.base_url = base_url.rstrip("/")
        self.trace = trace
        self.name = name
        self.acts = [self._coerce_act(a) for a in acts]
        self.acts.sort(key=lambda a: a.at_s)
        self.gates = [self._coerce_gate(g) for g in gates]
        self.driver = _replay.ReplayDriver(
            base_url, trace, speed=speed, clients=clients,
            max_retries=max_retries, timeout_s=timeout_s,
            token_read_delay_s=token_read_delay_s,
            fallback_shape=fallback_shape)
        if report_dir is None:
            report_dir = os.environ.get(ENV_GAMEDAY_REPORT_DIR) or None
        self.report_dir = report_dir
        self.scrape_urls = list(scrape_urls or [self.base_url])
        self.incident_urls = list(incident_urls or [self.base_url])
        self.report: Optional[dict] = None

    @classmethod
    def from_script(cls, script: dict, *, base_url: str, trace: dict,
                    hooks: Optional[Dict[str, Callable]] = None,
                    **overrides) -> "GameDay":
        """Build a drill from the declarative JSON grammar::

            {"name": "evacuate-b2",
             "speed": 10, "clients": 8,
             "acts": [
               {"at_s": 1.0, "kind": "fault",
                "spec": "serving.latency@1x40:0.05"},
               {"at_s": 2.5, "kind": "kill", "hook": "kill-b2"},
               {"at_s": 4.0, "kind": "drain", "backend": "b1"}],
             "gates": [
               {"kind": "critical_failures", "max_count": 0},
               {"kind": "availability", "min_ratio": 0.95},
               {"kind": "mttr", "max_s": 5.0}]}

        ``hooks`` binds the non-serializable acts: an act with
        ``"hook": "kill-b2"`` fires ``hooks["kill-b2"]()``."""
        hooks = hooks or {}
        acts = []
        for a in script.get("acts", []):
            a = dict(a)
            hook = a.pop("hook", None)
            if hook is not None:
                if hook not in hooks:
                    raise ValueError(f"script act references unbound "
                                     f"hook {hook!r}")
                a["fn"] = hooks[hook]
            acts.append(a)
        kwargs = {"name": script.get("name", "gameday"),
                  "speed": script.get("speed"),
                  "clients": script.get("clients"),
                  "acts": acts, "gates": script.get("gates", [])}
        kwargs.update(overrides)
        return cls(base_url, trace, **kwargs)

    @staticmethod
    def _coerce_act(a) -> Act:
        if isinstance(a, Act):
            return a
        a = dict(a)
        return Act(a.pop("at_s"), a.pop("kind"), **a)

    @staticmethod
    def _coerce_gate(g) -> Gate:
        if isinstance(g, Gate):
            return g
        g = dict(g)
        return Gate(g.pop("kind"), **g)

    def run(self) -> dict:
        """Execute the drill; returns (and stores) the report dict."""
        record_event("gameday.start", name=self.name,
                     target=self.base_url, acts=len(self.acts),
                     gates=len(self.gates),
                     rows=len(self.trace["rows"]),
                     speed=self.driver.speed)
        t_wall0 = time.time()
        self.driver.start()
        t0 = self.driver.t_run0
        m = _gameday_metrics_or_none()
        for act in self.acts:
            wait = t0 + act.at_s - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            act.fire(self.base_url)
            record_event("gameday.act", name=self.name, act=act.name,
                         kind=act.kind, error=act.error)
            if m is not None:
                m.acts_total.inc(kind=act.kind)
        summary = self.driver.join()
        results = summary.pop("results")
        fleet = scrape_fleet_counters(self.scrape_urls)
        health = (fetch_fleet_health(self.base_url)
                  if any(g.kind == "fleet_health" for g in self.gates)
                  else None)
        autoscaler_doc = (
            fetch_autoscaler(self.base_url)
            if any(g.kind == "autoscaler" for g in self.gates)
            or any(a.kind == "spawn_pressure" for a in self.acts)
            else None)
        verdicts = []
        for gate in self.gates:
            v = gate.evaluate(results, self.acts, fleet, health,
                              autoscaler=autoscaler_doc)
            verdicts.append(v)
            record_event("gameday.gate", name=self.name,
                         gate=v["gate"], passed=v["passed"],
                         value=v["value"])
            if m is not None:
                m.gates_total.inc(
                    result="pass" if v["passed"] else "breach")
        passed = all(v["passed"] for v in verdicts)
        verdict = "pass" if passed else "fail"
        incidents = fetch_incident_index(self.incident_urls)
        # worst requests of the run: bad outcomes first, then slowest
        worst = sorted(
            results,
            key=lambda r: (r["outcome"] != "ok", r["latency_s"]),
            reverse=True)[:8]
        client_ok = summary["ok"]
        # two fleet views of "requests served": the backends' own
        # counters, and — at a router target — the router's forward
        # counter. Take the larger: a SIGKILLed backend's counters die
        # with it, but the router survives and saw every forward, so a
        # drill that kills a backend still reconciles
        backend_served = sum(
            fleet.get(n, 0.0) for n in ("serving_requests_total",
                                        "generation_requests_total"))
        fleet_served = max(backend_served,
                           fleet.get("router_requests_total", 0.0))
        report = {
            "name": self.name,
            "verdict": verdict,
            "target": self.base_url,
            "started_at": t_wall0,
            "trace": {"rows": len(self.trace["rows"]),
                      "duration_s": self.trace.get("duration_s")},
            "replay": summary,
            "acts": [a.describe() for a in self.acts],
            "gates": verdicts,
            "worst_requests": worst,
            "incidents": incidents,
            # the autoscaler's decision ledger rides in the artifact so
            # a scale-out that passed (or breached) is auditable later
            "autoscaler": (None if autoscaler_doc is None else {
                "mode": autoscaler_doc.get("mode"),
                "desired": autoscaler_doc.get("desired"),
                "live": autoscaler_doc.get("live"),
                "ledger": autoscaler_doc.get("ledger")}),
            "fleet_health": (None if health is None else {
                "status": health.get("status"),
                "rules": [{"name": r.get("name"),
                           "state": r.get("state")}
                          for r in health.get("rules", [])]}),
            "reconciliation": {
                # the fleet must account for at least every success a
                # client observed (retries make fleet >= client); a
                # shortfall means the telemetry plane dropped traffic
                "client_ok": client_ok,
                "client_requests": summary["requests"],
                "fleet_served_total": fleet_served,
                "fleet_counters": fleet,
                "consistent": fleet_served >= client_ok,
            },
        }
        self.report = report
        if m is not None:
            m.runs_total.inc(verdict=verdict)
        path = self._write_report(report, t_wall0)
        record_event("gameday.report", name=self.name, verdict=verdict,
                     path=path,
                     breaches=sum(1 for v in verdicts
                                  if not v["passed"]))
        record_event("gameday.complete", name=self.name, verdict=verdict,
                     requests=summary["requests"],
                     availability=summary["availability"])
        return report

    def _write_report(self, report: dict, t_wall0: float
                      ) -> Optional[str]:
        if not self.report_dir:
            return None
        try:
            os.makedirs(self.report_dir, exist_ok=True)
            path = os.path.join(
                self.report_dir,
                f"{self.name}-{int(t_wall0)}.json")
            with open(path, "w", encoding="utf-8") as f:
                json.dump(report, f, indent=1, default=str)
            return path
        except Exception:  # noqa: BLE001 — artifact IO never fails a run
            return None


__all__ = [
    "ACT_KINDS",
    "ENV_GAMEDAY_REPORT_DIR",
    "GATE_KINDS",
    "Act",
    "GameDay",
    "GameDayMetrics",
    "Gate",
    "fetch_autoscaler",
    "fetch_fleet_health",
    "fetch_incident_index",
    "get_gameday_metrics",
    "scrape_fleet_counters",
]
