"""Backend process lifecycle for the fleet autoscaler.

The autoscaler (serving/autoscaler.py) decides *when* the fleet needs
another backend or one fewer; this module owns *how* one starts and
stops. The split mirrors the elastic supervisor's slot/process
separation: policy upstairs, ``Popen`` downstairs — and keeps the
autoscaler testable against an in-process launcher while production
drives real OS processes.

- :class:`BackendLauncher` — the pluggable contract: ``spawn(name) ->
  url``, ``retire(name)`` (graceful: SIGTERM → grace → SIGKILL),
  ``alive(name)``. The router's probe plane owns *admission* (a spawned
  backend is not routable until ``/readyz`` goes green), so ``spawn``
  returns as soon as the process exists.
- :class:`ProcessBackendLauncher` — subprocess backends on free local
  ports. Spawned environments inherit the fleet's warmup manifest
  (``DL4J_TPU_WARMUP_MANIFEST``) so a scale-out pre-warms the shapes
  the fleet is actually serving before traffic lands (ROADMAP item 8).
- :class:`CallableBackendLauncher` — in-process backends (anything
  with ``.url`` and ``.stop()``, e.g. a ModelServer) for fast tier-1
  tests and dry drills.
- :class:`FailStreak` — the supervisor's dead-slot streak discipline
  at fleet scope: a replacement that dies younger than
  ``immediate_exit_s`` counts toward the slot's streak;
  ``dead_slot_threshold`` consecutive immediate deaths mark the slot
  permanently dead so the autoscaler stops feeding it processes.

Stdlib only; no flight events here — the autoscaler narrates decisions,
this layer just reports what happened.
"""

from __future__ import annotations

import os
import socket
import subprocess
import time
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.analysis.lockcheck import make_lock


def free_port() -> int:
    """One OS-allocated free TCP port (the spawn-time port picker)."""
    s = socket.socket()
    try:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
    finally:
        s.close()


class BackendLauncher:
    """The pluggable lifecycle contract the autoscaler drives.

    Implementations own name → process bookkeeping; ``retire`` and
    ``alive`` on an unknown name are no-ops (the autoscaler also
    manages seed backends it never spawned)."""

    def spawn(self, name: str) -> str:
        """Start a backend and return its URL. Must not block on
        warmup — the router's probe plane gates admission."""
        raise NotImplementedError

    def retire(self, name: str) -> None:
        """Stop the named backend: graceful first, forceful after the
        grace deadline. Unknown names are ignored."""
        raise NotImplementedError

    def alive(self, name: str) -> bool:
        """True while the named backend's process/thread still runs.
        Unknown names are False."""
        return False

    def describe(self) -> dict:
        return {"kind": type(self).__name__}

    def stop_all(self) -> None:
        """Teardown helper: retire everything this launcher spawned."""


class ProcessBackendLauncher(BackendLauncher):
    """Subprocess backends: ``argv_for(name, port)`` builds the command
    line; the child inherits this process's environment plus ``env``
    plus the fleet's warmup manifest path when one is armed.

    ``retire`` is SIGTERM → ``grace_s`` → SIGKILL: a healthy backend
    drains and exits on SIGTERM (install_sigterm_teardown); a wedged
    one must not stall the control loop past the grace window."""

    def __init__(self, argv_for: Callable[[str, int], List[str]], *,
                 env: Optional[dict] = None, grace_s: float = 5.0,
                 manifest=None, host: str = "127.0.0.1"):
        self._argv_for = argv_for
        self._extra_env = dict(env or {})
        self.grace_s = float(grace_s)
        self._host = host
        self._manifest = manifest
        self._lock = make_lock("ProcessBackendLauncher._lock")
        self._procs: Dict[str, subprocess.Popen] = {}
        self._spawned_at: Dict[str, float] = {}

    def _child_env(self) -> dict:
        env = dict(os.environ)
        env.update(self._extra_env)
        if self._manifest is not None:
            # late import: serving.warmstart pulls the serving plane in,
            # and resilience must stay importable without it
            from deeplearning4j_tpu.serving.warmstart import (
                ENV_WARMUP_MANIFEST, resolve_warmup_manifest)
            m = resolve_warmup_manifest(self._manifest)
            if m is not None and m.path is not None:
                m.save()  # the child reads disk, not our memory
                env[ENV_WARMUP_MANIFEST] = str(m.path)
        return env

    def spawn(self, name: str) -> str:
        port = free_port()
        proc = subprocess.Popen(
            self._argv_for(name, port), env=self._child_env(),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        with self._lock:
            self._procs[name] = proc
            self._spawned_at[name] = time.monotonic()
        return f"http://{self._host}:{port}"

    def retire(self, name: str) -> None:
        with self._lock:
            proc = self._procs.pop(name, None)
            self._spawned_at.pop(name, None)
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=self.grace_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10.0)

    def alive(self, name: str) -> bool:
        with self._lock:
            proc = self._procs.get(name)
        return proc is not None and proc.poll() is None

    def age_s(self, name: str) -> Optional[float]:
        """Seconds since spawn (None for unknown names) — the
        immediate-exit classifier's input."""
        with self._lock:
            t = self._spawned_at.get(name)
        return None if t is None else time.monotonic() - t

    def describe(self) -> dict:
        with self._lock:
            names = sorted(self._procs)
        return {"kind": "process", "grace_s": self.grace_s,
                "backends": names,
                "alive": [n for n in names if self.alive(n)]}

    def stop_all(self) -> None:
        with self._lock:
            names = list(self._procs)
        for n in names:
            self.retire(n)


class CallableBackendLauncher(BackendLauncher):
    """In-process backends for tests: ``factory(name)`` returns any
    object with a ``.url`` attribute and a ``.stop()`` method (a
    started ModelServer fits). ``retire`` calls ``.stop()`` — there is
    no process to SIGKILL, so grace semantics collapse to one call."""

    def __init__(self, factory: Callable[[str], object]):
        self._factory = factory
        self._lock = make_lock("CallableBackendLauncher._lock")
        self._servers: Dict[str, object] = {}

    def spawn(self, name: str) -> str:
        server = self._factory(name)
        with self._lock:
            self._servers[name] = server
        return server.url

    def retire(self, name: str) -> None:
        with self._lock:
            server = self._servers.pop(name, None)
        if server is not None:
            server.stop()

    def alive(self, name: str) -> bool:
        with self._lock:
            server = self._servers.get(name)
        if server is None:
            return False
        # a server that exposes liveness reports it; others count as
        # alive while registered (tests drop them via retire)
        probe = getattr(server, "alive", None)
        if callable(probe):
            try:
                return bool(probe())
            except Exception:  # noqa: BLE001 — a dead server is False
                return False
        return True

    def server(self, name: str):
        with self._lock:
            return self._servers.get(name)

    def describe(self) -> dict:
        with self._lock:
            return {"kind": "callable", "backends": sorted(self._servers)}

    def stop_all(self) -> None:
        with self._lock:
            names = list(self._servers)
        for n in names:
            self.retire(n)


class FailStreak:
    """Per-slot immediate-exit streaks (supervisor discipline, fleet
    scope). A *slot* is the stable lineage key replacements share
    (``b2`` → ``b2-r1`` → ``b2-r2`` all charge slot ``b2``): the thing
    that is permanently broken is the workload/config, not any one
    process name."""

    def __init__(self, *, immediate_exit_s: float = 5.0,
                 dead_slot_threshold: int = 3):
        if dead_slot_threshold < 1:
            raise ValueError("dead_slot_threshold must be >= 1, got "
                             f"{dead_slot_threshold}")
        self.immediate_exit_s = float(immediate_exit_s)
        self.dead_slot_threshold = int(dead_slot_threshold)
        self._streak: Dict[str, int] = {}
        self._dead: set = set()

    def note_exit(self, slot: str, lifetime_s: Optional[float]) -> bool:
        """Fold one death into the slot's streak; returns True when
        this death marks the slot permanently dead. A lifetime older
        than ``immediate_exit_s`` (or unknown — a seed backend the
        launcher never spawned) proves the slot CAN run and resets the
        streak to 1, exactly like the supervisor's restart ladder."""
        if slot in self._dead:
            return False
        if lifetime_s is not None and lifetime_s <= self.immediate_exit_s:
            self._streak[slot] = self._streak.get(slot, 0) + 1
        else:
            self._streak[slot] = 1
        if self._streak[slot] >= self.dead_slot_threshold:
            self._dead.add(slot)
            return True
        return False

    def note_healthy(self, slot: str) -> None:
        """A replacement that reached routable clears the streak."""
        self._streak.pop(slot, None)

    def is_dead(self, slot: str) -> bool:
        return slot in self._dead

    def describe(self) -> dict:
        return {"immediate_exit_s": self.immediate_exit_s,
                "dead_slot_threshold": self.dead_slot_threshold,
                "streaks": dict(self._streak),
                "dead_slots": sorted(self._dead)}


__all__ = [
    "BackendLauncher",
    "CallableBackendLauncher",
    "FailStreak",
    "ProcessBackendLauncher",
    "free_port",
]
