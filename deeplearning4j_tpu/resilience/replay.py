"""Traffic replay: recorded ledger traces → open-loop load generation.

The request ledger (observability/reqlog.py) records what production
traffic actually looked like; this module turns that recording into a
repeatable experiment. ``RequestLedger.export_trace()`` (and ``GET
/debug/requests?format=trace`` / the fleet-wide aggregator variant)
produces a **trace**: payload-scrubbed rows of ``{plane, model,
arrival_offset_s, priority, tenant, payload_shape, deadline_s,
stream}`` — shapes only, never bytes. :class:`ReplayDriver` replays a
trace against a live ``ModelServer`` or ``FleetRouter`` URL:

- **open loop**: a dispatcher thread releases each request at its
  recorded arrival time divided by ``speed`` (1x–20x), regardless of
  whether earlier requests finished — offered load is faithful to the
  recording, so an overloaded target queues/sheds exactly as the real
  fleet would (a closed-loop generator would politely back off and
  hide the overload);
- **both planes**: predict rows synthesize zero inputs from
  ``payload_shape``; generation rows synthesize a prompt of
  ``payload_shape[0]`` tokens and replay through the recorded wire
  mode — streamed rows drain the chunked ndjson token stream
  (``token_read_delay_s`` makes the driver a deliberately SLOW client
  to exercise server-side stream backpressure), non-streamed rows
  collect;
- **client-side ledger**: every replayed request lands one result row
  (outcome, status, latency, send lag, attempts) — the game-day gates
  (resilience/gameday.py) are judged from THIS ledger and then
  cross-checked against the fleet's own federated metrics.

Scenario synthesizers warp a trace without touching the target:
:func:`warp_zipf_tenants` (skewed multi-tenant contention),
:func:`warp_diurnal` (sinusoidal rate ramp), :func:`warp_flash_crowd`
(compressed burst window), :func:`warp_duplicate_burst` (repeat
identical requests — the cache tier's hit path under replay). All are
deterministic under a fixed seed. :func:`synthesize_trace` builds a
trace from a spec when no ledger recording exists.

Knobs: ``DL4J_TPU_REPLAY_SPEED`` (default speed multiplier when the
driver isn't given one) and ``DL4J_TPU_REPLAY_CLIENTS`` (default
client-thread count). Metrics: ``replay_requests_total{plane,
outcome}``, ``replay_retries_total``, ``replay_send_lag_seconds``,
``replay_latency_seconds{plane}``, ``replay_in_flight``,
``replay_runs_total``.
"""

from __future__ import annotations

import json
import math
import os
import queue
import random
import threading
import time
from typing import Callable, List, Optional, Sequence

from deeplearning4j_tpu.observability import metrics as _metrics
from deeplearning4j_tpu.observability import reqlog as _reqlog
from deeplearning4j_tpu.observability.flightrecorder import record_event
from deeplearning4j_tpu.serving.client import ServingClient
from deeplearning4j_tpu.serving.errors import (
    ConnectionFailedError,
    DeadlineExceededError,
    NotReadyError,
    QueueFullError,
    ServingError,
    TenantQuotaError,
)

ENV_REPLAY_SPEED = "DL4J_TPU_REPLAY_SPEED"
ENV_REPLAY_CLIENTS = "DL4J_TPU_REPLAY_CLIENTS"

MAX_SPEED = 20.0

# the client-side outcome vocabulary: what the driver's ledger records
# per replayed request (a bounded metric label set, like reqlog's)
CLIENT_OUTCOMES = ("ok", "shed", "unavailable", "deadline", "rejected",
                   "error")


class ReplayMetrics:
    """The replay driver's exposition families (process default
    registry, ReqLogMetrics pattern)."""

    def __init__(self, registry: Optional[_metrics.MetricsRegistry] = None):
        r = registry if registry is not None else _metrics.default_registry()
        self.registry = r
        self.requests_total = r.counter(
            "replay_requests_total",
            "Trace rows replayed, by plane and client-side outcome "
            "(ok | shed | unavailable | deadline | rejected | error).",
            ("plane", "outcome"))
        self.retries_total = r.counter(
            "replay_retries_total",
            "Client-side retry attempts spent across all replayed "
            "requests (beyond each request's first attempt).")
        self.send_lag_seconds = r.histogram(
            "replay_send_lag_seconds",
            "How late each request left the driver relative to its "
            "ideal (speed-scaled) arrival time — open-loop fidelity; "
            "a saturated driver shows here, not as hidden backoff.")
        self.latency_seconds = r.histogram(
            "replay_latency_seconds",
            "Client-observed end-to-end latency of replayed requests "
            "(retries included), by plane.", ("plane",))
        self.in_flight = r.gauge(
            "replay_in_flight",
            "Replayed requests currently in flight in the driver.")
        self.runs_total = r.counter(
            "replay_runs_total",
            "Replay driver runs completed.")


_replay_metrics: Optional[ReplayMetrics] = None
_rm_lock = threading.Lock()


def get_replay_metrics() -> ReplayMetrics:
    global _replay_metrics
    if _replay_metrics is None:
        with _rm_lock:
            if _replay_metrics is None:
                _replay_metrics = ReplayMetrics()
    return _replay_metrics


def _drop_replay_metrics():
    global _replay_metrics
    _replay_metrics = None


_metrics.register_reset_hook(_drop_replay_metrics)


# -- trace plumbing -----------------------------------------------------------


def validate_trace(trace: dict) -> dict:
    """Structural check for a trace document (version, row fields);
    returns the trace for chaining, raises ValueError on junk."""
    if not isinstance(trace, dict) or trace.get("kind") != "dl4j_tpu_trace":
        raise ValueError("not a dl4j_tpu_trace document")
    if trace.get("version") != _reqlog.TRACE_VERSION:
        raise ValueError(
            f"unsupported trace version {trace.get('version')!r} "
            f"(this build replays version {_reqlog.TRACE_VERSION})")
    rows = trace.get("rows")
    if not isinstance(rows, list):
        raise ValueError("trace has no rows list")
    last = -1.0
    for i, row in enumerate(rows):
        if not isinstance(row, dict):
            raise ValueError(f"row {i} is not an object")
        off = row.get("arrival_offset_s")
        if not isinstance(off, (int, float)) or off < 0:
            raise ValueError(f"row {i} has bad arrival_offset_s {off!r}")
        if off < last:
            raise ValueError(f"row {i} arrives before row {i - 1} "
                             "(rows must be arrival-ordered)")
        last = off
        if row.get("plane") not in ("predict", "generation"):
            raise ValueError(f"row {i} has unknown plane "
                             f"{row.get('plane')!r}")
        if not row.get("model"):
            raise ValueError(f"row {i} has no model")
    return trace


def load_trace(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        return validate_trace(json.load(f))


def save_trace(trace: dict, path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(trace, f, indent=1)


def _rebuild(trace: dict, rows: List[dict]) -> dict:
    rows = sorted(rows, key=lambda r: r["arrival_offset_s"])
    out = dict(trace)
    out["rows"] = rows
    out["count"] = len(rows)
    out["duration_s"] = (round(rows[-1]["arrival_offset_s"], 6)
                         if rows else 0.0)
    return out


def synthesize_trace(spec: dict) -> dict:
    """Build a trace from a workload spec when no ledger recording
    exists. Deterministic under ``spec["seed"]``.

    Spec keys: ``n`` (row count), ``rate_rps`` (Poisson arrival rate),
    ``models`` (list of ``{name, plane, weight?, payload_shape?,
    prompt_len?, max_new_tokens?, stream?, deadline_s?}``),
    ``priorities`` (``{class: weight}``, default all-normal),
    ``tenants`` (tenant-name list, uniform pick; use
    :func:`warp_zipf_tenants` for skew), ``seed``."""
    rng = random.Random(spec.get("seed", 0))
    n = int(spec.get("n", 64))
    rate = float(spec.get("rate_rps", 8.0))
    models = spec.get("models") or [
        {"name": "model", "plane": "predict", "payload_shape": [1, 4]}]
    weights = [float(m.get("weight", 1.0)) for m in models]
    prios = spec.get("priorities") or {"normal": 1.0}
    prio_names = sorted(prios)
    prio_weights = [float(prios[p]) for p in prio_names]
    tenants = spec.get("tenants") or [None]
    rows: List[dict] = []
    t = 0.0
    for _ in range(n):
        m = rng.choices(models, weights=weights)[0]
        plane = m.get("plane", "predict")
        if plane == "generation":
            shape = [int(m.get("prompt_len", 8))]
        else:
            shape = m.get("payload_shape") or [1, 4]
        row = {"plane": plane, "model": m["name"],
               "arrival_offset_s": round(t, 6),
               "priority": rng.choices(prio_names,
                                       weights=prio_weights)[0],
               "tenant": rng.choice(tenants),
               "payload_shape": shape,
               "deadline_s": m.get("deadline_s",
                                   spec.get("deadline_s")),
               "stream": bool(m.get("stream", False))}
        if plane == "generation":
            row["max_new_tokens"] = int(m.get("max_new_tokens", 4))
        rows.append(row)
        t += rng.expovariate(rate)
    trace = {"version": _reqlog.TRACE_VERSION, "kind": "dl4j_tpu_trace",
             "t0_wall": None, "count": 0, "duration_s": 0.0, "rows": []}
    return validate_trace(_rebuild(trace, rows))


# -- scenario warps (pure; deterministic under a fixed seed) ------------------


def warp_zipf_tenants(trace: dict, *, n_tenants: int = 8, s: float = 1.2,
                      seed: int = 0) -> dict:
    """Reassign every row's tenant by a Zipf(s) draw over
    ``tenant-0..tenant-{n-1}`` — the skewed multi-tenant contention
    scenario (one hot tenant burning the quota ladder while the tail
    starves)."""
    if n_tenants < 1:
        raise ValueError("n_tenants must be >= 1")
    rng = random.Random(seed)
    weights = [1.0 / (k ** s) for k in range(1, n_tenants + 1)]
    names = [f"tenant-{k}" for k in range(n_tenants)]
    rows = []
    for row in trace["rows"]:
        r = dict(row)
        r["tenant"] = rng.choices(names, weights=weights)[0]
        rows.append(r)
    return _rebuild(trace, rows)


def warp_diurnal(trace: dict, *, period_s: Optional[float] = None,
                 depth: float = 0.5) -> dict:
    """Re-time arrivals through a sinusoidal rate profile: the
    instantaneous rate swings between ``(1 - depth)`` and
    ``(1 + depth)`` of the original across one period (default: the
    trace duration) — the diurnal ramp scenario, compressed to replay
    length. Deterministic (no randomness: gaps are rescaled by the
    local rate)."""
    if not 0.0 <= depth < 1.0:
        raise ValueError("depth must be in [0, 1)")
    rows = [dict(r) for r in trace["rows"]]
    if len(rows) < 2:
        return _rebuild(trace, rows)
    period = float(period_s or max(trace.get("duration_s") or 0.0, 1e-6))
    t_new = rows[0]["arrival_offset_s"]
    prev = rows[0]["arrival_offset_s"]
    rows[0]["arrival_offset_s"] = round(t_new, 6)
    for row in rows[1:]:
        gap = row["arrival_offset_s"] - prev
        prev = row["arrival_offset_s"]
        # rate high → gaps shrink; rate low → gaps stretch
        rate = 1.0 + depth * math.sin(2.0 * math.pi * prev / period)
        t_new += gap / max(rate, 1e-6)
        row["arrival_offset_s"] = round(t_new, 6)
    return _rebuild(trace, rows)


def warp_flash_crowd(trace: dict, *, at_frac: float = 0.5,
                     width_frac: float = 0.2,
                     magnitude: float = 5.0) -> dict:
    """Compress the arrival gaps inside a window (centered at
    ``at_frac`` of the trace, ``width_frac`` wide) by ``magnitude`` —
    the flash-crowd scenario: the same requests, arriving in a spike.
    Deterministic."""
    if magnitude <= 0:
        raise ValueError("magnitude must be > 0")
    dur = max(trace.get("duration_s") or 0.0, 1e-6)
    lo = (at_frac - width_frac / 2.0) * dur
    hi = (at_frac + width_frac / 2.0) * dur
    rows = [dict(r) for r in trace["rows"]]
    if len(rows) < 2:
        return _rebuild(trace, rows)
    t_new = rows[0]["arrival_offset_s"]
    prev = rows[0]["arrival_offset_s"]
    rows[0]["arrival_offset_s"] = round(t_new, 6)
    for row in rows[1:]:
        gap = row["arrival_offset_s"] - prev
        prev = row["arrival_offset_s"]
        if lo <= prev <= hi:
            gap /= magnitude
        t_new += gap
        row["arrival_offset_s"] = round(t_new, 6)
    return _rebuild(trace, rows)


def warp_duplicate_burst(trace: dict, *, frac: float = 0.25,
                         copies: int = 2, lag_s: float = 0.05,
                         seed: int = 0) -> dict:
    """Append ``copies`` duplicates of a random ``frac`` of rows,
    each arriving ``lag_s`` after its original — identical model/
    tenant/shape, so the response-cache tier sees a hit-heavy replay
    (duplicates of cacheable predicts should be absorbed without
    touching a batch slot)."""
    if not 0.0 <= frac <= 1.0:
        raise ValueError("frac must be in [0, 1]")
    rng = random.Random(seed)
    rows = [dict(r) for r in trace["rows"]]
    extra: List[dict] = []
    for row in rows:
        if rng.random() < frac:
            for c in range(1, copies + 1):
                dup = dict(row)
                dup["arrival_offset_s"] = round(
                    row["arrival_offset_s"] + lag_s * c, 6)
                extra.append(dup)
    return _rebuild(trace, rows + extra)


# -- outcome classification ---------------------------------------------------


def _classify(err: ServingError) -> str:
    if isinstance(err, (QueueFullError, TenantQuotaError)):
        return "shed"
    if isinstance(err, (NotReadyError, ConnectionFailedError)):
        return "unavailable"
    if isinstance(err, DeadlineExceededError):
        return "deadline"
    status = getattr(err, "http_status", 500)
    if status in (400, 404):
        return "rejected"
    return "error"


def summarize(results: Sequence[dict], *,
              slo_availability: float = 0.99) -> dict:
    """Gate-ready rollup of a driver's client-side ledger: counts by
    outcome, goodput, availability (ok / total), latency percentiles,
    open-loop send-lag fidelity, and the critical-class failures list
    (``priority == "critical"`` rows whose outcome isn't ok — the
    zero-tolerance gate input)."""
    total = len(results)
    by_outcome: dict = {}
    for r in results:
        by_outcome[r["outcome"]] = by_outcome.get(r["outcome"], 0) + 1
    ok = by_outcome.get("ok", 0)
    lat = sorted(r["latency_s"] for r in results if r["outcome"] == "ok")

    def pct(p: float) -> Optional[float]:
        if not lat:
            return None
        return round(lat[min(len(lat) - 1,
                             int(math.ceil(p * len(lat))) - 1)], 6)

    t0 = min((r["t_send"] for r in results), default=0.0)
    t1 = max((r["t_done"] for r in results), default=0.0)
    dur = max(t1 - t0, 1e-9)
    critical = [r for r in results
                if r.get("priority") == "critical"
                and r["outcome"] != "ok"]
    return {
        "requests": total,
        "by_outcome": by_outcome,
        "ok": ok,
        "availability": round(ok / total, 6) if total else None,
        "meets_slo": (ok / total >= slo_availability) if total else None,
        "goodput_rps": round(ok / dur, 3) if total else 0.0,
        "duration_s": round(dur, 3) if total else 0.0,
        "latency_p50_s": pct(0.50),
        "latency_p99_s": pct(0.99),
        "max_send_lag_s": round(max((r["send_lag_s"] for r in results),
                                    default=0.0), 6),
        "retries": sum(r.get("attempts", 1) - 1 for r in results),
        "critical_failures": critical,
    }


def first_success_after(results: Sequence[dict],
                        t: float) -> Optional[float]:
    """Seconds from ``t`` (monotonic, ``time.monotonic()`` domain) to
    the first client-observed success completing after it — the MTTR
    measurement a kill act's gate uses. None when nothing succeeded
    after ``t``."""
    times = [r["t_done"] for r in results
             if r["outcome"] == "ok" and r["t_done"] >= t]
    if not times:
        return None
    return min(times) - t


# -- the driver ---------------------------------------------------------------


def _synth_inputs(shape, fallback):
    """Zero inputs matching a trace row's payload_shape descriptor
    (list shape or {name: shape} dict); payload bytes were scrubbed at
    export, so zeros stand in — the compiled shapes, bucketing, and
    batching behave identically."""
    if shape is None:
        shape = fallback
    if shape is None:
        raise ValueError("row has no payload_shape and the driver has "
                         "no fallback_shape")

    def zeros(s):
        out = 0.0
        for dim in reversed([int(d) for d in s]):
            out = [out] * dim
        return out

    if isinstance(shape, dict):
        return {k: zeros(v) for k, v in shape.items()}
    return zeros(shape)


class ReplayDriver:
    """Open-loop, arrival-time-faithful replay of one trace against a
    ``ModelServer`` or ``FleetRouter`` base URL.

    A dispatcher thread releases rows at ``arrival_offset_s / speed``;
    ``clients`` worker threads execute them (an unbounded handoff
    queue keeps the dispatcher from ever blocking on a slow target —
    lateness is *measured* as ``send_lag_s``, never silently
    introduced). Results land in ``self.results``; :meth:`run` returns
    ``summarize(self.results)`` with the rows attached."""

    def __init__(self, base_url: str, trace: dict, *,
                 speed: Optional[float] = None,
                 clients: Optional[int] = None,
                 max_retries: int = 3,
                 timeout_s: float = 30.0,
                 token_read_delay_s: float = 0.0,
                 fallback_shape=None,
                 retry_seed: int = 0,
                 on_result: Optional[Callable[[dict], None]] = None):
        validate_trace(trace)
        self.base_url = base_url.rstrip("/")
        self.trace = trace
        if speed is None:
            speed = float(os.environ.get(ENV_REPLAY_SPEED) or 1.0)
        if not 0.0 < speed <= MAX_SPEED:
            raise ValueError(
                f"speed must be in (0, {MAX_SPEED:g}], got {speed}")
        self.speed = float(speed)
        if clients is None:
            clients = int(os.environ.get(ENV_REPLAY_CLIENTS) or 4)
        if clients < 1:
            raise ValueError(f"clients must be >= 1, got {clients}")
        self.clients = int(clients)
        self.max_retries = int(max_retries)
        self.timeout_s = float(timeout_s)
        self.token_read_delay_s = float(token_read_delay_s)
        self.fallback_shape = fallback_shape
        self.retry_seed = int(retry_seed)
        self.on_result = on_result
        self.results: List[dict] = []
        self._results_lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._queue: "queue.Queue" = queue.Queue()
        self.t_run0: Optional[float] = None  # monotonic start of replay

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ReplayDriver":
        """Launch dispatcher + workers without blocking (the game-day
        runner fires acts while this replays); :meth:`join` collects."""
        if self._threads:
            raise RuntimeError("driver already started")
        self.t_run0 = time.monotonic()
        record_event("replay.start", target=self.base_url,
                     rows=len(self.trace["rows"]), speed=self.speed,
                     clients=self.clients)
        for i in range(self.clients):
            t = threading.Thread(target=self._worker, args=(i,),
                                 name=f"replay-client-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        disp = threading.Thread(target=self._dispatch,
                                name="replay-dispatch", daemon=True)
        disp.start()
        self._threads.append(disp)
        return self

    def abort(self) -> None:
        """Stop dispatching further rows (in-flight requests finish);
        the game-day runner calls this when a gate hard-fails."""
        self._stop.set()

    def join(self, timeout_s: Optional[float] = None) -> dict:
        """Wait for the replay to finish and return the summary."""
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        for t in self._threads:
            left = None
            if deadline is not None:
                left = max(0.0, deadline - time.monotonic())
            t.join(left)
        self._threads = []
        with self._results_lock:
            results = sorted(self.results, key=lambda r: r["idx"])
        summary = summarize(results)
        summary["speed"] = self.speed
        summary["clients"] = self.clients
        summary["target"] = self.base_url
        summary["results"] = results
        m = _replay_metrics_or_none()
        if m is not None:
            m.runs_total.inc()
        record_event("replay.complete", target=self.base_url,
                     requests=summary["requests"], ok=summary["ok"],
                     availability=summary["availability"],
                     p99_s=summary["latency_p99_s"])
        return summary

    def run(self) -> dict:
        """Blocking replay: start + join."""
        self.start()
        return self.join()

    # -- internals ---------------------------------------------------------

    def _dispatch(self):
        t0 = self.t_run0
        for idx, row in enumerate(self.trace["rows"]):
            if self._stop.is_set():
                break
            ideal = t0 + row["arrival_offset_s"] / self.speed
            while True:
                lead = ideal - time.monotonic()
                if lead <= 0:
                    break
                if self._stop.wait(min(lead, 0.05)):
                    break
            if self._stop.is_set():
                break
            self._queue.put((idx, row, ideal))
        for _ in range(self.clients):
            self._queue.put(None)

    def _worker(self, worker_idx: int):
        client = ServingClient(
            self.base_url, timeout=self.timeout_s,
            max_retries=self.max_retries,
            retry_seed=self.retry_seed * 1009 + worker_idx)
        m = _replay_metrics_or_none()
        while True:
            item = self._queue.get()
            if item is None:
                return
            idx, row, ideal = item
            if m is not None:
                m.in_flight.inc()
            try:
                res = self._execute(client, idx, row, ideal)
            finally:
                if m is not None:
                    m.in_flight.dec()
            if m is not None:
                m.requests_total.inc(plane=row["plane"],
                                     outcome=res["outcome"])
                m.send_lag_seconds.observe(res["send_lag_s"])
                if res["outcome"] == "ok":
                    m.latency_seconds.observe(res["latency_s"],
                                              plane=row["plane"])
                if res.get("attempts", 1) > 1:
                    m.retries_total.inc(res["attempts"] - 1)
            with self._results_lock:
                self.results.append(res)
            if self.on_result is not None:
                try:
                    self.on_result(res)
                except Exception:  # noqa: BLE001 — observer never kills
                    pass

    def _execute(self, client: ServingClient, idx: int, row: dict,
                 ideal: float) -> dict:
        t_send = time.monotonic()
        cid = f"replay-{idx}"
        deadline_ms = (float(row["deadline_s"]) * 1000.0
                       if row.get("deadline_s") else None)
        outcome, status, tokens, attempts, error = "ok", 200, 0, 1, None
        try:
            if row["plane"] == "generation":
                attempts, tokens = self._do_generate(client, row, cid,
                                                     deadline_ms)
            else:
                inputs = _synth_inputs(row.get("payload_shape"),
                                       self.fallback_shape)
                client.predict(row["model"], inputs,
                               deadline_ms=deadline_ms,
                               correlation_id=cid,
                               priority=row.get("priority"),
                               tenant=row.get("tenant"))
        except ServingError as e:
            outcome = _classify(e)
            status = getattr(e, "http_status", 500)
            error = f"{type(e).__name__}: {e}"[:200]
        except Exception as e:  # noqa: BLE001 — one row, not the run
            outcome, status = "error", 500
            error = f"{type(e).__name__}: {e}"[:200]
        t_done = time.monotonic()
        return {"idx": idx, "cid": cid, "plane": row["plane"],
                "model": row["model"], "priority": row.get("priority"),
                "tenant": row.get("tenant"), "outcome": outcome,
                "status": status, "latency_s": round(t_done - t_send, 6),
                "t_send": t_send, "t_done": t_done,
                "send_lag_s": round(max(0.0, t_send - ideal), 6),
                "tokens": tokens, "attempts": attempts, "error": error}

    def _do_generate(self, client: ServingClient, row: dict, cid: str,
                     deadline_ms):
        shape = row.get("payload_shape") or [8]
        prompt_len = max(1, int(shape[0]) if shape else 8)
        prompt = [1] * prompt_len
        mnt = row.get("max_new_tokens")
        if not row.get("stream"):
            res = client.generate_tokens(
                row["model"], prompt, max_new_tokens=mnt,
                deadline_ms=deadline_ms, correlation_id=cid,
                priority=row.get("priority"), tenant=row.get("tenant"))
            return 1, len(res.get("tokens", []))
        # streaming: the client's retry policy cannot apply to a
        # generator (tokens cannot be un-yielded), so the driver
        # retries WHOLE streams on retryable sheds/preemptions —
        # discarded tokens are fine, replay measures the serving path
        attempts = 0
        delay = 0.05
        while True:
            attempts += 1
            tokens = 0
            try:
                for _tok in client.generate(
                        row["model"], prompt, max_new_tokens=mnt,
                        deadline_ms=deadline_ms, correlation_id=cid,
                        priority=row.get("priority"),
                        tenant=row.get("tenant")):
                    tokens += 1
                    if self.token_read_delay_s > 0:
                        # the deliberately slow client: server-side
                        # stream backpressure is part of the replay
                        time.sleep(self.token_read_delay_s)
                return attempts, tokens
            except ServingError as e:
                if not getattr(e, "retryable", False) \
                        or attempts > self.max_retries:
                    raise
                ra = getattr(e, "retry_after_ms", None)
                wait = max(delay, float(ra) / 1000.0 if ra else 0.0)
                time.sleep(min(wait, 2.0))
                delay = min(delay * 2.0, 2.0)


def _replay_metrics_or_none() -> Optional[ReplayMetrics]:
    try:
        if not _metrics.enabled():
            return None
        return get_replay_metrics()
    except Exception:  # noqa: BLE001 — metrics never fail the driver
        return None


__all__ = [
    "CLIENT_OUTCOMES",
    "ENV_REPLAY_CLIENTS",
    "ENV_REPLAY_SPEED",
    "MAX_SPEED",
    "ReplayDriver",
    "ReplayMetrics",
    "first_success_after",
    "get_replay_metrics",
    "load_trace",
    "save_trace",
    "summarize",
    "synthesize_trace",
    "validate_trace",
    "warp_diurnal",
    "warp_duplicate_burst",
    "warp_flash_crowd",
    "warp_zipf_tenants",
]
