"""Transient-failure retry: backoff schedules + a retrying data-iterator
wrapper.

The reference's AsyncDataSetIterator dies on the first reader IOError and
takes the fit loop with it; on preemptible fleets the dominant data-path
failure is *transient* (NFS blip, object-store 5xx, a reader racing a
rotating file). :func:`retrying` turns those into bounded, jittered
retries, and :func:`backoff_delays` is the shared capped-exponential
schedule (also used by ``ServingClient``'s 429/503 retry).

Stdlib only; no jax imports — safe from any thread.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Iterable, Iterator, Optional, Tuple, Type


def backoff_delays(*, base: float = 0.05, cap: float = 2.0,
                   factor: float = 2.0, jitter: float = 0.5,
                   rng: Optional[random.Random] = None) -> Iterator[float]:
    """Infinite generator of capped exponential backoff delays.

    ``jitter=j`` multiplies each delay by a uniform draw from
    ``[1-j, 1+j]`` (full jitter decorrelates retry storms across workers);
    the post-jitter delay is re-capped at ``cap``. Deterministic when
    given a seeded ``rng``.
    """
    if not 0.0 <= jitter <= 1.0:
        raise ValueError(f"jitter must be in [0, 1], got {jitter}")
    rng = rng if rng is not None else random.Random()
    attempt = 0
    while True:
        # exponent clamp: factor ** 1024 is a float OverflowError, and the
        # cap has long since bitten anyway
        d = min(cap, base * factor ** min(attempt, 64))
        if jitter:
            d *= 1.0 + rng.uniform(-jitter, jitter)
        yield max(0.0, min(cap, d))
        attempt += 1


class RetryingIterator:
    """Iterator wrapper that survives transient read failures.

    A failed Python generator cannot be resumed, so recovery re-creates
    the base iterator and fast-forwards past the ``produced`` items the
    consumer already received (items are re-read, not re-delivered —
    the storage pays, the training loop sees an uninterrupted stream).
    ``max_retries`` bounds *consecutive* failures; any successful item
    resets the budget, so an iterator that fails once an hour never
    exhausts it, while a hard-down source still errors out promptly.

    The base must be a re-iterable that re-yields the same items on
    re-iteration until a pass completes (``ArrayDataSetIterator`` does:
    its shuffle order is derived from (seed, epoch), and epoch advances
    only on a completed pass). Two failure shapes surface loudly instead
    of corrupting the stream: a one-shot iterator/generator cannot be
    re-created, so its first failure re-raises immediately; a base that
    comes back *shorter* than what was already delivered (an exhausted
    generator, a file rotated away) raises RuntimeError rather than
    silently ending the epoch early.

    Composes with the other wrappers: put ``retrying`` closest to the
    storage (inside AsyncDataSetIterator, outside the raw reader) so a
    retry re-reads one batch, not the prefetch queue.
    """

    def __init__(self, base: Iterable, *, max_retries: int = 5,
                 retry_on: Tuple[Type[BaseException], ...] = (IOError, OSError),
                 base_delay: float = 0.05, max_delay: float = 2.0,
                 jitter: float = 0.5, seed: Optional[int] = None,
                 sleep: Callable[[float], None] = time.sleep):
        if max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {max_retries}")
        self.base = base
        self.max_retries = max_retries
        self.retry_on = retry_on
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.jitter = jitter
        self.seed = seed
        self.sleep = sleep
        self.retry_log: list = []  # (produced, attempt, repr(error))

    def __iter__(self):
        produced = 0
        attempts = 0
        delays = None
        one_shot = False
        # pin the base's shuffle epoch (when it has one) so a retry
        # re-iteration replays the SAME permutation it fast-forwards
        epoch_pin = getattr(self.base, "epoch", None)
        while True:
            try:
                if epoch_pin is not None and hasattr(self.base, "set_epoch"):
                    self.base.set_epoch(epoch_pin)
                it = iter(self.base)
                one_shot = it is self.base
                # fast-forward past items the consumer already has
                for k in range(produced):
                    try:
                        next(it)
                    except StopIteration:
                        raise RuntimeError(
                            f"base iterator yielded only {k} items on "
                            f"re-iteration but {produced} were already "
                            "delivered — one-shot generator or shrunken "
                            "source; refusing to truncate the stream "
                            "silently") from None
                while True:
                    try:
                        item = next(it)
                    except StopIteration:
                        return
                    produced += 1
                    attempts = 0
                    yield item
            except self.retry_on as e:
                attempts += 1
                self.retry_log.append((produced, attempts, repr(e)))
                try:
                    from deeplearning4j_tpu.observability import (
                        metrics as _obsm,
                    )

                    if _obsm.enabled():
                        _obsm.get_resilience_metrics() \
                            .data_retries_total.inc()
                except Exception:  # noqa: BLE001 - telemetry never blocks retry
                    pass
                if one_shot:
                    # iter(base) returned base itself: the failed iterator
                    # cannot be re-created, a retry would truncate
                    raise
                if attempts > self.max_retries:
                    raise
                if attempts == 1:
                    # fresh failure streak: the schedule restarts at the
                    # base delay — like the retry budget, it must not
                    # remember transients recovered hours ago
                    delays = backoff_delays(
                        base=self.base_delay, cap=self.max_delay,
                        jitter=self.jitter, rng=random.Random(self.seed))
                self.sleep(next(delays))

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()

    @property
    def epoch(self):
        return getattr(self.base, "epoch", None)

    def set_epoch(self, epoch: int):
        if hasattr(self.base, "set_epoch"):
            self.base.set_epoch(epoch)

    def __len__(self):
        return len(self.base)  # type: ignore[arg-type]


def retrying(base: Iterable, **kwargs) -> RetryingIterator:
    """Wrap a dataset iterator with bounded exponential-backoff retry on
    transient read failures (see :class:`RetryingIterator`)."""
    return RetryingIterator(base, **kwargs)
