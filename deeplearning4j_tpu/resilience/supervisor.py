"""Elastic training supervisor: launch N workers, relaunch the cohort
on death or hang, resume from the latest verified checkpoint.

The reference ran multi-worker training under ParallelWrapper /
SharedTrainingMaster, whose production value was surviving worker loss
(SURVEY §2.6, §5.3). jax has no supervisor — a SIGKILLed worker leaves
its peers stalled in the next collective until the watchdog
(resilience/cluster.py) times them out, and then *nothing restarts the
job*. This module is that missing process-level layer:

- :class:`ElasticSupervisor` launches ``num_workers`` subprocesses (one
  command per worker, parameterized by env: worker id, world size,
  generation, heartbeat dir), then monitors them:

  * a worker exiting non-zero (or being signal-killed) fails the
    *cohort* — SPMD training cannot continue minus one replica;
  * a worker whose heartbeat progress stamp goes stale is *hung*
    (stuck in a collective whose peer died, or livelocked) and fails
    the cohort the same way;
  * all workers exiting 0 completes the run.

- On cohort failure the survivors are terminated (SIGTERM, grace,
  SIGKILL), and after a capped full-jitter backoff
  (``resilience.retry.backoff_delays``) the whole cohort is relaunched
  as generation N+1 — bounded by ``max_restarts``, after which
  :class:`SupervisorGaveUp` surfaces the full exit history.

Recovery correctness is the *worker's* job: a worker that trains via
``FaultTolerantTrainer.fit(resume=True)`` (or
``PreemptionCheckpointer.resume``) restores the latest **verified**
checkpoint on relaunch, so the relaunched cohort resumes at the exact
rolled-back step — the supervisor only guarantees the relaunch happens,
with fresh coordination state per generation (``on_generation`` mints
per-generation env, e.g. a new coordinator port).

Everything is observable: ``supervisor.*`` flight-recorder events,
``resilience_supervisor_restarts_total`` on the shared registry, and
per-worker log files under ``log_dir``. Stdlib only.
"""

from __future__ import annotations

import dataclasses
import os
import random
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Union

from deeplearning4j_tpu.resilience.cluster import (
    ENV_CRASH_DIR,
    ENV_HEARTBEAT_DIR,
    ENV_HEARTBEAT_INTERVAL,
    dead_peers,
)
from deeplearning4j_tpu.resilience.retry import backoff_delays

ENV_WORKER_ID = "DL4J_TPU_WORKER_ID"
ENV_NUM_WORKERS = "DL4J_TPU_NUM_WORKERS"
ENV_GENERATION = "DL4J_TPU_GENERATION"


@dataclasses.dataclass
class WorkerExit:
    """One worker's terminal observation within a generation."""

    generation: int
    worker_id: int
    returncode: Optional[int]  # None = killed by the supervisor (hang)
    reason: str                # "exit" | "hang" | "cohort"
    log_path: Optional[str] = None


class SupervisorGaveUp(RuntimeError):
    """The restart budget is exhausted; carries the full exit history."""

    def __init__(self, msg: str, exits: List[WorkerExit]):
        super().__init__(msg)
        self.exits = exits


@dataclasses.dataclass
class SupervisorResult:
    """A completed run: how many generations it took and every exit
    observed along the way (empty when generation 1 just worked)."""

    generations: int
    restarts: int
    exits: List[WorkerExit]


def _flight(kind: str, **data):
    try:
        from deeplearning4j_tpu.observability.flightrecorder import (
            record_event,
        )

        record_event(kind, **data)
    except Exception:  # noqa: BLE001 — telemetry never fails supervision
        pass


class ElasticSupervisor:
    """Launch, watch, and relaunch a training-worker cohort.

    ``command``: the worker argv (one list used for every worker — the
    worker reads its identity from env), or a callable
    ``(worker_id, generation) -> argv``. Each worker's env carries
    ``DL4J_TPU_WORKER_ID`` / ``DL4J_TPU_NUM_WORKERS`` /
    ``DL4J_TPU_GENERATION`` plus the heartbeat directory; workers that
    want hang detection call
    ``resilience.cluster.heartbeat_from_env()`` and ``touch()`` once per
    step (cheap — in-memory stamp). Workers without heartbeats are still
    supervised for exits, just not for hangs.

    ``on_generation``: optional ``(generation) -> dict`` returning extra
    env vars for that generation — the hook that mints a fresh
    coordinator port per relaunch (gRPC coordination state does not
    survive its processes).

    Usage::

        sup = ElasticSupervisor([sys.executable, "worker.py"],
                                num_workers=2, max_restarts=3,
                                workdir=run_dir)
        result = sup.run()        # returns when all workers exit 0
    """

    def __init__(
        self,
        command: Union[Sequence[str], Callable[[int, int], Sequence[str]]],
        *,
        num_workers: int,
        max_restarts: int = 3,
        workdir: Optional[str | Path] = None,
        env: Optional[Dict[str, str]] = None,
        on_generation: Optional[Callable[[int], Dict[str, str]]] = None,
        heartbeat_timeout_s: Optional[float] = None,
        heartbeat_interval_s: float = 0.25,
        poll_interval_s: float = 0.1,
        grace_s: float = 5.0,
        backoff_base_s: float = 0.25,
        backoff_max_s: float = 30.0,
        backoff_jitter: float = 0.5,
        seed: int = 0,
        telemetry: bool = False,
        telemetry_poll_interval_s: float = 1.0,
        cluster_server_port: Optional[int] = None,
        cluster_slo_rules: Optional[Sequence] = None,
    ):
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
        self.command = command
        self.num_workers = num_workers
        self.max_restarts = max_restarts
        self.workdir = Path(workdir) if workdir is not None else \
            Path(".") / "supervisor-run"
        self.env = dict(env) if env is not None else dict(os.environ)
        self.on_generation = on_generation
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.heartbeat_interval_s = heartbeat_interval_s
        self.poll_interval_s = poll_interval_s
        self.grace_s = grace_s
        self._delays = backoff_delays(
            base=backoff_base_s, cap=backoff_max_s, jitter=backoff_jitter,
            rng=random.Random(seed))
        self.exits: List[WorkerExit] = []
        self.generation = 0
        self._procs: List[subprocess.Popen] = []
        self._logs: List[Path] = []
        # -- cluster telemetry federation (observability/federation.py):
        # with telemetry=True each generation's workers get an exporter
        # port base + file-sink dir in env; the supervisor polls every
        # worker's snapshot each telemetry_poll_interval_s, serves the
        # federated view at /cluster/* (cluster_server_port: 0 =
        # ephemeral, None = no HTTP surface), runs a HealthEngine over
        # the federated registry (cluster_slo_rules: None = the default
        # worker-liveness rule), and buries the cohort's last-known
        # snapshots in a crash dossier on every teardown.
        self.telemetry = bool(telemetry)
        self.telemetry_poll_interval_s = float(telemetry_poll_interval_s)
        self.cluster_server_port = cluster_server_port
        self.cluster_slo_rules = cluster_slo_rules
        self._restart_count = 0
        self._aggregator = None
        self._cluster_server = None
        self._cluster_engine = None
        self._poll_thread: Optional[threading.Thread] = None
        self._poll_stop = threading.Event()

    # -- introspection -------------------------------------------------------

    @property
    def heartbeat_dir(self) -> Path:
        return self.workdir / "heartbeats"

    def worker_log(self, worker_id: int,
                   generation: Optional[int] = None) -> Path:
        gen = self.generation if generation is None else generation
        return self.workdir / f"gen{gen}_worker{worker_id}.log"

    @property
    def telemetry_dir(self) -> Path:
        return self.workdir / "telemetry"

    @property
    def aggregator(self):
        """The :class:`~deeplearning4j_tpu.observability.federation.
        ClusterAggregator` (None until the first telemetry-enabled
        launch)."""
        return self._aggregator

    @property
    def cluster_server(self):
        return self._cluster_server

    @property
    def cluster_url(self) -> Optional[str]:
        return (self._cluster_server.url
                if self._cluster_server is not None else None)

    # -- telemetry federation ------------------------------------------------

    def _pick_telemetry_port_base(self) -> Optional[int]:
        """A base port such that base..base+N-1 all bind right now
        (workers derive base + worker_id). Racy by nature — a worker
        losing the race falls back to its file sink, which the
        aggregator reads anyway."""
        import socket

        for _ in range(32):
            socks = []
            try:
                s0 = socket.socket()
                s0.bind(("127.0.0.1", 0))
                base = s0.getsockname()[1]
                socks.append(s0)
                ok = base + self.num_workers <= 65535
                for i in range(1, self.num_workers if ok else 0):
                    s = socket.socket()
                    try:
                        s.bind(("127.0.0.1", base + i))
                        socks.append(s)
                    except OSError:
                        ok = False
                        break
                if ok:
                    return base
            finally:
                for s in socks:
                    s.close()
        return None

    def _arm_telemetry(self, env: Dict[str, str]) -> None:
        """Per-generation telemetry env + aggregator (re)configuration;
        called from ``_launch_cohort`` before workers spawn."""
        from deeplearning4j_tpu.observability.federation import (
            ENV_TELEMETRY_DIR,
            ENV_TELEMETRY_PORT_BASE,
            ClusterAggregator,
        )

        base = self._pick_telemetry_port_base()
        self.telemetry_dir.mkdir(parents=True, exist_ok=True)
        if base is not None:
            env[ENV_TELEMETRY_PORT_BASE] = str(base)
        env[ENV_TELEMETRY_DIR] = str(self.telemetry_dir)
        if self._aggregator is None:
            # fresh run: a PREVIOUS run's sink files must not read as
            # this cohort's last-known state (they would defeat the
            # aggregator's startup grace and leak foreign snapshots
            # into the federated view/dossier). Cleared only here —
            # across THIS run's generations the files are the dead
            # workers' final states the dossier needs.
            for f in self.telemetry_dir.glob("worker_*.json"):
                try:
                    f.unlink()
                except OSError:
                    pass
            self._aggregator = ClusterAggregator(
                num_workers=self.num_workers, port_base=base,
                sink_dir=self.telemetry_dir,
                heartbeat_dir=self.heartbeat_dir,
                restarts=lambda: self._restart_count)
        else:
            self._aggregator.set_port_base(base)

    def _start_telemetry_surface(self) -> None:
        """Cluster HTTP surface + federated SLO engine (idempotent)."""
        if self._aggregator is None:
            return
        if self._cluster_engine is None:
            try:
                from deeplearning4j_tpu.observability.federation import (
                    default_cluster_rules,
                )
                from deeplearning4j_tpu.observability.slo import (
                    HealthEngine,
                )

                rules = (list(self.cluster_slo_rules)
                         if self.cluster_slo_rules is not None
                         else default_cluster_rules())
                self._cluster_engine = HealthEngine(
                    rules, registries=self._aggregator.registries(),
                    interval_s=max(1.0, self.telemetry_poll_interval_s))
                self._cluster_engine.start()
            except Exception:  # noqa: BLE001 — telemetry never fails
                self._cluster_engine = None  # supervision
        if self._cluster_server is None \
                and self.cluster_server_port is not None:
            try:
                from deeplearning4j_tpu.observability.federation import (
                    ClusterTelemetryServer,
                )

                self._cluster_server = ClusterTelemetryServer(
                    self._aggregator, port=self.cluster_server_port,
                    engine=self._cluster_engine,
                    max_staleness_s=self.telemetry_poll_interval_s)
                self._cluster_server.start()
            except Exception:  # noqa: BLE001
                self._cluster_server = None
        if self._poll_thread is None:
            # polling runs on its own thread: a wedged worker blocks a
            # fetch for fetch_timeout_s, and that must never delay the
            # watch loop's exit/hang detection
            self._poll_stop.clear()
            self._poll_thread = threading.Thread(
                target=self._poll_loop, daemon=True,
                name="supervisor-telemetry")
            self._poll_thread.start()

    def _poll_loop(self):
        while not self._poll_stop.wait(self.telemetry_poll_interval_s):
            try:
                self._aggregator.poll()
            except Exception:  # noqa: BLE001 — telemetry never fails
                pass           # supervision

    def _stop_telemetry_surface(self) -> None:
        self._poll_stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=10)
            self._poll_thread = None
        if self._aggregator is not None:
            try:
                self._aggregator.close()  # releases fetch-pool threads
            except Exception:  # noqa: BLE001
                pass
        if self._cluster_server is not None:
            try:
                self._cluster_server.stop()
            except Exception:  # noqa: BLE001
                pass
            self._cluster_server = None
        if self._cluster_engine is not None:
            try:
                self._cluster_engine.stop()
            except Exception:  # noqa: BLE001
                pass
            self._cluster_engine = None

    def _write_cluster_dossier(self, failure: str) -> Optional[str]:
        """On cohort teardown: one final poll (the dead worker's file
        sink still holds its last pre-crash snapshot), then the whole
        last-known cluster view — worker table, merged timeline, every
        worker's final snapshot — into a crash report.

        Written WITHOUT ``utils.crash.write_crash_report``: that path
        imports jax and enumerates devices, and a supervisor that
        initializes an accelerator backend between generations would
        hold the very devices its relaunched workers need."""
        if self._aggregator is None:
            return None
        try:
            self._aggregator.poll()
        except Exception:  # noqa: BLE001
            pass
        try:
            import datetime
            import json

            crash_dir = Path(os.environ.get(ENV_CRASH_DIR,
                                            str(self.workdir)))
            crash_dir.mkdir(parents=True, exist_ok=True)
            report = {
                "timestamp": datetime.datetime.now().isoformat(),
                "pid": os.getpid(),
                "kind": "supervisor_cluster_dossier",
                "extra": {
                    "supervisor_failure": failure,
                    "generation": self.generation,
                    "cluster_dossier": self._aggregator.dossier(),
                },
            }
            try:
                from deeplearning4j_tpu.observability.flightrecorder import (  # noqa: E501
                    get_flight_recorder,
                )

                report["flight_recorder"] = \
                    get_flight_recorder().dump(last_seconds=120.0)
            except Exception:  # noqa: BLE001
                pass
            # generation + microseconds uniquify: rapid launch-crash
            # loops (sub-second backoff) must not overwrite the
            # previous generation's dossier — the forensic artifact
            # this path exists to preserve
            stamp = datetime.datetime.now().strftime("%Y%m%d-%H%M%S-%f")
            path = crash_dir / (f"dl4j-tpu-crash-{stamp}-cluster-"
                                f"g{self.generation}-{os.getpid()}.json")
            path.write_text(json.dumps(report, indent=2, default=str))
            try:
                from deeplearning4j_tpu.observability import (
                    metrics as _obsm,
                )

                if _obsm.enabled():
                    _obsm.get_resilience_metrics() \
                         .crash_reports_total.inc()
            except Exception:  # noqa: BLE001
                pass
            _flight("supervisor.cluster_dossier",
                    generation=self.generation, path=str(path))
            return str(path)
        except Exception:  # noqa: BLE001 — reporting never blocks the
            return None    # relaunch

    # -- cohort lifecycle ----------------------------------------------------

    def _argv(self, worker_id: int) -> List[str]:
        if callable(self.command):
            return list(self.command(worker_id, self.generation))
        return list(self.command)

    def _launch_cohort(self, gen_env: Dict[str, str]):
        # heartbeats are per-generation: a stale beacon from the killed
        # previous cohort must not read as a dead peer of the new one
        hb = self.heartbeat_dir
        if hb.is_dir():
            for f in hb.glob("proc_*.json"):
                try:
                    f.unlink()
                except OSError:
                    pass
        hb.mkdir(parents=True, exist_ok=True)
        if self.telemetry:
            self._arm_telemetry(gen_env)
        self._procs, self._logs = [], []
        for wid in range(self.num_workers):
            env = dict(self.env)
            env.update(gen_env)
            env[ENV_WORKER_ID] = str(wid)
            env[ENV_NUM_WORKERS] = str(self.num_workers)
            env[ENV_GENERATION] = str(self.generation)
            env[ENV_HEARTBEAT_DIR] = str(hb)
            env[ENV_HEARTBEAT_INTERVAL] = str(self.heartbeat_interval_s)
            log_path = self.worker_log(wid)
            log = open(log_path, "w")
            try:
                proc = subprocess.Popen(
                    self._argv(wid), env=env, stdout=log,
                    stderr=subprocess.STDOUT,
                    start_new_session=True)  # one worker's SIGKILL storm
            finally:                         # never hits the supervisor
                log.close()
            self._procs.append(proc)
            self._logs.append(log_path)
        _flight("supervisor.launch", generation=self.generation,
                num_workers=self.num_workers,
                pids=[p.pid for p in self._procs])

    def _hung_workers(self) -> List[int]:
        if self.heartbeat_timeout_s is None:
            return []
        try:
            # progress staleness, not beacon staleness: a worker stuck in
            # a collective still runs its beacon thread — the stamp its
            # train loop stopped touching is what goes stale
            return dead_peers(
                self.heartbeat_dir, timeout_s=self.heartbeat_timeout_s,
                progress_timeout_s=self.heartbeat_timeout_s)
        except OSError:
            return []

    @staticmethod
    def _signal_worker(p: subprocess.Popen, sig: int):
        """Signal the worker's whole process GROUP (each worker got its
        own session via start_new_session): a worker that wraps the real
        trainer in a shell/launcher must not leave grandchildren holding
        the coordinator port or heartbeat files past teardown."""
        try:
            os.killpg(p.pid, sig)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                p.send_signal(sig)
            except OSError:
                pass

    def _terminate_cohort(self, reason: str, first: Optional[int] = None):
        for p in self._procs:
            if p.poll() is None:
                self._signal_worker(p, signal.SIGTERM)
        deadline = time.monotonic() + self.grace_s
        for p in self._procs:
            remaining = deadline - time.monotonic()
            try:
                p.wait(timeout=max(0.01, remaining))
            except subprocess.TimeoutExpired:
                self._signal_worker(p, signal.SIGKILL)
                p.wait()
        for wid, p in enumerate(self._procs):
            why = reason if wid == first else "cohort"
            self.exits.append(WorkerExit(
                generation=self.generation, worker_id=wid,
                returncode=p.returncode, reason=why,
                log_path=str(self._logs[wid])))

    def _watch_cohort(self) -> Optional[str]:
        """Block until the generation resolves; returns None on success
        (all workers exited 0) or the failure reason."""
        while True:
            codes = [p.poll() for p in self._procs]
            bad = next((i for i, c in enumerate(codes)
                        if c is not None and c != 0), None)
            if bad is not None:
                _flight("supervisor.worker_exit",
                        generation=self.generation, worker=bad,
                        returncode=codes[bad])
                self._terminate_cohort("exit", first=bad)
                return f"worker {bad} exited {codes[bad]}"
            if all(c == 0 for c in codes):
                for wid, p in enumerate(self._procs):
                    self.exits.append(WorkerExit(
                        generation=self.generation, worker_id=wid,
                        returncode=0, reason="exit",
                        log_path=str(self._logs[wid])))
                return None
            hung = [w for w in self._hung_workers()
                    if w < len(codes) and codes[w] is None]
            if hung:
                _flight("supervisor.worker_hang",
                        generation=self.generation, workers=hung)
                self._terminate_cohort("hang", first=hung[0])
                return f"worker(s) {hung} hung (stale heartbeat progress)"
            time.sleep(self.poll_interval_s)

    # -- run -----------------------------------------------------------------

    def run(self) -> SupervisorResult:
        """Supervise until the cohort completes; relaunch on failure up
        to ``max_restarts`` times, then raise :class:`SupervisorGaveUp`."""
        self.workdir.mkdir(parents=True, exist_ok=True)
        restarts = 0
        try:
            while True:
                self.generation += 1
                gen_env = dict(self.on_generation(self.generation)
                               if self.on_generation is not None else {})
                self._launch_cohort(gen_env)
                self._start_telemetry_surface()
                failure = self._watch_cohort()
                if failure is None:
                    _flight("supervisor.complete",
                            generation=self.generation, restarts=restarts)
                    return SupervisorResult(generations=self.generation,
                                            restarts=restarts,
                                            exits=self.exits)
                # cohort teardown: the aggregator's last-known view of
                # every worker (the dead one's final snapshot included)
                # becomes the crash dossier before anything relaunches
                self._write_cluster_dossier(failure)
                if restarts >= self.max_restarts:
                    _flight("supervisor.gave_up",
                            generation=self.generation,
                            restarts=restarts, failure=failure)
                    raise SupervisorGaveUp(
                        f"cohort failed {restarts + 1}x (restart budget "
                        f"{self.max_restarts}); last failure: {failure}",
                        self.exits)
                restarts += 1
                self._restart_count = restarts
                delay = next(self._delays)
                _flight("supervisor.restart", generation=self.generation,
                        restarts=restarts, failure=failure,
                        backoff_s=round(delay, 3))
                try:
                    from deeplearning4j_tpu.observability import (
                        metrics as _obsm,
                    )

                    if _obsm.enabled():
                        _obsm.get_resilience_metrics() \
                             .supervisor_restarts_total.inc()
                except Exception:  # noqa: BLE001
                    pass
                time.sleep(delay)
        finally:
            self._stop_telemetry_surface()

    def stop(self):
        """Terminate any live workers (cleanup path for callers that
        abandon a run mid-flight)."""
        for p in self._procs:
            if p.poll() is None:
                self._signal_worker(p, signal.SIGTERM)
                try:
                    p.wait(timeout=self.grace_s)
                except subprocess.TimeoutExpired:
                    self._signal_worker(p, signal.SIGKILL)


def worker_identity() -> Dict[str, int]:
    """The supervisor-provided identity of this worker process
    (``{"worker_id", "num_workers", "generation"}``; zeros/ones when not
    running under a supervisor) — what a worker script reads to wire
    ``distributed.initialize(process_id=..., num_processes=...)``.
    Delegates to the observability layer's parser so every consumer
    (snapshots, crash reports, worker scripts) agrees on junk-env
    semantics (degrade to defaults, never raise)."""
    from deeplearning4j_tpu.observability.federation import (
        worker_identity as _identity,
    )

    return _identity()


def install_sigterm_teardown(sup: ElasticSupervisor) -> bool:
    """Install a SIGTERM handler that tears the cohort down with the
    supervisor (a systemd/k8s stop of the supervisor must not orphan
    workers); returns False off-main-thread where handlers cannot be
    installed. Opt-in — call it after constructing the supervisor."""
    def _handler(*_):
        sup.stop()
        sys.exit(143)

    try:
        signal.signal(signal.SIGTERM, _handler)
        return True
    except ValueError:  # non-main thread
        return False
