"""Elastic training supervisor: launch N workers, relaunch the cohort
on death or hang, resume from the latest verified checkpoint.

The reference ran multi-worker training under ParallelWrapper /
SharedTrainingMaster, whose production value was surviving worker loss
(SURVEY §2.6, §5.3). jax has no supervisor — a SIGKILLed worker leaves
its peers stalled in the next collective until the watchdog
(resilience/cluster.py) times them out, and then *nothing restarts the
job*. This module is that missing process-level layer:

- :class:`ElasticSupervisor` launches ``num_workers`` subprocesses (one
  command per worker, parameterized by env: worker id, world size,
  generation, heartbeat dir), then monitors them:

  * a worker exiting non-zero (or being signal-killed) fails the
    *cohort* — SPMD training cannot continue minus one replica;
  * a worker whose heartbeat progress stamp goes stale is *hung*
    (stuck in a collective whose peer died, or livelocked) and fails
    the cohort the same way;
  * all workers exiting 0 completes the run.

- On cohort failure the survivors are terminated (SIGTERM, grace,
  SIGKILL), and after a capped full-jitter backoff
  (``resilience.retry.backoff_delays``) the whole cohort is relaunched
  as generation N+1 — bounded by ``max_restarts``, after which
  :class:`SupervisorGaveUp` surfaces the full exit history.

Recovery correctness is the *worker's* job: a worker that trains via
``FaultTolerantTrainer.fit(resume=True)`` (or
``PreemptionCheckpointer.resume``) restores the latest **verified**
checkpoint on relaunch, so the relaunched cohort resumes at the exact
rolled-back step — the supervisor only guarantees the relaunch happens,
with fresh coordination state per generation (``on_generation`` mints
per-generation env, e.g. a new coordinator port).

Everything is observable: ``supervisor.*`` flight-recorder events,
``resilience_supervisor_restarts_total`` on the shared registry, and
per-worker log files under ``log_dir``. Stdlib only.
"""

from __future__ import annotations

import dataclasses
import os
import random
import signal
import subprocess
import sys
import time
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Union

from deeplearning4j_tpu.resilience.cluster import (
    ENV_HEARTBEAT_DIR,
    ENV_HEARTBEAT_INTERVAL,
    dead_peers,
)
from deeplearning4j_tpu.resilience.retry import backoff_delays

ENV_WORKER_ID = "DL4J_TPU_WORKER_ID"
ENV_NUM_WORKERS = "DL4J_TPU_NUM_WORKERS"
ENV_GENERATION = "DL4J_TPU_GENERATION"


@dataclasses.dataclass
class WorkerExit:
    """One worker's terminal observation within a generation."""

    generation: int
    worker_id: int
    returncode: Optional[int]  # None = killed by the supervisor (hang)
    reason: str                # "exit" | "hang" | "cohort"
    log_path: Optional[str] = None


class SupervisorGaveUp(RuntimeError):
    """The restart budget is exhausted; carries the full exit history."""

    def __init__(self, msg: str, exits: List[WorkerExit]):
        super().__init__(msg)
        self.exits = exits


@dataclasses.dataclass
class SupervisorResult:
    """A completed run: how many generations it took and every exit
    observed along the way (empty when generation 1 just worked)."""

    generations: int
    restarts: int
    exits: List[WorkerExit]


def _flight(kind: str, **data):
    try:
        from deeplearning4j_tpu.observability.flightrecorder import (
            record_event,
        )

        record_event(kind, **data)
    except Exception:  # noqa: BLE001 — telemetry never fails supervision
        pass


class ElasticSupervisor:
    """Launch, watch, and relaunch a training-worker cohort.

    ``command``: the worker argv (one list used for every worker — the
    worker reads its identity from env), or a callable
    ``(worker_id, generation) -> argv``. Each worker's env carries
    ``DL4J_TPU_WORKER_ID`` / ``DL4J_TPU_NUM_WORKERS`` /
    ``DL4J_TPU_GENERATION`` plus the heartbeat directory; workers that
    want hang detection call
    ``resilience.cluster.heartbeat_from_env()`` and ``touch()`` once per
    step (cheap — in-memory stamp). Workers without heartbeats are still
    supervised for exits, just not for hangs.

    ``on_generation``: optional ``(generation) -> dict`` returning extra
    env vars for that generation — the hook that mints a fresh
    coordinator port per relaunch (gRPC coordination state does not
    survive its processes).

    Usage::

        sup = ElasticSupervisor([sys.executable, "worker.py"],
                                num_workers=2, max_restarts=3,
                                workdir=run_dir)
        result = sup.run()        # returns when all workers exit 0
    """

    def __init__(
        self,
        command: Union[Sequence[str], Callable[[int, int], Sequence[str]]],
        *,
        num_workers: int,
        max_restarts: int = 3,
        workdir: Optional[str | Path] = None,
        env: Optional[Dict[str, str]] = None,
        on_generation: Optional[Callable[[int], Dict[str, str]]] = None,
        heartbeat_timeout_s: Optional[float] = None,
        heartbeat_interval_s: float = 0.25,
        poll_interval_s: float = 0.1,
        grace_s: float = 5.0,
        backoff_base_s: float = 0.25,
        backoff_max_s: float = 30.0,
        backoff_jitter: float = 0.5,
        seed: int = 0,
    ):
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
        self.command = command
        self.num_workers = num_workers
        self.max_restarts = max_restarts
        self.workdir = Path(workdir) if workdir is not None else \
            Path(".") / "supervisor-run"
        self.env = dict(env) if env is not None else dict(os.environ)
        self.on_generation = on_generation
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.heartbeat_interval_s = heartbeat_interval_s
        self.poll_interval_s = poll_interval_s
        self.grace_s = grace_s
        self._delays = backoff_delays(
            base=backoff_base_s, cap=backoff_max_s, jitter=backoff_jitter,
            rng=random.Random(seed))
        self.exits: List[WorkerExit] = []
        self.generation = 0
        self._procs: List[subprocess.Popen] = []
        self._logs: List[Path] = []

    # -- introspection -------------------------------------------------------

    @property
    def heartbeat_dir(self) -> Path:
        return self.workdir / "heartbeats"

    def worker_log(self, worker_id: int,
                   generation: Optional[int] = None) -> Path:
        gen = self.generation if generation is None else generation
        return self.workdir / f"gen{gen}_worker{worker_id}.log"

    # -- cohort lifecycle ----------------------------------------------------

    def _argv(self, worker_id: int) -> List[str]:
        if callable(self.command):
            return list(self.command(worker_id, self.generation))
        return list(self.command)

    def _launch_cohort(self, gen_env: Dict[str, str]):
        # heartbeats are per-generation: a stale beacon from the killed
        # previous cohort must not read as a dead peer of the new one
        hb = self.heartbeat_dir
        if hb.is_dir():
            for f in hb.glob("proc_*.json"):
                try:
                    f.unlink()
                except OSError:
                    pass
        hb.mkdir(parents=True, exist_ok=True)
        self._procs, self._logs = [], []
        for wid in range(self.num_workers):
            env = dict(self.env)
            env.update(gen_env)
            env[ENV_WORKER_ID] = str(wid)
            env[ENV_NUM_WORKERS] = str(self.num_workers)
            env[ENV_GENERATION] = str(self.generation)
            env[ENV_HEARTBEAT_DIR] = str(hb)
            env[ENV_HEARTBEAT_INTERVAL] = str(self.heartbeat_interval_s)
            log_path = self.worker_log(wid)
            log = open(log_path, "w")
            try:
                proc = subprocess.Popen(
                    self._argv(wid), env=env, stdout=log,
                    stderr=subprocess.STDOUT,
                    start_new_session=True)  # one worker's SIGKILL storm
            finally:                         # never hits the supervisor
                log.close()
            self._procs.append(proc)
            self._logs.append(log_path)
        _flight("supervisor.launch", generation=self.generation,
                num_workers=self.num_workers,
                pids=[p.pid for p in self._procs])

    def _hung_workers(self) -> List[int]:
        if self.heartbeat_timeout_s is None:
            return []
        try:
            # progress staleness, not beacon staleness: a worker stuck in
            # a collective still runs its beacon thread — the stamp its
            # train loop stopped touching is what goes stale
            return dead_peers(
                self.heartbeat_dir, timeout_s=self.heartbeat_timeout_s,
                progress_timeout_s=self.heartbeat_timeout_s)
        except OSError:
            return []

    @staticmethod
    def _signal_worker(p: subprocess.Popen, sig: int):
        """Signal the worker's whole process GROUP (each worker got its
        own session via start_new_session): a worker that wraps the real
        trainer in a shell/launcher must not leave grandchildren holding
        the coordinator port or heartbeat files past teardown."""
        try:
            os.killpg(p.pid, sig)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                p.send_signal(sig)
            except OSError:
                pass

    def _terminate_cohort(self, reason: str, first: Optional[int] = None):
        for p in self._procs:
            if p.poll() is None:
                self._signal_worker(p, signal.SIGTERM)
        deadline = time.monotonic() + self.grace_s
        for p in self._procs:
            remaining = deadline - time.monotonic()
            try:
                p.wait(timeout=max(0.01, remaining))
            except subprocess.TimeoutExpired:
                self._signal_worker(p, signal.SIGKILL)
                p.wait()
        for wid, p in enumerate(self._procs):
            why = reason if wid == first else "cohort"
            self.exits.append(WorkerExit(
                generation=self.generation, worker_id=wid,
                returncode=p.returncode, reason=why,
                log_path=str(self._logs[wid])))

    def _watch_cohort(self) -> Optional[str]:
        """Block until the generation resolves; returns None on success
        (all workers exited 0) or the failure reason."""
        while True:
            codes = [p.poll() for p in self._procs]
            bad = next((i for i, c in enumerate(codes)
                        if c is not None and c != 0), None)
            if bad is not None:
                _flight("supervisor.worker_exit",
                        generation=self.generation, worker=bad,
                        returncode=codes[bad])
                self._terminate_cohort("exit", first=bad)
                return f"worker {bad} exited {codes[bad]}"
            if all(c == 0 for c in codes):
                for wid, p in enumerate(self._procs):
                    self.exits.append(WorkerExit(
                        generation=self.generation, worker_id=wid,
                        returncode=0, reason="exit",
                        log_path=str(self._logs[wid])))
                return None
            hung = [w for w in self._hung_workers()
                    if w < len(codes) and codes[w] is None]
            if hung:
                _flight("supervisor.worker_hang",
                        generation=self.generation, workers=hung)
                self._terminate_cohort("hang", first=hung[0])
                return f"worker(s) {hung} hung (stale heartbeat progress)"
            time.sleep(self.poll_interval_s)

    # -- run -----------------------------------------------------------------

    def run(self) -> SupervisorResult:
        """Supervise until the cohort completes; relaunch on failure up
        to ``max_restarts`` times, then raise :class:`SupervisorGaveUp`."""
        self.workdir.mkdir(parents=True, exist_ok=True)
        restarts = 0
        while True:
            self.generation += 1
            gen_env = dict(self.on_generation(self.generation)
                           if self.on_generation is not None else {})
            self._launch_cohort(gen_env)
            failure = self._watch_cohort()
            if failure is None:
                _flight("supervisor.complete", generation=self.generation,
                        restarts=restarts)
                return SupervisorResult(generations=self.generation,
                                        restarts=restarts, exits=self.exits)
            if restarts >= self.max_restarts:
                _flight("supervisor.gave_up", generation=self.generation,
                        restarts=restarts, failure=failure)
                raise SupervisorGaveUp(
                    f"cohort failed {restarts + 1}x (restart budget "
                    f"{self.max_restarts}); last failure: {failure}",
                    self.exits)
            restarts += 1
            delay = next(self._delays)
            _flight("supervisor.restart", generation=self.generation,
                    restarts=restarts, failure=failure,
                    backoff_s=round(delay, 3))
            try:
                from deeplearning4j_tpu.observability import metrics as _obsm

                if _obsm.enabled():
                    _obsm.get_resilience_metrics() \
                         .supervisor_restarts_total.inc()
            except Exception:  # noqa: BLE001
                pass
            time.sleep(delay)

    def stop(self):
        """Terminate any live workers (cleanup path for callers that
        abandon a run mid-flight)."""
        for p in self._procs:
            if p.poll() is None:
                self._signal_worker(p, signal.SIGTERM)
                try:
                    p.wait(timeout=self.grace_s)
                except subprocess.TimeoutExpired:
                    self._signal_worker(p, signal.SIGKILL)


def worker_identity() -> Dict[str, int]:
    """The supervisor-provided identity of this worker process
    (``{"worker_id", "num_workers", "generation"}``; zeros/ones when not
    running under a supervisor) — what a worker script reads to wire
    ``distributed.initialize(process_id=..., num_processes=...)``."""
    return {
        "worker_id": int(os.environ.get(ENV_WORKER_ID, "0")),
        "num_workers": int(os.environ.get(ENV_NUM_WORKERS, "1")),
        "generation": int(os.environ.get(ENV_GENERATION, "1")),
    }


def install_sigterm_teardown(sup: ElasticSupervisor) -> bool:
    """Install a SIGTERM handler that tears the cohort down with the
    supervisor (a systemd/k8s stop of the supervisor must not orphan
    workers); returns False off-main-thread where handlers cannot be
    installed. Opt-in — call it after constructing the supervisor."""
    def _handler(*_):
        sup.stop()
        sys.exit(143)

    try:
        signal.signal(signal.SIGTERM, _handler)
        return True
    except ValueError:  # non-main thread
        return False
