"""Elastic training supervisor: launch N workers, relaunch the cohort
on death or hang, resume from the latest verified checkpoint — and,
when a slot is *permanently* gone, keep training on the survivors.

The reference ran multi-worker training under ParallelWrapper /
SharedTrainingMaster, whose production value was surviving worker loss
(SURVEY §2.6, §5.3). jax has no supervisor — a SIGKILLed worker leaves
its peers stalled in the next collective until the watchdog
(resilience/cluster.py) times them out, and then *nothing restarts the
job*. This module is that missing process-level layer:

- :class:`ElasticSupervisor` launches ``num_workers`` subprocesses (one
  command per worker, parameterized by env: worker id, world size,
  generation, heartbeat dir), then monitors them:

  * a worker exiting non-zero (or being signal-killed) fails the
    *cohort* — SPMD training cannot continue minus one replica;
  * a worker whose heartbeat progress stamp goes stale is *hung*
    (stuck in a collective whose peer died, or livelocked) and fails
    the cohort the same way;
  * all workers exiting 0 completes the run.

- On cohort failure the survivors are terminated (SIGTERM, grace,
  SIGKILL), and after a capped full-jitter backoff
  (``resilience.retry.backoff_delays``) the whole cohort is relaunched
  as generation N+1 — bounded by ``max_restarts``, after which
  :class:`SupervisorGaveUp` surfaces the full exit history.

**Degraded mode** (``min_workers`` armed): relaunch-at-same-N assumes
every failure is transient, so a permanently lost slot (host gone, port
unbindable, crash loop) burns the whole restart budget and still ends
in :class:`SupervisorGaveUp`. With ``min_workers`` set the supervisor
instead *classifies* failures per slot — ``dead_slot_threshold``
consecutive immediate exits (younger than ``immediate_exit_s``) from
one slot, an explicit :meth:`ElasticSupervisor.mark_slot_dead`, or the
env-injectable ``supervisor.slot_dead`` fault — and on a dead slot
**shrinks to the survivors**: the cohort is torn down, worker ids are
compacted (slot identity rides along as ``DL4J_TPU_SLOT_ID``), the
per-generation env is re-derived for the smaller world
(``DL4J_TPU_NUM_WORKERS``, a fresh telemetry port base sized to the
survivor count, a fresh coordinator port via ``on_generation``), and
the cohort relaunches at N-k. Workers resume from the latest verified
checkpoint through the existing topology-independent restore, and the
data layer re-derives each worker's shard from the new ``(worker_id,
num_workers)`` under an explicit shrink policy
(``data.iterators.ShrinkPolicy``: preserve the global batch — each
survivor's share grows — or preserve the per-worker batch and accept
degraded throughput). A background **capacity probe** then retests the
dead slots on a jittered backoff (bind the slot's ports + an optional
user ``slot_healthy`` callback) and, once every dead slot probes
healthy, **re-expands to full N at the next checkpoint boundary**
(a new entry in ``checkpoint_dir``'s rotation index; immediately when
no ``checkpoint_dir`` is armed) so the planned teardown never loses a
step. Every topology transition writes a cluster crash dossier and is
observable: ``supervisor.shrink`` / ``supervisor.expand`` flight
events, ``cluster_workers_active`` / ``cluster_degraded`` gauges and
``supervisor_shrinks_total`` / ``supervisor_expands_total`` counters
federated through the cluster aggregator.

Recovery correctness is the *worker's* job: a worker that trains via
``FaultTolerantTrainer.fit(resume=True)`` (or
``PreemptionCheckpointer.resume``) restores the latest **verified**
checkpoint on relaunch, so the relaunched cohort resumes at the exact
rolled-back step — the supervisor only guarantees the relaunch happens,
with fresh coordination state per generation (``on_generation`` mints
per-generation env, e.g. a new coordinator port).

Everything is observable: ``supervisor.*`` flight-recorder events,
``resilience_supervisor_restarts_total`` on the shared registry, and
per-worker log files under ``log_dir``. Stdlib only.
"""

from __future__ import annotations

import dataclasses
import inspect
import os
import random
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path
from typing import (
    Callable,
    Dict,
    List,
    Optional,
    Sequence,
    Set,
    Union,
)

from deeplearning4j_tpu.analysis.lockcheck import make_lock
from deeplearning4j_tpu.resilience.cluster import (
    ENV_CRASH_DIR,
    ENV_HEARTBEAT_DIR,
    ENV_HEARTBEAT_INTERVAL,
    dead_peers,
)
from deeplearning4j_tpu.resilience.retry import backoff_delays

ENV_WORKER_ID = "DL4J_TPU_WORKER_ID"
ENV_NUM_WORKERS = "DL4J_TPU_NUM_WORKERS"
ENV_GENERATION = "DL4J_TPU_GENERATION"
# degraded-mode identity: the worker's PHYSICAL slot (stable across
# shrink/expand; worker ids are compacted per generation) and the
# cohort's full size, so the data layer can apply its shrink policy.
# data/iterators.py reads the same names (duplicated literals — this
# module must stay importable without jax, that one without this one).
ENV_SLOT_ID = "DL4J_TPU_SLOT_ID"
ENV_BASELINE_NUM_WORKERS = "DL4J_TPU_BASELINE_NUM_WORKERS"
ENV_SHRINK_POLICY = "DL4J_TPU_SHRINK_POLICY"
# cold-start robustness: armed for every generation when the supervisor
# is given a compile cache dir / warmup manifest, so a relaunch or a
# re-expanded cohort restores compiled artifacts + the traffic-derived
# shape mix instead of recompiling from scratch. Literals duplicated
# from runtime/compilecache.py + serving/warmstart.py — this module
# must stay importable without jax.
ENV_COMPILE_CACHE_DIR = "DL4J_TPU_COMPILE_CACHE_DIR"
ENV_WARMUP_MANIFEST = "DL4J_TPU_WARMUP_MANIFEST"

# the rotation-index file serde/checkpoint.py maintains — watched (never
# parsed) for the expansion checkpoint boundary, so the supervisor needs
# no jax/numpy import to know a new checkpoint landed
_CKPT_INDEX = "checkpoint_index.json"


@dataclasses.dataclass
class WorkerExit:
    """One worker's terminal observation within a generation."""

    generation: int
    worker_id: int
    returncode: Optional[int]  # None = killed by the supervisor (hang)
    reason: str                # "exit" | "hang" | "cohort" | "shrink"
    #                            | "expand"
    log_path: Optional[str] = None
    slot: Optional[int] = None  # physical slot (== worker_id until a
    #                             shrink compacts the ids)


class SupervisorGaveUp(RuntimeError):
    """The restart budget is exhausted; carries the full exit history."""

    def __init__(self, msg: str, exits: List[WorkerExit]):
        super().__init__(msg)
        self.exits = exits


@dataclasses.dataclass
class SupervisorResult:
    """A completed run: how many generations it took and every exit
    observed along the way (empty when generation 1 just worked)."""

    generations: int
    restarts: int
    exits: List[WorkerExit]
    shrinks: int = 0
    expands: int = 0
    dead_slots: List[int] = dataclasses.field(default_factory=list)
    final_workers: int = 0


@dataclasses.dataclass
class _GenOutcome:
    """How one generation resolved (``_watch_cohort``'s verdict)."""

    kind: str                  # "ok" | "fail" | "expand"
    failure: Optional[str] = None
    worker: Optional[int] = None      # first failing worker index
    slot: Optional[int] = None        # ... and its physical slot
    reason: Optional[str] = None      # "exit" | "hang" | "shrink"
    lifetime_s: float = 0.0


def _flight(kind: str, **data):
    try:
        from deeplearning4j_tpu.observability.flightrecorder import (
            record_event,
        )

        record_event(kind, **data)
    except Exception:  # noqa: BLE001 — telemetry never fails supervision
        pass


class ElasticSupervisor:
    """Launch, watch, and relaunch a training-worker cohort.

    ``command``: the worker argv (one list used for every worker — the
    worker reads its identity from env), or a callable
    ``(worker_id, generation) -> argv``. Each worker's env carries
    ``DL4J_TPU_WORKER_ID`` / ``DL4J_TPU_NUM_WORKERS`` /
    ``DL4J_TPU_GENERATION`` (plus ``DL4J_TPU_SLOT_ID`` /
    ``DL4J_TPU_BASELINE_NUM_WORKERS`` / ``DL4J_TPU_SHRINK_POLICY`` for
    the degraded-mode data plane) and the heartbeat directory; workers
    that want hang detection call
    ``resilience.cluster.heartbeat_from_env()`` and ``touch()`` once per
    step (cheap — in-memory stamp). Workers without heartbeats are still
    supervised for exits, just not for hangs.

    ``on_generation``: optional hook returning extra env vars for a
    generation — the hook that mints a fresh coordinator port per
    relaunch (gRPC coordination state does not survive its processes).
    Signature ``(generation) -> dict`` or
    ``(generation, num_workers) -> dict`` — the two-argument form sees
    the *effective* (possibly shrunken) cohort size.

    Degraded mode: pass ``min_workers`` (the smallest cohort worth
    running) to allow shrink-to-survivors; see the module docstring for
    the classification/shrink/probe/expand lifecycle. ``checkpoint_dir``
    points at the workers' (shared) verified-checkpoint directory so
    re-expansion waits for the next checkpoint boundary instead of
    tearing down mid-step window.

    Usage::

        sup = ElasticSupervisor([sys.executable, "worker.py"],
                                num_workers=2, max_restarts=3,
                                workdir=run_dir, min_workers=1,
                                checkpoint_dir=ckpt_dir)
        result = sup.run()        # returns when all workers exit 0
    """

    def __init__(
        self,
        command: Union[Sequence[str], Callable[[int, int], Sequence[str]]],
        *,
        num_workers: int,
        max_restarts: int = 3,
        workdir: Optional[str | Path] = None,
        env: Optional[Dict[str, str]] = None,
        on_generation: Optional[Callable[..., Dict[str, str]]] = None,
        heartbeat_timeout_s: Optional[float] = None,
        heartbeat_interval_s: float = 0.25,
        poll_interval_s: float = 0.1,
        grace_s: float = 5.0,
        backoff_base_s: float = 0.25,
        backoff_max_s: float = 30.0,
        backoff_jitter: float = 0.5,
        seed: int = 0,
        telemetry: bool = False,
        telemetry_poll_interval_s: float = 1.0,
        cluster_server_port: Optional[int] = None,
        cluster_slo_rules: Optional[Sequence] = None,
        min_workers: Optional[int] = None,
        dead_slot_threshold: int = 3,
        immediate_exit_s: float = 5.0,
        shrink_policy: Optional[str] = None,
        checkpoint_dir: Optional[str | Path] = None,
        probe_interval_s: float = 5.0,
        probe_max_interval_s: float = 60.0,
        probe_jitter: float = 0.5,
        slot_healthy: Optional[Callable[[int], bool]] = None,
        slot_ports: Optional[Callable[[int], Sequence[int]]] = None,
        max_topology_changes: int = 16,
        compile_cache_dir: Optional[str | Path] = None,
        warmup_manifest: Optional[str | Path] = None,
    ):
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
        if min_workers is not None and not 1 <= min_workers <= num_workers:
            raise ValueError(
                f"min_workers must be in [1, num_workers={num_workers}], "
                f"got {min_workers}")
        if dead_slot_threshold < 1:
            raise ValueError("dead_slot_threshold must be >= 1, got "
                             f"{dead_slot_threshold}")
        self.command = command
        self.num_workers = num_workers
        self.max_restarts = max_restarts
        self.workdir = Path(workdir) if workdir is not None else \
            Path(".") / "supervisor-run"
        self.env = dict(env) if env is not None else dict(os.environ)
        self.on_generation = on_generation
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.heartbeat_interval_s = heartbeat_interval_s
        self.poll_interval_s = poll_interval_s
        self.grace_s = grace_s
        self._delays = backoff_delays(
            base=backoff_base_s, cap=backoff_max_s, jitter=backoff_jitter,
            rng=random.Random(seed))
        self.exits: List[WorkerExit] = []
        self.generation = 0
        self._procs: List[subprocess.Popen] = []
        self._logs: List[Path] = []
        # -- degraded mode (shrink-to-survivors) -----------------------------
        self.min_workers = min_workers
        self.dead_slot_threshold = dead_slot_threshold
        self.immediate_exit_s = immediate_exit_s
        self.shrink_policy = shrink_policy
        self.checkpoint_dir = (Path(checkpoint_dir)
                               if checkpoint_dir is not None else None)
        self.probe_interval_s = probe_interval_s
        self.probe_max_interval_s = probe_max_interval_s
        self.probe_jitter = probe_jitter
        self.slot_healthy = slot_healthy
        self.slot_ports = slot_ports
        self.max_topology_changes = max_topology_changes
        # cold-start robustness: a workdir-relative default when True is
        # passed, any path used verbatim. Each generation's env carries
        # both, so relaunches AND re-expansions take traffic warm.
        if compile_cache_dir is True:
            compile_cache_dir = self.workdir / "compile_cache"
        if warmup_manifest is True:
            warmup_manifest = self.workdir / "warmup_manifest.json"
        self.compile_cache_dir = (Path(compile_cache_dir)
                                  if compile_cache_dir is not None else None)
        self.warmup_manifest = (Path(warmup_manifest)
                                if warmup_manifest is not None else None)
        self.dead_slots: Set[int] = set()
        self.shrinks = 0
        self.expands = 0
        self._fail_streak: Dict[int, int] = {}
        self._marked_dead: Set[int] = set()
        self._marked_lock = make_lock("ElasticSupervisor._marked_lock")
        self._gen_slots: List[int] = list(range(num_workers))
        self._launch_time = 0.0
        self._probe_thread: Optional[threading.Thread] = None
        self._probe_stop = threading.Event()
        self._expand_ready = threading.Event()
        self._ckpt_sig_at_ready = None
        self._probe_seed = seed + 1
        # probe-thread generation: a shrink supersedes any in-flight
        # probe pass, so a stale thread can never arm expansion for a
        # dead set it did not test. _probe_lock serializes the probe
        # state machine (dead-set mutation, epoch bump + ready-clear,
        # recheck + ready-set, and the shared backoff generator) across
        # the run thread and any number of probe threads.
        self._probe_epoch = 0
        self._probe_lock = make_lock("ElasticSupervisor._probe_lock")
        # ONE backoff schedule for the supervisor's lifetime: a slot
        # that flaps (probes healthy, crash-loops on expansion,
        # re-shrinks) keeps escalating toward probe_max_interval_s
        # instead of hammering on a fresh fast schedule every cycle
        self._probe_delays = None
        self._last_port_base: Optional[int] = None
        # -- cluster telemetry federation (observability/federation.py):
        # with telemetry=True each generation's workers get an exporter
        # port base + file-sink dir in env; the supervisor polls every
        # worker's snapshot each telemetry_poll_interval_s, serves the
        # federated view at /cluster/* (cluster_server_port: 0 =
        # ephemeral, None = no HTTP surface), runs a HealthEngine over
        # the federated registry (cluster_slo_rules: None = the default
        # worker-liveness rule), and buries the cohort's last-known
        # snapshots in a crash dossier on every teardown.
        self.telemetry = bool(telemetry)
        self.telemetry_poll_interval_s = float(telemetry_poll_interval_s)
        self.cluster_server_port = cluster_server_port
        self.cluster_slo_rules = cluster_slo_rules
        self._restart_count = 0
        self._aggregator = None
        self._cluster_server = None
        self._cluster_engine = None
        self._poll_thread: Optional[threading.Thread] = None
        self._poll_stop = threading.Event()

    # -- introspection -------------------------------------------------------

    @property
    def heartbeat_dir(self) -> Path:
        return self.workdir / "heartbeats"

    def worker_log(self, worker_id: int,
                   generation: Optional[int] = None) -> Path:
        gen = self.generation if generation is None else generation
        return self.workdir / f"gen{gen}_worker{worker_id}.log"

    @property
    def telemetry_dir(self) -> Path:
        return self.workdir / "telemetry"

    @property
    def aggregator(self):
        """The :class:`~deeplearning4j_tpu.observability.federation.
        ClusterAggregator` (None until the first telemetry-enabled
        launch)."""
        return self._aggregator

    @property
    def cluster_server(self):
        return self._cluster_server

    @property
    def cluster_url(self) -> Optional[str]:
        return (self._cluster_server.url
                if self._cluster_server is not None else None)

    @property
    def degraded(self) -> bool:
        """True while the cohort runs without its dead slots."""
        return bool(self.dead_slots)

    def active_slots(self) -> List[int]:
        """The physical slots the next (or current) generation runs —
        worker ids are their positions in this list."""
        return [s for s in range(self.num_workers)
                if s not in self.dead_slots]

    def mark_slot_dead(self, slot: int) -> None:
        """Classify ``slot`` permanently dead *now* (operator/scheduler
        knowledge the exit-history heuristic can't see: host
        decommissioned, maintenance drain). The watch loop tears the
        cohort down at its next poll and relaunches on the survivors.
        Requires degraded mode (``min_workers``), and refuses a mark
        that would take the cohort below the floor — silently consuming
        the operator's intent after a useless teardown would be worse
        than failing the call."""
        if not 0 <= slot < self.num_workers:
            raise ValueError(f"slot must be in [0, {self.num_workers}), "
                             f"got {slot}")
        if self.min_workers is None:
            raise RuntimeError(
                "mark_slot_dead requires degraded mode: construct the "
                "supervisor with min_workers=<floor> to allow shrinking")
        with self._marked_lock:
            survivors = [s for s in range(self.num_workers)
                         if s not in self.dead_slots
                         and s not in self._marked_dead and s != slot]
            if slot not in self.dead_slots \
                    and len(survivors) < self.min_workers:
                raise ValueError(
                    f"marking slot {slot} dead would leave "
                    f"{len(survivors)} worker(s), below "
                    f"min_workers={self.min_workers}")
            self._marked_dead.add(slot)

    # -- telemetry federation ------------------------------------------------

    def _pick_telemetry_port_base(self, n: Optional[int] = None
                                  ) -> Optional[int]:
        """A base port such that base..base+n-1 all bind right now
        (workers derive base + worker_id). ``n`` is the generation's
        *effective* cohort size — re-derived per generation so a
        shrunken cohort never inherits (or leaks) a dead slot's
        reservation. Racy by nature — a worker losing the race falls
        back to its file sink, which the aggregator reads anyway."""
        n = self.num_workers if n is None else n
        for _ in range(32):
            socks = []
            try:
                s0 = socket.socket()
                s0.bind(("127.0.0.1", 0))
                base = s0.getsockname()[1]
                socks.append(s0)
                ok = base + n <= 65535
                for i in range(1, n if ok else 0):
                    s = socket.socket()
                    try:
                        s.bind(("127.0.0.1", base + i))
                        socks.append(s)
                    except OSError:
                        ok = False
                        break
                if ok:
                    return base
            finally:
                for s in socks:
                    s.close()
        return None

    def _topology_info(self) -> dict:
        """What the aggregator publishes about the cohort's shape (the
        ``cluster_workers_active`` / ``cluster_degraded`` gauges and the
        time-in-degraded-mode counter feed from this)."""
        return {
            "workers_active": len(self.active_slots()),
            "workers_baseline": self.num_workers,
            "degraded": bool(self.dead_slots),
            "dead_slots": sorted(self.dead_slots),
            "shrinks": self.shrinks,
            "expands": self.expands,
        }

    def _arm_telemetry(self, env: Dict[str, str], n: int) -> None:
        """Per-generation telemetry env + aggregator (re)configuration;
        called from ``_launch_cohort`` before workers spawn. ``n`` is
        this generation's effective cohort size."""
        from deeplearning4j_tpu.observability.federation import (
            ENV_TELEMETRY_DIR,
            ENV_TELEMETRY_PORT_BASE,
            ClusterAggregator,
        )

        base = self._pick_telemetry_port_base(n)
        self._last_port_base = base
        self.telemetry_dir.mkdir(parents=True, exist_ok=True)
        if base is not None:
            env[ENV_TELEMETRY_PORT_BASE] = str(base)
        env[ENV_TELEMETRY_DIR] = str(self.telemetry_dir)
        if self._aggregator is None:
            # fresh run: a PREVIOUS run's sink files must not read as
            # this cohort's last-known state (they would defeat the
            # aggregator's startup grace and leak foreign snapshots
            # into the federated view/dossier). Cleared only here —
            # across THIS run's generations the files are the dead
            # workers' final states the dossier needs.
            for f in self.telemetry_dir.glob("worker_*.json"):
                try:
                    f.unlink()
                except OSError:
                    pass
            self._aggregator = ClusterAggregator(
                num_workers=n, port_base=base,
                sink_dir=self.telemetry_dir,
                heartbeat_dir=self.heartbeat_dir,
                restarts=lambda: self._restart_count,
                topology=self._topology_info,
                local_events=self._supervisor_events)
        else:
            # a shrink/expand changes the cohort size: re-derive the
            # polled worker-id range WITH the port base, or the
            # aggregator keeps polling (and failing on) dead slots'
            # stale reservations
            self._aggregator.set_cohort(n, port_base=base)

    def _cluster_m(self):
        """The aggregator's ClusterMetrics, or None without telemetry."""
        return (self._aggregator.metrics
                if self._aggregator is not None else None)

    def _supervisor_events(self) -> List[dict]:
        """This (supervisor) process's own ``supervisor.*`` flight
        events — merged into the cluster timeline so launches, shrinks
        and expansions appear next to the worker events they caused.
        Filtered to the supervisor namespace: the supervisor process's
        ring also carries unrelated local telemetry (tests, co-located
        training) that must not masquerade as cohort history."""
        try:
            from deeplearning4j_tpu.observability.flightrecorder import (
                get_flight_recorder,
            )

            return [e for e in get_flight_recorder().events()
                    if str(e.get("kind", "")).startswith("supervisor.")]
        except Exception:  # noqa: BLE001
            return []

    def _start_telemetry_surface(self) -> None:
        """Cluster HTTP surface + federated SLO engine (idempotent)."""
        if self._aggregator is None:
            return
        if self._cluster_engine is None:
            try:
                from deeplearning4j_tpu.observability.federation import (
                    default_cluster_rules,
                )
                from deeplearning4j_tpu.observability.slo import (
                    HealthEngine,
                )

                rules = (list(self.cluster_slo_rules)
                         if self.cluster_slo_rules is not None
                         else default_cluster_rules())
                self._cluster_engine = HealthEngine(
                    rules, registries=self._aggregator.registries(),
                    interval_s=max(1.0, self.telemetry_poll_interval_s))
                self._cluster_engine.start()
            except Exception:  # noqa: BLE001 — telemetry never fails
                self._cluster_engine = None  # supervision
        if self._cluster_server is None \
                and self.cluster_server_port is not None:
            try:
                from deeplearning4j_tpu.observability.federation import (
                    ClusterTelemetryServer,
                )

                self._cluster_server = ClusterTelemetryServer(
                    self._aggregator, port=self.cluster_server_port,
                    engine=self._cluster_engine,
                    max_staleness_s=self.telemetry_poll_interval_s)
                self._cluster_server.start()
            except Exception:  # noqa: BLE001
                self._cluster_server = None
        if self._poll_thread is None:
            # polling runs on its own thread: a wedged worker blocks a
            # fetch for fetch_timeout_s, and that must never delay the
            # watch loop's exit/hang detection
            self._poll_stop.clear()
            self._poll_thread = threading.Thread(
                target=self._poll_loop, daemon=True,
                name="supervisor-telemetry")
            self._poll_thread.start()

    def _poll_loop(self):
        while not self._poll_stop.wait(self.telemetry_poll_interval_s):
            try:
                self._aggregator.poll()
            except Exception:  # noqa: BLE001 — telemetry never fails
                pass           # supervision

    def _stop_telemetry_surface(self) -> None:
        self._poll_stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=10)
            self._poll_thread = None
        if self._aggregator is not None:
            try:
                self._aggregator.close()  # releases fetch-pool threads
            except Exception:  # noqa: BLE001
                pass
        if self._cluster_server is not None:
            try:
                self._cluster_server.stop()
            except Exception:  # noqa: BLE001
                pass
            self._cluster_server = None
        if self._cluster_engine is not None:
            try:
                self._cluster_engine.stop()
            except Exception:  # noqa: BLE001
                pass
            self._cluster_engine = None

    def _write_cluster_dossier(self, failure: str) -> Optional[str]:
        """On cohort teardown (failure OR planned topology transition):
        one final poll (the dead worker's file sink still holds its last
        pre-crash snapshot), then the whole last-known cluster view —
        worker table, merged timeline, every worker's final snapshot —
        into a crash report.

        Written WITHOUT ``utils.crash.write_crash_report``: that path
        imports jax and enumerates devices, and a supervisor that
        initializes an accelerator backend between generations would
        hold the very devices its relaunched workers need."""
        if self._aggregator is None:
            return None
        try:
            self._aggregator.poll()
        except Exception:  # noqa: BLE001
            pass
        try:
            import datetime
            import json

            crash_dir = Path(os.environ.get(ENV_CRASH_DIR,
                                            str(self.workdir)))
            crash_dir.mkdir(parents=True, exist_ok=True)
            dossier = self._aggregator.dossier()
            # incidents the cohort was carrying at teardown, hoisted to
            # the report's top level: the first question a post-mortem
            # asks is "was anything already firing when it died?"
            open_incidents = dossier.get("open_incidents", [])
            report = {
                "timestamp": datetime.datetime.now().isoformat(),
                "pid": os.getpid(),
                "kind": "supervisor_cluster_dossier",
                "open_incidents": open_incidents,
                "extra": {
                    "supervisor_failure": failure,
                    "generation": self.generation,
                    "topology": self._topology_info(),
                    "cluster_dossier": dossier,
                },
            }
            try:
                from deeplearning4j_tpu.observability.flightrecorder import (  # noqa: E501
                    get_flight_recorder,
                )

                report["flight_recorder"] = \
                    get_flight_recorder().dump(last_seconds=120.0)
            except Exception:  # noqa: BLE001
                pass
            # generation + microseconds uniquify: rapid launch-crash
            # loops (sub-second backoff) must not overwrite the
            # previous generation's dossier — the forensic artifact
            # this path exists to preserve
            stamp = datetime.datetime.now().strftime("%Y%m%d-%H%M%S-%f")
            path = crash_dir / (f"dl4j-tpu-crash-{stamp}-cluster-"
                                f"g{self.generation}-{os.getpid()}.json")
            path.write_text(json.dumps(report, indent=2, default=str))
            try:
                from deeplearning4j_tpu.observability import (
                    metrics as _obsm,
                )

                if _obsm.enabled():
                    _obsm.get_resilience_metrics() \
                         .crash_reports_total.inc()
            except Exception:  # noqa: BLE001
                pass
            _flight("supervisor.cluster_dossier",
                    generation=self.generation, path=str(path),
                    open_incidents=len(open_incidents))
            return str(path)
        except Exception:  # noqa: BLE001 — reporting never blocks the
            return None    # relaunch

    # -- cohort lifecycle ----------------------------------------------------

    def _argv(self, worker_id: int) -> List[str]:
        if callable(self.command):
            return list(self.command(worker_id, self.generation))
        return list(self.command)

    def _generation_env(self) -> Dict[str, str]:
        """The hook-minted extra env for this generation; the
        two-argument hook form also sees the effective cohort size."""
        if self.on_generation is None:
            return {}
        try:
            nparams = len(inspect.signature(
                self.on_generation).parameters)
        except (TypeError, ValueError):
            nparams = 1
        if nparams >= 2:
            return dict(self.on_generation(self.generation,
                                           len(self.active_slots())))
        return dict(self.on_generation(self.generation))

    def _launch_cohort(self, gen_env: Dict[str, str]):
        # heartbeats are per-generation: a stale beacon from the killed
        # previous cohort must not read as a dead peer of the new one
        hb = self.heartbeat_dir
        if hb.is_dir():
            for f in hb.glob("proc_*.json"):
                try:
                    f.unlink()
                except OSError:
                    pass
        hb.mkdir(parents=True, exist_ok=True)
        active = self.active_slots()
        n = len(active)
        if self.telemetry:
            self._arm_telemetry(gen_env, n)
        self._gen_slots = active
        self._procs, self._logs = [], []
        self._launch_time = time.monotonic()
        for wid, slot in enumerate(active):
            env = dict(self.env)
            env.update(gen_env)
            env[ENV_WORKER_ID] = str(wid)
            env[ENV_NUM_WORKERS] = str(n)
            env[ENV_GENERATION] = str(self.generation)
            env[ENV_SLOT_ID] = str(slot)
            env[ENV_BASELINE_NUM_WORKERS] = str(self.num_workers)
            if self.shrink_policy is not None:
                env[ENV_SHRINK_POLICY] = str(self.shrink_policy)
            env[ENV_HEARTBEAT_DIR] = str(hb)
            env[ENV_HEARTBEAT_INTERVAL] = str(self.heartbeat_interval_s)
            if self.compile_cache_dir is not None:
                self.compile_cache_dir.mkdir(parents=True, exist_ok=True)
                env[ENV_COMPILE_CACHE_DIR] = str(self.compile_cache_dir)
            if self.warmup_manifest is not None:
                env[ENV_WARMUP_MANIFEST] = str(self.warmup_manifest)
            log_path = self.worker_log(wid)
            log = open(log_path, "w")
            try:
                proc = subprocess.Popen(
                    self._argv(wid), env=env, stdout=log,
                    stderr=subprocess.STDOUT,
                    start_new_session=True)  # one worker's SIGKILL storm
            finally:                         # never hits the supervisor
                log.close()
            self._procs.append(proc)
            self._logs.append(log_path)
        _flight("supervisor.launch", generation=self.generation,
                num_workers=n, slots=active, degraded=self.degraded,
                pids=[p.pid for p in self._procs])
        m = self._cluster_m()
        if m is not None:
            try:
                m.workers_active.set(float(n))
                m.degraded.set(1.0 if self.degraded else 0.0)
            except Exception:  # noqa: BLE001 — telemetry never fails
                pass

    def _hung_workers(self) -> List[int]:
        if self.heartbeat_timeout_s is None:
            return []
        try:
            # progress staleness, not beacon staleness: a worker stuck in
            # a collective still runs its beacon thread — the stamp its
            # train loop stopped touching is what goes stale
            return dead_peers(
                self.heartbeat_dir, timeout_s=self.heartbeat_timeout_s,
                progress_timeout_s=self.heartbeat_timeout_s)
        except OSError:
            return []

    @staticmethod
    def _signal_worker(p: subprocess.Popen, sig: int):
        """Signal the worker's whole process GROUP (each worker got its
        own session via start_new_session): a worker that wraps the real
        trainer in a shell/launcher must not leave grandchildren holding
        the coordinator port or heartbeat files past teardown."""
        try:
            os.killpg(p.pid, sig)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                p.send_signal(sig)
            except OSError:
                pass

    def _terminate_cohort(self, reason: str, first: Optional[int] = None):
        for p in self._procs:
            if p.poll() is None:
                self._signal_worker(p, signal.SIGTERM)
        deadline = time.monotonic() + self.grace_s
        for p in self._procs:
            remaining = deadline - time.monotonic()
            try:
                p.wait(timeout=max(0.01, remaining))
            except subprocess.TimeoutExpired:
                self._signal_worker(p, signal.SIGKILL)
                p.wait()
        for wid, p in enumerate(self._procs):
            why = reason if wid == first or first is None else "cohort"
            self.exits.append(WorkerExit(
                generation=self.generation, worker_id=wid,
                returncode=p.returncode, reason=why,
                log_path=str(self._logs[wid]),
                slot=self._gen_slots[wid]))

    # -- degraded mode: classification / probe / expand ----------------------

    def _consume_marked(self) -> Set[int]:
        with self._marked_lock:
            marked, self._marked_dead = self._marked_dead, set()
        return marked

    def _classify_failure(self, out: _GenOutcome) -> Set[int]:
        """Which slots this failure proves permanently dead: K
        consecutive immediate exits from one slot, an external
        :meth:`mark_slot_dead`, or the ``supervisor.slot_dead``
        injectable fault (chaos testing the shrink path without a real
        crash loop)."""
        newly: Set[int] = set(self._consume_marked()) - self.dead_slots
        slot = out.slot
        if out.lifetime_s > self.immediate_exit_s:
            # the generation ran long before failing: EVERY slot was
            # healthy for a while, so nobody is crash-looping — isolated
            # immediate exits days apart must not accumulate into a
            # death sentence for a slot that ran fine in between
            self._fail_streak.clear()
        elif slot is not None and out.reason == "exit":
            self._fail_streak[slot] = self._fail_streak.get(slot, 0) + 1
            if self._fail_streak[slot] >= self.dead_slot_threshold:
                newly.add(slot)
        try:
            from deeplearning4j_tpu.resilience.faults import (
                get_fault_injector,
            )

            if get_fault_injector().fire("supervisor.slot_dead") is not None \
                    and slot is not None:
                newly.add(slot)
        except Exception:  # noqa: BLE001 — injection must never break
            pass           # real supervision
        return newly

    def _shrink(self, newly_dead: Set[int], failure: str) -> None:
        """Commit a topology shrink: record the dead slots, surface the
        transition (flight event + counters + dossier), and start the
        capacity probe that will earn the expansion back."""
        before = len(self.active_slots())
        with self._probe_lock:
            self.dead_slots |= newly_dead
        for s in newly_dead:
            self._fail_streak.pop(s, None)
        self.shrinks += 1
        after = len(self.active_slots())
        _flight("supervisor.shrink", generation=self.generation,
                dead_slots=sorted(newly_dead),
                all_dead_slots=sorted(self.dead_slots),
                from_workers=before, to_workers=after, cause=failure,
                policy=self.shrink_policy)
        m = self._cluster_m()
        if m is not None:
            try:
                m.shrinks_total.inc()
                m.degraded.set(1.0)
                m.workers_active.set(float(after))
            except Exception:  # noqa: BLE001
                pass
        self._start_probe()

    def _expand(self) -> None:
        """Commit the re-expansion: the probed-healthy slots rejoin and
        the cohort relaunches at full N from the checkpoint the boundary
        wait just observed."""
        before = len(self.active_slots())
        with self._probe_lock:
            healed = sorted(self.dead_slots)
            self.dead_slots.clear()
            self._expand_ready.clear()
        self.expands += 1
        _flight("supervisor.expand", generation=self.generation,
                healed_slots=healed, from_workers=before,
                to_workers=self.num_workers)
        m = self._cluster_m()
        if m is not None:
            try:
                m.expands_total.inc()
                m.degraded.set(0.0)
                m.workers_active.set(float(self.num_workers))
            except Exception:  # noqa: BLE001
                pass

    def _ckpt_signature(self):
        """Cheap identity of the newest checkpoint-index write (the
        expansion boundary detector — content is never parsed, so no
        jax/numpy enters the supervisor process)."""
        if self.checkpoint_dir is None:
            return None
        try:
            st = (self.checkpoint_dir / _CKPT_INDEX).stat()
            return (st.st_mtime_ns, st.st_size)
        except OSError:
            return None

    def _probe_slot(self, slot: int) -> bool:
        """One capacity retest of a dead slot: every port the slot needs
        must bind right now (``slot_ports`` when provided; else the
        slot's would-be telemetry port at the last armed base, skipped
        when that port sits inside the live survivors' range), and the
        user's ``slot_healthy`` callback (scheduler/host checks the
        supervisor can't see) must agree. With neither hook nor a
        telemetry base armed the probe degrades to a plain cooldown
        retry — expansion then leans on the escalating backoff and the
        ``max_topology_changes`` bound to contain a flapping slot."""
        ports: List[int] = []
        if self.slot_ports is not None:
            try:
                ports = [int(p) for p in self.slot_ports(slot)]
            except Exception:  # noqa: BLE001 — a broken hook reads as
                return False   # unhealthy, never as healthy
        elif self._last_port_base is not None:
            cand = self._last_port_base + slot
            if cand >= self._last_port_base + len(self._gen_slots):
                # outside the live survivors' port range: a squatter
                # (the slot's old tenant) still holding it means the
                # slot's resources are not back
                ports = [cand]
        ok = True
        for port in ports:
            s = socket.socket()
            try:
                s.bind(("127.0.0.1", port))
            except OSError:
                ok = False
            finally:
                s.close()
            if not ok:
                break
        if ok and self.slot_healthy is not None:
            try:
                ok = bool(self.slot_healthy(slot))
            except Exception:  # noqa: BLE001
                ok = False
        _flight("supervisor.probe", slot=slot, ok=ok, ports=ports)
        return ok

    def _start_probe(self) -> None:
        """(Re)arm the capacity probe for the CURRENT dead set. Always
        bumps the probe epoch and starts a fresh thread: an in-flight
        pass that was testing a smaller dead set is superseded — a
        stale thread must never arm expansion for slots it did not
        probe (the epoch check and the ready-set happen under one lock,
        so a superseded thread's arm is either rejected or already
        cleared here)."""
        with self._probe_lock:
            self._probe_epoch += 1
            self._expand_ready.clear()
        self._probe_stop.clear()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, args=(self._probe_epoch,),
            daemon=True, name="supervisor-capacity-probe")
        self._probe_thread.start()

    def _next_probe_delay(self) -> float:
        """One delay off the supervisor-lifetime backoff schedule.
        Locked: a superseded probe thread may overlap the new one for
        one wakeup, and two threads calling next() on one generator
        concurrently is a ValueError."""
        with self._probe_lock:
            if self._probe_delays is None:
                self._probe_delays = backoff_delays(
                    base=self.probe_interval_s,
                    cap=self.probe_max_interval_s,
                    jitter=self.probe_jitter,
                    rng=random.Random(self._probe_seed))
            return next(self._probe_delays)

    def _probe_loop(self, epoch: int):
        """Retest dead slots on a capped full-jitter backoff; once EVERY
        dead slot probes healthy, arm the expansion (the watch loop
        executes it at the next checkpoint boundary). Partial healing
        keeps probing — re-expansion restores full N, not N-k+1. The
        backoff generator persists across probe restarts so a flapping
        slot keeps escalating instead of resetting to the fast end."""
        while not self._probe_stop.wait(self._next_probe_delay()):
            if epoch != self._probe_epoch:
                return  # superseded by a newer probe thread
            dead = sorted(self.dead_slots)
            if not dead:
                return
            if all(self._probe_slot(s) for s in dead):
                with self._probe_lock:
                    if epoch != self._probe_epoch \
                            or sorted(self.dead_slots) != dead:
                        continue  # a shrink landed mid-pass: retest all
                    # boundary baseline is captured NOW: only a
                    # checkpoint written after the heal releases the
                    # expansion, so the relaunched full cohort resumes
                    # from a post-heal save
                    self._ckpt_sig_at_ready = self._ckpt_signature()
                    self._expand_ready.set()
                _flight("supervisor.expand_ready", healed_slots=dead)
                return

    def _stop_probe(self) -> None:
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5)
            self._probe_thread = None

    def _expansion_due(self) -> bool:
        """The probe armed expansion AND the checkpoint boundary passed
        (a new rotation-index write since the probe passed; immediate
        when no ``checkpoint_dir`` is armed)."""
        if not self._expand_ready.is_set():
            return False
        if self.checkpoint_dir is None:
            return True
        return self._ckpt_signature() != self._ckpt_sig_at_ready

    # -- watch ---------------------------------------------------------------

    def _watch_cohort(self) -> _GenOutcome:
        """Block until the generation resolves: success (all workers
        exited 0), failure (exit/hang/marked-dead slot), or a due
        expansion (planned teardown at the checkpoint boundary)."""
        while True:
            codes = [p.poll() for p in self._procs]
            bad = next((i for i, c in enumerate(codes)
                        if c is not None and c != 0), None)
            if bad is not None:
                lifetime = time.monotonic() - self._launch_time
                _flight("supervisor.worker_exit",
                        generation=self.generation, worker=bad,
                        slot=self._gen_slots[bad], returncode=codes[bad],
                        lifetime_s=round(lifetime, 3))
                self._terminate_cohort("exit", first=bad)
                return _GenOutcome(
                    "fail",
                    failure=(f"worker {bad} (slot {self._gen_slots[bad]}) "
                             f"exited {codes[bad]}"),
                    worker=bad, slot=self._gen_slots[bad], reason="exit",
                    lifetime_s=lifetime)
            if all(c == 0 for c in codes):
                for wid, p in enumerate(self._procs):
                    self.exits.append(WorkerExit(
                        generation=self.generation, worker_id=wid,
                        returncode=0, reason="exit",
                        log_path=str(self._logs[wid]),
                        slot=self._gen_slots[wid]))
                return _GenOutcome("ok")
            marked = {s for s in self._consume_marked()
                      if s in self._gen_slots}
            if marked:
                first = self._gen_slots.index(sorted(marked)[0])
                # re-queue so classification (which consumes the marked
                # set again) still sees every marked slot
                with self._marked_lock:
                    self._marked_dead |= marked
                _flight("supervisor.slot_marked_dead",
                        generation=self.generation, slots=sorted(marked))
                self._terminate_cohort("shrink", first=first)
                return _GenOutcome(
                    "fail",
                    failure=f"slot(s) {sorted(marked)} marked dead",
                    worker=first, slot=self._gen_slots[first],
                    reason="shrink",
                    lifetime_s=time.monotonic() - self._launch_time)
            if self._expansion_due():
                self._terminate_cohort("expand")
                return _GenOutcome(
                    "expand",
                    failure=(f"planned expansion to {self.num_workers} "
                             "workers at checkpoint boundary"))
            hung = [w for w in self._hung_workers()
                    if w < len(codes) and codes[w] is None]
            if hung:
                _flight("supervisor.worker_hang",
                        generation=self.generation, workers=hung)
                self._terminate_cohort("hang", first=hung[0])
                return _GenOutcome(
                    "fail",
                    failure=(f"worker(s) {hung} hung (stale heartbeat "
                             "progress)"),
                    worker=hung[0], slot=self._gen_slots[hung[0]],
                    reason="hang",
                    lifetime_s=time.monotonic() - self._launch_time)
            time.sleep(self.poll_interval_s)

    # -- run -----------------------------------------------------------------

    def run(self) -> SupervisorResult:
        """Supervise until the cohort completes; relaunch on failure up
        to ``max_restarts`` times (consecutive failures at one topology
        — a shrink or expansion resets the streak: it changes the
        failure regime), then raise :class:`SupervisorGaveUp`."""
        self.workdir.mkdir(parents=True, exist_ok=True)
        restarts = 0
        streak = 0   # consecutive failures since the last topology change
        try:
            while True:
                self.generation += 1
                gen_env = self._generation_env()
                self._launch_cohort(gen_env)
                self._start_telemetry_surface()
                out = self._watch_cohort()
                if out.kind == "ok":
                    _flight("supervisor.complete",
                            generation=self.generation, restarts=restarts,
                            shrinks=self.shrinks, expands=self.expands)
                    return SupervisorResult(
                        generations=self.generation, restarts=restarts,
                        exits=self.exits, shrinks=self.shrinks,
                        expands=self.expands,
                        dead_slots=sorted(self.dead_slots),
                        final_workers=len(self.active_slots()))
                # cohort teardown: the aggregator's last-known view of
                # every worker (the dead one's final snapshot included)
                # becomes the crash dossier before anything relaunches.
                # Topology transitions commit FIRST so their dossier
                # carries the supervisor.shrink/expand event and the
                # post-transition topology — the forensic record of the
                # transition itself, not just the failure before it.
                if out.kind == "expand":
                    self._expand()
                    self._write_cluster_dossier(out.failure)
                    streak = 0
                    continue  # planned transition: no backoff, no budget
                newly_dead = (self._classify_failure(out)
                              if self.min_workers is not None else set())
                survivors = ([s for s in self.active_slots()
                              if s not in newly_dead]
                             if newly_dead else [])
                if newly_dead and len(survivors) >= self.min_workers \
                        and self.shrinks + self.expands \
                        < self.max_topology_changes:
                    self._shrink(newly_dead, out.failure)
                    self._write_cluster_dossier(
                        f"shrink to {len(survivors)} worker(s) after: "
                        f"{out.failure}")
                    restarts += 1
                    self._restart_count = restarts
                    streak = 0  # new topology, new failure regime
                    continue    # the failing slot is out: relaunch now
                if newly_dead:
                    # classification said dead but the floor / topology
                    # budget denies the shrink: surface it loudly — the
                    # intent is dropped here (relaunch at the same N),
                    # never silently
                    _flight("supervisor.shrink_denied",
                            generation=self.generation,
                            dead_slots=sorted(newly_dead),
                            survivors=len(survivors),
                            reason=("below min_workers"
                                    if len(survivors) < self.min_workers
                                    else "max_topology_changes reached"))
                self._write_cluster_dossier(out.failure)
                if streak >= self.max_restarts:
                    _flight("supervisor.gave_up",
                            generation=self.generation,
                            restarts=restarts, failure=out.failure)
                    raise SupervisorGaveUp(
                        f"cohort failed {streak + 1}x (restart budget "
                        f"{self.max_restarts}); last failure: "
                        f"{out.failure}",
                        self.exits)
                restarts += 1
                streak += 1
                self._restart_count = restarts
                delay = next(self._delays)
                _flight("supervisor.restart", generation=self.generation,
                        restarts=restarts, failure=out.failure,
                        backoff_s=round(delay, 3))
                try:
                    from deeplearning4j_tpu.observability import (
                        metrics as _obsm,
                    )

                    if _obsm.enabled():
                        _obsm.get_resilience_metrics() \
                             .supervisor_restarts_total.inc()
                except Exception:  # noqa: BLE001
                    pass
                time.sleep(delay)
        finally:
            self._stop_probe()
            self._stop_telemetry_surface()

    def stop(self):
        """Terminate any live workers (cleanup path for callers that
        abandon a run mid-flight)."""
        self._probe_stop.set()
        for p in self._procs:
            if p.poll() is None:
                self._signal_worker(p, signal.SIGTERM)
                try:
                    p.wait(timeout=self.grace_s)
                except subprocess.TimeoutExpired:
                    self._signal_worker(p, signal.SIGKILL)


def worker_identity() -> Dict[str, int]:
    """The supervisor-provided identity of this worker process
    (``{"worker_id", "num_workers", "generation"}``; zeros/ones when not
    running under a supervisor) — what a worker script reads to wire
    ``distributed.initialize(process_id=..., num_processes=...)``.
    Delegates to the observability layer's parser so every consumer
    (snapshots, crash reports, worker scripts) agrees on junk-env
    semantics (degrade to defaults, never raise)."""
    from deeplearning4j_tpu.observability.federation import (
        worker_identity as _identity,
    )

    return _identity()


def install_sigterm_teardown(sup: ElasticSupervisor) -> bool:
    """Install a SIGTERM handler that tears the cohort down with the
    supervisor (a systemd/k8s stop of the supervisor must not orphan
    workers); returns False off-main-thread where handlers cannot be
    installed. Opt-in — call it after constructing the supervisor."""
    def _handler(*_):
        sup.stop()
        sys.exit(143)

    try:
        signal.signal(signal.SIGTERM, _handler)
        return True
    except ValueError:  # non-main thread
        return False
