"""Fault-tolerance layer: fault injection, retry, auto-recovering training.

Three pillars (ISSUE 2; SURVEY §5.3/§5.4):

- ``faults``   — seeded deterministic :class:`FaultInjector` with named
  injection points wired through data/train/serde/serving, configured via
  ``DL4J_TPU_FAULTS`` so failure paths run in CI;
- ``retry``    — :func:`retrying` data-iterator wrapper + shared
  :func:`backoff_delays` (capped exponential, full jitter);
- ``recovery`` — :class:`RecoveryPolicy` + :class:`FaultTolerantTrainer`
  (rollback to the latest *verified* checkpoint on NaN/inf, bounded
  retries, optional LR cut and poison-batch skip).

Checkpoint integrity itself (SHA-256 manifests, atomic writes,
``verify_checkpoint`` / ``latest_verified_checkpoint`` / quarantine)
lives in ``serde/checkpoint.py`` — this package is the policy layer on
top of it. Stdlib + numpy + jax only.

``backendpool`` adds the fleet autoscaler's lifecycle plane: the
pluggable :class:`BackendLauncher` contract (subprocess and in-process
implementations) plus :class:`FailStreak`, the supervisor's dead-slot
streak discipline at fleet scope.
"""

from deeplearning4j_tpu.resilience.backendpool import (
    BackendLauncher,
    CallableBackendLauncher,
    FailStreak,
    ProcessBackendLauncher,
    free_port,
)
from deeplearning4j_tpu.resilience.cluster import (
    CollectiveTimeout,
    CollectiveWatchdog,
    HeartbeatWriter,
    dead_peers,
    dump_thread_stacks,
    heartbeat_from_env,
    read_heartbeats,
)
from deeplearning4j_tpu.resilience.faults import (
    POINT_CKPT_CORRUPT,
    POINT_CKPT_WRITE_CRASH,
    POINT_COLLECTIVE_STALL,
    POINT_DATA_READ,
    POINT_SERVING_ERROR,
    POINT_SERVING_LATENCY,
    POINT_SERVING_WORKER_CRASH,
    POINT_STEP_NAN,
    POINT_TRAIN_WORKER_KILL,
    FaultInjector,
    FaultPlan,
    InjectedFault,
    get_fault_injector,
    parse_fault_spec,
    set_fault_injector,
)
from deeplearning4j_tpu.resilience.recovery import (
    FaultTolerantTrainer,
    NonFiniteLossError,
    RecoveryPolicy,
)
from deeplearning4j_tpu.resilience.retry import (
    RetryingIterator,
    backoff_delays,
    retrying,
)
from deeplearning4j_tpu.resilience.supervisor import (
    ElasticSupervisor,
    SupervisorGaveUp,
    WorkerExit,
    install_sigterm_teardown,
)

__all__ = [
    "BackendLauncher",
    "CallableBackendLauncher",
    "FailStreak",
    "ProcessBackendLauncher",
    "free_port",
    "FaultInjector",
    "FaultPlan",
    "InjectedFault",
    "get_fault_injector",
    "set_fault_injector",
    "parse_fault_spec",
    "POINT_DATA_READ",
    "POINT_STEP_NAN",
    "POINT_CKPT_WRITE_CRASH",
    "POINT_CKPT_CORRUPT",
    "POINT_SERVING_LATENCY",
    "POINT_SERVING_ERROR",
    "POINT_COLLECTIVE_STALL",
    "POINT_SERVING_WORKER_CRASH",
    "POINT_TRAIN_WORKER_KILL",
    "CollectiveTimeout",
    "CollectiveWatchdog",
    "HeartbeatWriter",
    "dead_peers",
    "dump_thread_stacks",
    "heartbeat_from_env",
    "read_heartbeats",
    "ElasticSupervisor",
    "SupervisorGaveUp",
    "WorkerExit",
    "install_sigterm_teardown",
    "FaultTolerantTrainer",
    "NonFiniteLossError",
    "RecoveryPolicy",
    "RetryingIterator",
    "backoff_delays",
    "retrying",
]
