"""Deterministic fault injection (SURVEY §5.3: failure detection is only
testable if failures are reproducible).

The reference stack's resilience was proven by production incidents; here
failure paths are first-class, CI-testable code: a seeded
:class:`FaultInjector` fires at *named injection points* compiled into the
hot paths (data read, train step, checkpoint write, serving request).
Every hook is a no-op attribute check when no plan targets its point, so
production runs pay one dict lookup per batch, not a conditional forest.

Named injection points wired through the codebase:

==========================  =====================================================
``data.read``            ``ArrayDataSetIterator`` raises ``IOError`` before a
                            batch (transient storage failure)
``train.step_nan``          the batch's float features are replaced with NaN
                            before the step (poison batch → non-finite loss)
``checkpoint.write_crash``  raises (or SIGKILLs with ``mode="kill"``) between
                            writing ``state.npz``'s tmp file and the atomic
                            rename — the classic crash-mid-checkpoint window
``checkpoint.corrupt``      truncates the *final* ``state.npz`` after a
                            successful, indexed write (bit-rot / torn disk; the
                            manifest must catch it on restore)
``serving.latency``         sleeps ``arg`` seconds inside ``handle_predict``
``serving.error``           ``handle_predict`` sheds with a retryable 429
``serving.overload``        synthetic sustained overload: sleeps ``arg``
                            seconds inside ``handle_predict`` per firing —
                            armed with ``xTIMES`` it holds the serving p99
                            degraded until the budget exhausts, driving the
                            AIMD shrink → brownout ladder → recovery loop
                            in chaos tests
``collective.stall``        sleeps ``arg`` seconds inside a watchdog-guarded
                            collective (``runtime/distributed.barrier`` /
                            ``broadcast_host_data``) — a dead-peer stall the
                            watchdog deadline must catch (resilience/cluster)
``serving.worker_crash``    kills the ``ParallelInference`` worker thread that
                            picked up the next batch (the in-flight batch must
                            fail retryably and the worker must be respawned)
``train.worker_kill``       raises (or with ``!kill`` SIGKILLs the process)
                            at the top of the N-th training step — the
                            elastic supervisor's relaunch/resume trigger
``supervisor.slot_dead``    fires in the SUPERVISOR process while it
                            classifies a cohort failure: the failing slot is
                            ruled permanently dead, driving the
                            shrink-to-survivors path without a real crash
                            loop (``at=N`` = the N-th cohort failure)
``router.backend_down``     fires in the FLEET ROUTER's send path (requests
                            AND health probes both trigger it): the chosen
                            backend is refused with a synthetic connection
                            failure. ``arg`` selects the victim — the
                            backend's table index, or ``-1`` for whichever
                            backend was chosen. Armed with ``xTIMES`` it
                            holds a backend "down" long enough to drive
                            ejection / retry-elsewhere / re-admission in
                            chaos tests and the bench MTTR probe without
                            killing a real process
``router.backend_latency``  sleeps ``arg`` seconds in the router's forward
                            path before the backend send (slow-backend /
                            congested-link chaos; drives retry-budget and
                            p99 tests)
``compile.cache_corrupt``   flips bytes in one persistent-compile-cache
                            artifact on disk BEFORE the integrity walk
                            (runtime/compilecache.py ``activate``) — the
                            manifest check must quarantine it and the
                            process must degrade to a fresh compile,
                            never load a poisoned executable
``compile.cache_stall``     sleeps ``arg`` seconds inside compile-cache
                            activation (a hung cache filesystem): warmup
                            — and therefore ``/readyz`` — must stay
                            not-ready for the duration instead of
                            declaring a cold process warm
==========================  =====================================================

Plans are deterministic: ``at=N`` fires on the N-th trigger of the point
(1-based), ``prob=p`` draws from the injector's own seeded RNG. Wired
through the environment config (``DL4J_TPU_FAULTS`` /
``DL4J_TPU_FAULT_SEED``) so subprocess tests and CI enable faults without
touching code::

    DL4J_TPU_FAULTS="train.step_nan@8;checkpoint.corrupt@2"
    DL4J_TPU_FAULTS="checkpoint.write_crash@3!kill"      # real SIGKILL
    DL4J_TPU_FAULTS="serving.latency@1x5:0.25"           # 5 firings, 0.25 s

Grammar per ``;``/``,``-separated entry:
``point[@AT|%PROB][xTIMES][:ARG][!MODE]`` (default ``@1``, ``x1``,
``!raise``).
"""

from __future__ import annotations

import dataclasses
import os
import random
import re
import signal
import threading
import time
from typing import Dict, List, Optional

# Canonical injection point names (importable, greppable).
POINT_DATA_READ = "data.read"
POINT_STEP_NAN = "train.step_nan"
POINT_CKPT_WRITE_CRASH = "checkpoint.write_crash"
POINT_CKPT_CORRUPT = "checkpoint.corrupt"
POINT_SERVING_LATENCY = "serving.latency"
POINT_SERVING_ERROR = "serving.error"
POINT_SERVING_OVERLOAD = "serving.overload"
POINT_COLLECTIVE_STALL = "collective.stall"
POINT_SERVING_WORKER_CRASH = "serving.worker_crash"
POINT_TRAIN_WORKER_KILL = "train.worker_kill"
POINT_SUPERVISOR_SLOT_DEAD = "supervisor.slot_dead"
POINT_ROUTER_BACKEND_DOWN = "router.backend_down"
POINT_ROUTER_BACKEND_LATENCY = "router.backend_latency"
POINT_COMPILE_CACHE_CORRUPT = "compile.cache_corrupt"
POINT_COMPILE_CACHE_STALL = "compile.cache_stall"

KNOWN_POINTS = (
    POINT_DATA_READ,
    POINT_STEP_NAN,
    POINT_CKPT_WRITE_CRASH,
    POINT_CKPT_CORRUPT,
    POINT_SERVING_LATENCY,
    POINT_SERVING_ERROR,
    POINT_SERVING_OVERLOAD,
    POINT_COLLECTIVE_STALL,
    POINT_SERVING_WORKER_CRASH,
    POINT_TRAIN_WORKER_KILL,
    POINT_SUPERVISOR_SLOT_DEAD,
    POINT_ROUTER_BACKEND_DOWN,
    POINT_ROUTER_BACKEND_LATENCY,
    POINT_COMPILE_CACHE_CORRUPT,
    POINT_COMPILE_CACHE_STALL,
)


class InjectedFault(Exception):
    """Raised by a fired injection point (never by production code paths)."""


@dataclasses.dataclass
class FaultPlan:
    """One planned firing schedule for one injection point.

    ``at``: fire on the first trigger whose 1-based count reaches ``at``
    (and the next ``times - 1`` matching triggers). ``prob``: fire each
    trigger with this probability from the injector's seeded RNG instead.
    ``arg`` carries a point-specific scalar (latency seconds, retry-after
    seconds). ``mode``: ``"raise"`` or ``"kill"`` (process SIGKILL — real
    crash-consistency testing, not an exception the caller could catch).
    """

    point: str
    at: Optional[int] = 1
    prob: float = 0.0
    times: int = 1
    arg: float = 0.0
    mode: str = "raise"
    fired: int = 0


class FaultInjector:
    """Seeded, deterministic fault injector.

    Thread-safe: trigger counting and plan state are guarded by one lock
    (checkpoint writes fire from the AsyncCheckpointer worker, serving
    points from HTTP handler threads). ``log`` records every firing
    ``{point, trigger, time}`` for assertions and post-mortems.
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._rng = random.Random(seed)
        self._plans: Dict[str, List[FaultPlan]] = {}
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()
        self.log: List[dict] = []

    @property
    def enabled(self) -> bool:
        """True if any plan is installed — the hooks' fast-path gate."""
        return bool(self._plans)

    def planned(self, point: str) -> bool:
        """True if any plan targets ``point`` (cheap membership check;
        callers that must restructure control flow around a possible
        firing — e.g. the collective watchdog's worker-thread hop — gate
        on this instead of paying the hop for unrelated plans)."""
        return point in self._plans

    def plans_for(self, point: str) -> List[FaultPlan]:
        """Snapshot of the plans installed for ``point``. For callers
        with target-selective semantics (the fleet router's
        ``router.backend_down`` encodes its victim in ``arg``): they
        must inspect plan args BEFORE consuming a firing, or a finite
        ``times=N`` plan aimed at one target gets silently drained by
        triggers the plan was never meant to hit."""
        with self._lock:
            return list(self._plans.get(point, ()))

    def plan(self, point: str, *, at: Optional[int] = None, prob: float = 0.0,
             times: int = 1, arg: float = 0.0,
             mode: str = "raise") -> "FaultInjector":
        """Install a firing schedule; returns self for chaining."""
        if at is None and not prob:
            at = 1
        if at is not None and at < 1:
            raise ValueError(f"at must be >= 1 (1-based trigger), got {at}")
        if not 0.0 <= prob <= 1.0:
            raise ValueError(f"prob must be in [0, 1], got {prob}")
        if times < 1:
            raise ValueError(f"times must be >= 1, got {times}")
        if mode not in ("raise", "kill"):
            raise ValueError(f"mode must be 'raise' or 'kill', got {mode!r}")
        with self._lock:
            self._plans.setdefault(point, []).append(
                FaultPlan(point=point, at=at, prob=prob, times=times,
                          arg=arg, mode=mode))
        return self

    def reset(self):
        """Clear trigger counts, fired counters, the RNG, and the log —
        plans stay installed (rerun the same deterministic schedule)."""
        with self._lock:
            self._counts.clear()
            self.log.clear()
            self._rng = random.Random(self.seed)
            for plans in self._plans.values():
                for p in plans:
                    p.fired = 0

    # -- core ----------------------------------------------------------------

    def fire(self, point: str) -> Optional[FaultPlan]:
        """Count one trigger of ``point``; return the plan that fires, or
        None. Unplanned points return immediately without counting."""
        if point not in self._plans:
            return None
        with self._lock:
            count = self._counts.get(point, 0) + 1
            self._counts[point] = count
            for p in self._plans[point]:
                if p.fired >= p.times:
                    continue
                if p.at is not None:
                    hit = count >= p.at
                else:
                    hit = self._rng.random() < p.prob
                if hit:
                    p.fired += 1
                    self.log.append({"point": point, "trigger": count,
                                     "time": time.time()})
                    try:
                        # the black-box timeline must show the injected
                        # fault next to the recovery it caused
                        from deeplearning4j_tpu.observability.flightrecorder import (  # noqa: E501
                            record_event,
                        )

                        record_event("fault.injected", point=point,
                                     trigger=count, mode=p.mode, arg=p.arg)
                    except Exception:  # noqa: BLE001 - never mask the fault
                        pass
                    return p
        return None

    def triggers(self, point: str) -> int:
        with self._lock:
            return self._counts.get(point, 0)

    # -- hook helpers (what the wired code paths call) -----------------------

    def maybe_fail(self, point: str, exc=InjectedFault,
                   msg: Optional[str] = None) -> bool:
        """Raise ``exc`` (or SIGKILL under ``mode='kill'``) if the point
        fires; returns False otherwise."""
        p = self.fire(point)
        if p is None:
            return False
        if p.mode == "kill":
            os.kill(os.getpid(), signal.SIGKILL)  # no cleanup, by design
        raise exc(msg or f"injected fault at '{point}' "
                         f"(firing {p.fired}/{p.times})")

    def maybe_sleep(self, point: str) -> bool:
        """Sleep the fired plan's ``arg`` seconds (latency spike)."""
        p = self.fire(point)
        if p is not None and p.arg > 0:
            time.sleep(p.arg)
            return True
        return p is not None

    def maybe_poison_batch(self, batch):
        """NaN-poison a batch dict's float ``features`` when
        ``train.step_nan`` fires; otherwise return the batch untouched."""
        if self.fire(POINT_STEP_NAN) is None:
            return batch
        import numpy as np

        def nanify(v):
            if isinstance(v, dict):
                return {k: nanify(x) for k, x in v.items()}
            arr = np.asarray(v)
            if np.issubdtype(arr.dtype, np.floating):
                return np.full_like(arr, np.nan)
            return v

        out = dict(batch)
        if "features" in out:
            out["features"] = nanify(out["features"])
        return out


# -- spec parsing + process-wide injector ------------------------------------

_SPEC_RE = re.compile(
    r"^(?P<point>[\w.]+)"
    r"(?:@(?P<at>\d+)|%(?P<prob>[0-9.eE+-]+))?"
    r"(?:x(?P<times>\d+))?"
    r"(?::(?P<arg>[0-9.eE+-]+))?"
    r"(?:!(?P<mode>\w+))?$")


def parse_fault_spec(spec: str) -> List[dict]:
    """``DL4J_TPU_FAULTS`` grammar → list of ``FaultInjector.plan`` kwargs.

    ``point[@AT|%PROB][xTIMES][:ARG][!MODE]``, entries separated by ``;``
    or ``,``. Raises ValueError with the offending entry on bad syntax.
    """
    plans = []
    for entry in re.split(r"[;,]", spec):
        entry = entry.strip()
        if not entry:
            continue
        m = _SPEC_RE.match(entry)
        if m is None:
            raise ValueError(
                f"bad fault spec entry {entry!r}; expected "
                "point[@AT|%PROB][xTIMES][:ARG][!MODE]")
        g = m.groupdict()
        if g["point"] not in KNOWN_POINTS:
            # a typo'd env spec would otherwise arm a point nothing ever
            # fires, and the fault test it backs would pass vacuously
            # (programmatic plan() stays open for custom points)
            raise ValueError(
                f"unknown injection point {g['point']!r}; known points: "
                + ", ".join(KNOWN_POINTS))
        plans.append({
            "point": g["point"],
            "at": int(g["at"]) if g["at"] else (None if g["prob"] else 1),
            "prob": float(g["prob"]) if g["prob"] else 0.0,
            "times": int(g["times"]) if g["times"] else 1,
            "arg": float(g["arg"]) if g["arg"] else 0.0,
            "mode": g["mode"] or "raise",
        })
    return plans


_injector: Optional[FaultInjector] = None
_injector_lock = threading.Lock()


def get_fault_injector() -> FaultInjector:
    """Process-wide injector, built on first use from the environment
    config (``DL4J_TPU_FAULTS`` / ``DL4J_TPU_FAULT_SEED``). With no spec
    it is empty (``enabled == False``) and every hook is a fast no-op."""
    global _injector
    if _injector is None:
        with _injector_lock:
            if _injector is None:
                from deeplearning4j_tpu.runtime.environment import (
                    get_environment,
                )

                env = get_environment()
                inj = FaultInjector(seed=getattr(env, "fault_seed", 0))
                spec = getattr(env, "fault_spec", "")
                for kw in (parse_fault_spec(spec) if spec else []):
                    inj.plan(**kw)
                _injector = inj
    return _injector


def set_fault_injector(inj: Optional[FaultInjector]):
    """Install (or with None, drop back to env-built) the process-wide
    injector — tests swap in a programmatic schedule this way."""
    global _injector
    _injector = inj
