"""Auto-recovering training: rollback-to-verified-checkpoint on NaN/inf.

The stack already *detects* failures — the checkify NaN guard raises, the
host can see a non-finite loss — but detection kills the run. This module
closes the loop (SURVEY §5.3/§5.4: the reference's production value was
surviving exactly this): :class:`FaultTolerantTrainer` wraps a built
``Trainer`` and drives the same compiled step, but

- checkpoints on a step cadence with the *verified* writer
  (``serde.checkpoint``: per-array SHA-256 manifest, atomic replace),
  including an anchor checkpoint before the first step so a rollback
  target always exists;
- after every step, host-checks the loss for NaN/inf (and catches the
  checkify guard's raise when ``check_nan`` is on);
- on failure, restores the **latest verified** checkpoint — walking the
  rotation index past corrupt/truncated/missing entries, quarantining the
  bad ones — and resumes from the rolled-back step, with
  :class:`RecoveryPolicy` bounding total rollbacks;
- optionally cuts the effective learning rate on each rollback (update
  scaling: exact for every updater, applied by re-jitting the step), and
  skips a batch that keeps producing NaN (poison data, not a transient);
- wraps the data iterator with ``retrying()`` for transient IO errors.

Donation-correct: the compiled step donates the input TrainState, so a
failed step cannot be retried in place — the donated buffers are gone.
Rollback therefore always goes through the host-side checkpoint, which is
also why the anchor save at step 0 is unconditional. The per-step host
read of the scalar loss costs one tiny D2H sync; ``check_every`` amortizes
it when steps are short.
"""

from __future__ import annotations

import dataclasses
import json
import math
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Set, Tuple

import jax


class NonFiniteLossError(RuntimeError):
    """Raised host-side when a step's loss is NaN/inf (the recovery
    trigger when the compiled checkify guard is off)."""

    def __init__(self, msg: str, step: Optional[int] = None):
        super().__init__(msg)
        self.step = step


def _obs():
    """Shared-registry resilience bundle, or None when instrumentation is
    off — recovery events (rollbacks, skips, LR cuts) are rare, so the
    lazy lookup per event is free next to the checkpoint IO around it."""
    from deeplearning4j_tpu.observability import metrics as _obsm

    return _obsm.get_resilience_metrics() if _obsm.enabled() else None


def _flight(kind: str, **data):
    """Recovery events into the black-box ring: a crash report's timeline
    must show the rollbacks/skips that preceded it."""
    from deeplearning4j_tpu.observability.flightrecorder import record_event

    record_event(kind, **data)


def _train_obs():
    """The same training bundle Trainer.fit feeds — FaultTolerantTrainer
    drives the compiled step from its own loop, so it reports step/sample
    counts itself or a recovering run would vanish from the scrape."""
    from deeplearning4j_tpu.observability import metrics as _obsm

    return _obsm.get_training_metrics() if _obsm.enabled() else None


def _nan_exception_types():
    """Exception classes that mean 'this step produced non-finite values':
    our host check, numpy's FP errors, and the checkify guard's raise."""
    types: list = [NonFiniteLossError, FloatingPointError]
    try:
        from jax.experimental import checkify

        types.append(checkify.JaxRuntimeError)
    except (ImportError, AttributeError):  # older jax spells it differently
        pass
    return tuple(types)


@dataclasses.dataclass
class RecoveryPolicy:
    """Knobs for :class:`FaultTolerantTrainer` (all host-side).

    ``max_rollbacks``: total rollbacks allowed per ``fit`` before the
    failure propagates (a run that cannot make progress must eventually
    surface, not loop forever). ``checkpoint_every``: steps between
    rolling verified saves (the rollback granularity). ``lr_cut``: each
    rollback multiplies the effective LR by this (1.0 = off; applied as an
    update scale, re-jitting the step — a compile per rollback, not per
    step). ``skip_poison_after``: a batch whose step has failed this many
    times is skipped on replay (0 = never skip; transients never hit this
    because the retry usually succeeds). ``data_retries``: transient-IO
    retry budget for the iterator wrapper (0 = don't wrap).

    Poison-batch attribution assumes ``check_every == 1``: with a larger
    cadence the NaN is detected up to ``check_every - 1`` steps after the
    batch that caused it, so ``skip_poison_after`` may skip the detection
    batch rather than the poison one (rollback and ``lr_cut`` still
    work — only the skip targets the wrong batch). Keep ``check_every=1``
    when relying on poison skipping.
    """

    max_rollbacks: int = 3
    checkpoint_every: int = 25
    checkpoint_every_epoch: bool = True
    keep_last: int = 3
    lr_cut: float = 1.0
    skip_poison_after: int = 2
    data_retries: int = 5
    data_base_delay: float = 0.05
    data_max_delay: float = 2.0
    check_every: int = 1


class FaultTolerantTrainer:
    """Wrap a ``Trainer`` with checkpointed auto-recovery.

    Usage::

        trainer = Trainer(model)
        ft = FaultTolerantTrainer(trainer, "ckpts", model=model)
        ts = ft.fit(trainer.init_state(), data, epochs=3)

    ``fit`` resumes from the latest *verified* checkpoint in ``directory``
    if one exists (same relaunch story as ``PreemptionCheckpointer``, but
    integrity-checked), so a crashed/preempted/NaN-killed run continues
    with ``ft.fit(...)`` unchanged. ``recoveries`` records every rollback
    and skipped batch for post-mortems.

    Standard backprop only — TBPTT's window-carry state is not
    checkpointed at window granularity, so rolling back inside a batch
    would silently zero carries.
    """

    def __init__(self, trainer, directory: str | Path, *,
                 policy: Optional[RecoveryPolicy] = None, model=None):
        if getattr(trainer.net, "backprop_type", "standard") == "tbptt":
            raise ValueError(
                "FaultTolerantTrainer supports backprop_type='standard' "
                "only (TBPTT carries are not checkpointed per window)")
        self.trainer = trainer
        self.directory = Path(directory)
        self.policy = policy or RecoveryPolicy()
        self.model = model
        self.recoveries: List[dict] = []
        self._lr_scale = 1.0
        self._step_fn = trainer.train_step
        if not 0.0 < self.policy.lr_cut <= 1.0:
            raise ValueError(
                f"lr_cut must be in (0, 1], got {self.policy.lr_cut}")
        # the unwrapped updater, captured now: _install_lr_scale always
        # wraps THIS, so repeated fits (or a second wrapper on the same
        # trainer) never stack scalings
        self._orig_upd = trainer._upd_update

    def _install_lr_scale(self):
        """Wrap the updater so update vectors are scaled by ``_lr_scale``
        (scaling the *updates* is an exact LR cut for any updater, unlike
        scaling gradients under Adam). The scale is read at trace time:
        each cut re-jits the step (see ``_rollback``). Installed only for
        the duration of ``fit`` — a shared Trainer must not keep tracing
        through a stale scale after this wrapper's run ended."""
        orig_upd = self._orig_upd

        def scaled_update(grads, opt_state, params, step):
            updates, new_opt = orig_upd(grads, opt_state, params, step)
            s = self._lr_scale
            if s != 1.0:
                updates = jax.tree_util.tree_map(lambda u: u * s, updates)
            return updates, new_opt

        self.trainer._upd_update = scaled_update

    # -- checkpoint plumbing -------------------------------------------------

    def _save(self, ts, *, epoch: int, batch_in_epoch: int, tag: str):
        from deeplearning4j_tpu.serde.checkpoint import save_checkpoint

        # Never checkpoint a poisoned state: NaN/inf params hash cleanly
        # (integrity digests are content-blind), so a saved one would
        # verify forever and become an inescapable rollback target. This
        # window exists whenever detection lags the damage (check_every>1,
        # or a loss that goes non-finite a few steps after the params do).
        # The check reduces on device — one scalar D2H, not a second full
        # host copy of a state save_checkpoint is about to snapshot anyway.
        import jax.numpy as jnp

        ok = True
        for leaf in jax.tree_util.tree_leaves(ts.params):
            arr = jnp.asarray(leaf)
            if jnp.issubdtype(arr.dtype, jnp.floating):
                ok = jnp.logical_and(ok, jnp.isfinite(arr).all())
        if not bool(jax.device_get(ok)):
            step = int(jax.device_get(ts.step))
            self.recoveries.append({
                "kind": "skip_checkpoint",
                "step": step,
                "reason": "non-finite params"})
            rm = _obs()
            if rm is not None:
                rm.checkpoint_skips_total.inc()
            _flight("resilience.checkpoint_skip", step=step,
                    reason="non-finite params")
            return
        save_checkpoint(
            self.directory, ts, model=self.model, tag=tag,
            keep_last=self.policy.keep_last,
            extra_meta={"epoch": epoch, "batch_in_epoch": batch_in_epoch})

    def _latest_verified(self) -> Optional[str]:
        from deeplearning4j_tpu.serde.checkpoint import (
            latest_verified_checkpoint,
        )

        return latest_verified_checkpoint(self.directory)

    def resume(self, ts) -> Any:
        """Restore the latest verified checkpoint into ``ts`` (template);
        returns ``ts`` unchanged when none exists."""
        restored, _ = self._resume(ts)
        return restored

    def _resume(self, ts) -> Tuple[Any, Tuple[int, int]]:
        from deeplearning4j_tpu.serde.checkpoint import restore_checkpoint

        d = self._latest_verified()
        if d is None:
            return ts, (0, 0)
        meta = json.loads((Path(d) / "meta.json").read_text())
        return (restore_checkpoint(d, ts),
                (int(meta.get("epoch", 0)), int(meta.get("batch_in_epoch", 0))))

    def _rollback(self, template, err) -> Tuple[Any, Tuple[int, int]]:
        from deeplearning4j_tpu.serde.checkpoint import restore_checkpoint

        d = self._latest_verified()
        if d is None:
            raise RuntimeError(
                "no verified checkpoint to roll back to "
                f"(directory={self.directory})") from err
        meta = json.loads((Path(d) / "meta.json").read_text())
        ts = restore_checkpoint(d, template)
        self.recoveries.append({
            "kind": "rollback", "checkpoint": d,
            "to_step": int(meta.get("step", 0)), "cause": repr(err)})
        rm = _obs()
        if rm is not None:
            rm.rollbacks_total.inc()
        _flight("resilience.rollback", checkpoint=str(d),
                to_step=int(meta.get("step", 0)), cause=repr(err)[:200])
        return ts, (int(meta.get("epoch", 0)),
                    int(meta.get("batch_in_epoch", 0)))

    # -- fit -----------------------------------------------------------------

    def fit(self, ts, data, *, epochs: int = 1, listeners: Optional[List] = None,
            steps_per_epoch: Optional[int] = None, resume: bool = True):
        from deeplearning4j_tpu.data.dataset import as_batch_dict
        from deeplearning4j_tpu.resilience.cluster import touch_heartbeat
        from deeplearning4j_tpu.resilience.faults import get_fault_injector
        from deeplearning4j_tpu.resilience.retry import (
            RetryingIterator,
            retrying,
        )

        tr = self.trainer
        pol = self.policy
        listeners = listeners or []
        inj = get_fault_injector()
        nan_types = _nan_exception_types()
        self._lr_scale = 1.0          # cuts do not carry across fits
        self._step_fn = tr.train_step
        if pol.lr_cut != 1.0:
            self._install_lr_scale()

        start_epoch, skip_batches = 0, 0
        if resume:
            ts, (start_epoch, skip_batches) = self._resume(ts)
        if pol.data_retries and not isinstance(data, RetryingIterator):
            data = retrying(data, max_retries=pol.data_retries,
                            base_delay=pol.data_base_delay,
                            max_delay=pol.data_max_delay, seed=0)
        # outermost wrap (prefetch over the retrying reader) so retried
        # reads are what the background thread overlaps; no-op unless
        # DL4J_TPU_AUTO_PREFETCH=1 (both wrappers pass set_epoch through)
        from deeplearning4j_tpu.data.iterators import maybe_auto_prefetch

        data = maybe_auto_prefetch(data)
        host_step = int(jax.device_get(ts.step))
        # Anchor: a rollback target must exist before the first step can
        # fail (the donated input state is unrecoverable host-side).
        if self._latest_verified() is None:
            self._save(ts, epoch=start_epoch, batch_in_epoch=skip_batches,
                       tag="init")

        rollbacks = 0
        fail_counts: Dict[Tuple[int, int], int] = {}
        skip_set: Set[Tuple[int, int]] = set()
        stop = False
        tm = _train_obs()
        if tm is not None:
            from deeplearning4j_tpu.train.trainer import _StepTelemetry

            tele = _StepTelemetry(tr, tm)
        for lst in listeners:
            lst.on_fit_start(tr, ts)
        # incident pipeline: arm the "train" device-capture hook for the
        # life of this fit, exactly like Trainer.fit (the per-step
        # note below is a no-op global check when nothing is pending)
        from deeplearning4j_tpu.observability.incidents import (
            enter_training,
            exit_training,
            note_train_step,
        )

        enter_training()
        try:
            epoch = start_epoch
            while epoch < epochs and not stop:
                if hasattr(data, "set_epoch"):
                    # pin the shuffle permutation to the logical epoch:
                    # a relaunched process (fresh iterator at epoch 0) or
                    # a rollback replay fast-forwards skip_batches of the
                    # SAME order the checkpoint position was recorded
                    # against, not a different permutation's prefix
                    data.set_epoch(epoch)
                for lst in listeners:
                    lst.on_epoch_start(epoch)
                restart_epoch = False
                b = 0
                it = iter(data)
                while True:
                    # manual next(): the read is timed so the starvation
                    # detector sees FT runs too (Trainer.fit measures the
                    # same leg)
                    t_read = time.perf_counter() if tm is not None else 0.0
                    try:
                        batch = next(it)
                    except StopIteration:
                        break
                    read_s = (time.perf_counter() - t_read
                              if tm is not None else 0.0)
                    if b < skip_batches:
                        b += 1
                        continue
                    if (epoch, b) in skip_set:
                        self.recoveries.append(
                            {"kind": "skip_batch", "epoch": epoch, "batch": b})
                        rm = _obs()
                        if rm is not None:
                            rm.skipped_batches_total.inc()
                        _flight("resilience.skip_batch", epoch=epoch, batch=b)
                        b += 1
                        continue
                    batch = as_batch_dict(batch)
                    if inj.enabled:
                        # "train.worker_kill": die here (SIGKILL under
                        # !kill) so supervisor relaunch/resume paths are
                        # chaos-testable at an exact step
                        inj.maybe_fail("train.worker_kill")
                        batch = inj.maybe_poison_batch(batch)
                    if tr._batch_sharding is not None:
                        batch = jax.device_put(batch, tr._batch_sharding)
                    new_ts = None
                    t_step = time.perf_counter() if tm is not None else 0.0
                    try:
                        new_ts, metrics = self._step_fn(ts, batch)
                        if pol.check_every and \
                                (host_step + 1) % pol.check_every == 0:
                            loss = float(jax.device_get(
                                metrics["total_loss"]))
                            if not math.isfinite(loss):
                                raise NonFiniteLossError(
                                    f"non-finite loss {loss} at step "
                                    f"{host_step + 1}", step=host_step + 1)
                    except nan_types as e:
                        rollbacks += 1
                        key = (epoch, b)
                        fail_counts[key] = fail_counts.get(key, 0) + 1
                        if rollbacks > pol.max_rollbacks:
                            raise
                        if pol.skip_poison_after and \
                                fail_counts[key] >= pol.skip_poison_after:
                            skip_set.add(key)
                        template = new_ts if new_ts is not None else ts
                        ts, (r_epoch, r_skip) = self._rollback(template, e)
                        host_step = int(jax.device_get(ts.step))
                        if pol.lr_cut != 1.0:
                            self._lr_scale *= pol.lr_cut
                            # fresh jit wrapper → fresh trace → the new
                            # scale constant is baked into the executable
                            self._step_fn = tr._jit_with_nan_guard(
                                tr._raw_step, tr._jit_kwargs)
                            self.recoveries.append(
                                {"kind": "lr_cut", "scale": self._lr_scale})
                            rm = _obs()
                            if rm is not None:
                                rm.lr_cuts_total.inc()
                            _flight("resilience.lr_cut",
                                    scale=self._lr_scale)
                        epoch = r_epoch
                        skip_batches = r_skip
                        restart_epoch = True
                        break
                    ts = new_ts
                    host_step += 1
                    note_train_step()  # armed incident capture boundary
                    touch_heartbeat()  # supervisor hang-detector beacon
                    if tm is not None:
                        step_s = time.perf_counter() - t_step
                        tm.step_seconds.observe(step_s)
                        tm.data_read_seconds.observe(read_s)
                        tm.steps_total.inc()
                        feats = jax.tree_util.tree_leaves(batch["features"])
                        tm.samples_total.inc(feats[0].shape[0])
                        tele.on_step(ts, batch, read_s, step_s, host_step)
                    b += 1
                    if pol.checkpoint_every and \
                            host_step % pol.checkpoint_every == 0:
                        self._save(ts, epoch=epoch, batch_in_epoch=b,
                                   tag="auto")
                    for lst in listeners:
                        if lst.on_iteration(epoch, host_step, ts, metrics):
                            stop = True
                    if steps_per_epoch is not None and b >= steps_per_epoch:
                        break
                    if stop:
                        break
                if restart_epoch:
                    if hasattr(data, "reset"):
                        data.reset()
                    continue  # same (or rolled-back) epoch, fast-forwarding
                skip_batches = 0
                for lst in listeners:
                    if lst.on_epoch_end(epoch, ts):
                        stop = True
                if hasattr(data, "reset"):
                    data.reset()
                if tm is not None:
                    tm.epochs_total.inc()
                epoch += 1
                if pol.checkpoint_every_epoch and epoch < epochs:
                    # position = start of the next epoch: a rollback in
                    # epoch e+1 never replays epoch e's batches
                    self._save(ts, epoch=epoch, batch_in_epoch=0,
                               tag=f"epoch{epoch - 1}")
        finally:
            exit_training()
            tr._upd_update = self._orig_upd
            for lst in listeners:
                lst.on_fit_end(tr, ts)
        return ts
