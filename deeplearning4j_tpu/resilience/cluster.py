"""Collective watchdog: detect, report, and TYPE a stalled cluster.

The nastiest multi-host failure mode is not a crash — it is a *silent
stall*: one peer dies (OOM-killed, preempted, kernel panic) and every
surviving process blocks forever inside its next collective
(``barrier``, ``broadcast_host_data``, the psum inside a compiled step).
The reference stack detected this with VoidParameterServer heartbeats
over Aeron (SURVEY §5.3); jax's coordination service has no user-facing
liveness surface, so this module rebuilds the detection layer host-side:

- :class:`HeartbeatWriter` — each worker publishes a beacon file
  (``proc_<i>.json``: pid, seq, wall time, and a *progress* stamp the
  training loop advances via :meth:`~HeartbeatWriter.touch`) to a shared
  directory; :func:`dead_peers` reads all beacons and names the peers
  whose beat (or progress) went stale. ``touch()`` is an in-memory
  monotonic store (~ns) — the background thread does the file IO, so
  per-step beats cost nothing on the hot path.
- :class:`CollectiveWatchdog` — runs a blocking host collective under a
  deadline (worker thread + join). On stall it dumps **every thread's
  stack** plus the flight-recorder timeline into a crash report
  (``utils/crash.py``), names the dead peers when a heartbeat directory
  is armed, and raises a typed :class:`CollectiveTimeout` instead of
  hanging — so the process exits and the elastic supervisor
  (``resilience/supervisor.py``) can relaunch the cohort.

``runtime/distributed.py`` routes ``barrier`` / ``broadcast_host_data``
through the watchdog whenever a deadline is armed
(``DL4J_TPU_COLLECTIVE_TIMEOUT_S``, default 300 s in multi-process
jobs), and fires the ``collective.stall`` injection point inside the
guarded region so the whole detection path is chaos-testable in one
process. Stdlib only.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

ENV_COLLECTIVE_TIMEOUT = "DL4J_TPU_COLLECTIVE_TIMEOUT_S"
ENV_HEARTBEAT_DIR = "DL4J_TPU_HEARTBEAT_DIR"
ENV_HEARTBEAT_INTERVAL = "DL4J_TPU_HEARTBEAT_INTERVAL_S"
ENV_CRASH_DIR = "DL4J_TPU_CRASH_DIR"
DEFAULT_COLLECTIVE_TIMEOUT_S = 300.0
DEFAULT_HEARTBEAT_INTERVAL_S = 1.0


class CollectiveTimeout(RuntimeError):
    """A host collective exceeded its deadline — the cluster is stalled.

    Typed so supervisors/relaunch logic can distinguish "a peer is gone,
    restart the cohort" from ordinary training failures. Carries the
    operation name, the deadline, the crash-report path (thread stacks +
    flight recorder), and the peers whose heartbeat was stale at
    detection time (empty when no heartbeat directory is armed)."""

    def __init__(self, msg: str, *, op: str = "", timeout_s: float = 0.0,
                 crash_report: Optional[str] = None,
                 dead: Optional[List[int]] = None):
        super().__init__(msg)
        self.op = op
        self.timeout_s = timeout_s
        self.crash_report = crash_report
        self.dead = list(dead or [])


def dump_thread_stacks() -> Dict[str, List[str]]:
    """Every live thread's current stack, by thread name — the "where is
    everyone blocked?" half of a stall post-mortem."""
    names = {th.ident: th.name for th in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for ident, frame in sys._current_frames().items():
        name = names.get(ident, f"thread-{ident}")
        out[name] = traceback.format_stack(frame)
    return out


# -- heartbeat files ----------------------------------------------------------


class HeartbeatWriter:
    """Publish this process's liveness beacon to a shared directory.

    A daemon thread rewrites ``<dir>/proc_<id>.json`` every ``interval_s``
    with ``{pid, process_id, seq, time, progress_age_s}``. ``touch()``
    stores a monotonic stamp in memory (call it once per training step);
    the beacon's ``progress_age_s`` is how long ago the last touch was,
    so a reader can tell a *hung* main thread (fresh beacon, stale
    progress) from a *dead* process (stale beacon). Until the FIRST
    ``touch()`` the beacon reports ``progress_age_s: null`` and hang
    detection stays off — a long first-step compile must not read as a
    hang (touch once right after bootstrap if you want wedged-init
    coverage). Writes are atomic (tmp + ``os.replace``) — a reader
    never sees a torn beacon."""

    def __init__(self, directory: str | Path, process_id: int, *,
                 interval_s: float = DEFAULT_HEARTBEAT_INTERVAL_S):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.directory = Path(directory)
        self.process_id = int(process_id)
        self.interval_s = float(interval_s)
        self._seq = 0
        self._progress: Optional[float] = None  # set by the first touch()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def path(self) -> Path:
        return self.directory / f"proc_{self.process_id}.json"

    def touch(self) -> None:
        """Mark forward progress (in-memory, ~ns; no file IO)."""
        self._progress = time.monotonic()

    def beat(self) -> None:
        """Write one beacon now (the background thread calls this)."""
        self.directory.mkdir(parents=True, exist_ok=True)
        self._seq += 1
        progress = self._progress
        doc = {
            "pid": os.getpid(),
            "process_id": self.process_id,
            "seq": self._seq,
            "time": time.time(),
            "progress_age_s": (round(time.monotonic() - progress, 3)
                               if progress is not None else None),
        }
        tmp = self.path.with_suffix(".tmp")
        tmp.write_text(json.dumps(doc))
        os.replace(tmp, self.path)

    def start(self) -> "HeartbeatWriter":
        if self._thread is not None:
            return self
        self._stop.clear()
        self.beat()  # a beacon exists before start() returns
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"heartbeat-{self.process_id}")
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.beat()
            except OSError:  # transient FS trouble: keep beating
                pass

    def stop(self, *, remove: bool = False) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if remove:
            try:
                self.path.unlink()
            except OSError:
                pass

    def __enter__(self) -> "HeartbeatWriter":
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def read_heartbeats(directory: str | Path) -> Dict[int, dict]:
    """All peers' latest beacons, by process id. Torn/unparseable files
    are skipped (the atomic writer makes them rare; a reader must never
    crash on one)."""
    out: Dict[int, dict] = {}
    d = Path(directory)
    if not d.is_dir():
        return out
    for f in d.glob("proc_*.json"):
        try:
            doc = json.loads(f.read_text())
            out[int(doc["process_id"])] = doc
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return out


def dead_peers(directory: str | Path, *, timeout_s: float,
               expect: Optional[int] = None,
               progress_timeout_s: Optional[float] = None,
               now: Optional[float] = None) -> List[int]:
    """Process ids whose beacon is stale/missing (dead process) or —
    with ``progress_timeout_s`` — whose progress stamp went stale while
    the beacon stayed fresh (hung main thread). A beacon that never
    reported progress (``progress_age_s: null`` — the worker has not
    touched yet, e.g. still in its first compile) is NOT hung: hang
    detection starts at the first touch. ``expect``: also report ids in
    ``range(expect)`` that never wrote a beacon."""
    beats = read_heartbeats(directory)
    t = time.time() if now is None else now
    dead = set()
    if expect is not None:
        dead.update(i for i in range(expect) if i not in beats)
    for pid_, doc in beats.items():
        age = doc.get("progress_age_s")
        if t - float(doc.get("time", 0.0)) > timeout_s:
            dead.add(pid_)
        elif progress_timeout_s is not None and age is not None \
                and float(age) > progress_timeout_s:
            dead.add(pid_)
    return sorted(dead)


_PROC_HEARTBEAT: Optional[HeartbeatWriter] = None


def heartbeat_from_env(process_id: Optional[int] = None
                       ) -> Optional[HeartbeatWriter]:
    """Start a :class:`HeartbeatWriter` from the supervisor-provided
    environment (``DL4J_TPU_HEARTBEAT_DIR`` + worker id), or None when
    no supervisor armed one — the one-liner a worker script calls. The
    writer is published process-wide so the training loops' per-step
    :func:`touch_heartbeat` advances its progress stamp."""
    global _PROC_HEARTBEAT
    directory = os.environ.get(ENV_HEARTBEAT_DIR)
    if not directory:
        return None
    if process_id is None:
        process_id = int(os.environ.get("DL4J_TPU_WORKER_ID", "0"))
    prev = _PROC_HEARTBEAT
    if prev is not None:
        if str(prev.directory) == directory \
                and prev.process_id == process_id:
            return prev  # idempotent: bootstrap helper + script both call
        # two writers alternating beacons would flap the supervisor's
        # hang detector (only the new one's progress stamp advances)
        prev.stop()
    interval = float(os.environ.get(ENV_HEARTBEAT_INTERVAL,
                                    str(DEFAULT_HEARTBEAT_INTERVAL_S)))
    hb = HeartbeatWriter(directory, process_id,
                         interval_s=interval).start()
    _PROC_HEARTBEAT = hb
    return hb


def get_process_heartbeat() -> Optional[HeartbeatWriter]:
    return _PROC_HEARTBEAT


def set_process_heartbeat(hb: Optional[HeartbeatWriter]) -> None:
    global _PROC_HEARTBEAT
    _PROC_HEARTBEAT = hb


def touch_heartbeat() -> None:
    """Advance the process heartbeat's progress stamp (the supervisor's
    hang detector watches it). A global load + None check when no
    supervisor armed a heartbeat — cheap enough for every train step."""
    hb = _PROC_HEARTBEAT
    if hb is not None:
        hb.touch()


# -- the watchdog -------------------------------------------------------------


def default_collective_timeout_s() -> Optional[float]:
    """The armed deadline: ``DL4J_TPU_COLLECTIVE_TIMEOUT_S`` seconds
    (<= 0 disables), defaulting to 300 s. ``None`` means "no watchdog"."""
    raw = os.environ.get(ENV_COLLECTIVE_TIMEOUT)
    if raw is None:
        return DEFAULT_COLLECTIVE_TIMEOUT_S
    try:
        val = float(raw)
    except ValueError:
        return DEFAULT_COLLECTIVE_TIMEOUT_S
    return val if val > 0 else None


class CollectiveWatchdog:
    """Run blocking host collectives under a deadline; on stall, report
    then raise instead of hanging forever.

    ``run(fn, op=..., timeout_s=...)`` executes ``fn`` on a daemon worker
    thread and joins with the deadline. On timeout it:

    1. collects every thread's stack (the stalled collective's included),
    2. reads the heartbeat directory (when armed) to name dead peers,
    3. writes a crash report carrying both plus the flight-recorder
       timeline (``utils/crash.write_crash_report``),
    4. bumps ``resilience_collective_timeouts_total`` and records a
       ``collective.timeout`` flight event,
    5. raises :class:`CollectiveTimeout`.

    The abandoned worker thread keeps blocking (a stuck gRPC barrier is
    not interruptible from Python) — it is a daemon, so the expected
    next move, *exit and let the supervisor relaunch*, is never blocked
    by it. A late result from a timed-out collective is discarded."""

    def __init__(self, *, timeout_s: Optional[float] = None,
                 crash_dir: Optional[str] = None,
                 heartbeat_dir: Optional[str | Path] = None,
                 heartbeat_timeout_s: float = 5.0,
                 expect_peers: Optional[int] = None):
        self.timeout_s = timeout_s
        self.crash_dir = crash_dir if crash_dir is not None else \
            os.environ.get(ENV_CRASH_DIR, ".")
        self.heartbeat_dir = heartbeat_dir if heartbeat_dir is not None \
            else os.environ.get(ENV_HEARTBEAT_DIR)
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.expect_peers = expect_peers

    def resolve_timeout(self, timeout_s: Optional[float] = None
                        ) -> Optional[float]:
        if timeout_s is not None:
            return timeout_s if timeout_s > 0 else None
        if self.timeout_s is not None:
            return self.timeout_s if self.timeout_s > 0 else None
        return default_collective_timeout_s()

    def run(self, fn: Callable[[], Any], *, op: str = "collective",
            timeout_s: Optional[float] = None) -> Any:
        deadline = self.resolve_timeout(timeout_s)
        if deadline is None:
            return fn()
        box: Dict[str, Any] = {}
        done = threading.Event()

        def _call():
            try:
                box["result"] = fn()
            except BaseException as e:  # noqa: BLE001 — deliver to caller
                box["error"] = e
            finally:
                done.set()

        th = threading.Thread(target=_call, daemon=True,
                              name=f"collective-{op}")
        th.start()
        if not done.wait(deadline):
            raise self._on_stall(op, deadline)
        if "error" in box:
            raise box["error"]
        return box.get("result")

    def _on_stall(self, op: str, deadline: float) -> CollectiveTimeout:
        dead: List[int] = []
        if self.heartbeat_dir:
            try:
                dead = dead_peers(self.heartbeat_dir,
                                  timeout_s=self.heartbeat_timeout_s,
                                  expect=self.expect_peers)
            except OSError:
                pass
        msg = (f"collective '{op}' exceeded its {deadline:g}s deadline"
               + (f"; stale peers: {dead}" if dead else ""))
        report = None
        try:
            from deeplearning4j_tpu.utils.crash import write_crash_report

            report = write_crash_report(
                self.crash_dir,
                exception=CollectiveTimeout(msg, op=op, timeout_s=deadline),
                extra={"collective_op": op, "timeout_s": deadline,
                       "dead_peers": dead,
                       "thread_stacks": dump_thread_stacks()})
        except Exception:  # noqa: BLE001 — reporting never masks the stall
            pass
        try:
            from deeplearning4j_tpu.observability.flightrecorder import (
                record_event,
            )

            record_event("collective.timeout", op=op, timeout_s=deadline,
                         dead_peers=dead, crash_report=report)
        except Exception:  # noqa: BLE001
            pass
        try:
            from deeplearning4j_tpu.observability import metrics as _obsm

            if _obsm.enabled():
                _obsm.get_resilience_metrics().collective_timeouts_total.inc()
        except Exception:  # noqa: BLE001
            pass
        return CollectiveTimeout(msg, op=op, timeout_s=deadline,
                                 crash_report=report, dead=dead)


_WATCHDOG: Optional[CollectiveWatchdog] = None
_WATCHDOG_LOCK = threading.Lock()


def get_watchdog() -> CollectiveWatchdog:
    """Process-wide watchdog (env-configured deadline/dirs on first use);
    ``runtime/distributed.py`` routes guarded collectives through it."""
    global _WATCHDOG
    if _WATCHDOG is None:
        with _WATCHDOG_LOCK:
            if _WATCHDOG is None:
                _WATCHDOG = CollectiveWatchdog()
    return _WATCHDOG


def set_watchdog(wd: Optional[CollectiveWatchdog]) -> None:
    """Install (or with None, rebuild from env on next use) the
    process-wide watchdog — tests arm short deadlines this way."""
    global _WATCHDOG
    _WATCHDOG = wd
