"""Arrow IPC reader (↔ datavec-arrow: ArrowRecordReader / ArrowConverter).

ref: org.datavec.arrow.recordreader.ArrowRecordReader — DataVec reads Arrow
record batches as records for the transform engine. Here the IPC stream and
file (Feather V2) formats are decoded by a DEPENDENCY-FREE reader: a ~100
LoC minimal flatbuffer accessor plus the Arrow framing rules (encapsulated
messages, schema + record-batch flatbuffers, validity/offset/data buffer
layout). ``pyarrow``, when importable, is used only as an optional fast
path (``use_pyarrow=True``) — the wire-format knowledge lives here, the
same posture as the ONNX reader's dependency-free protobuf codec
(modelimport/onnx_proto.py).

Scope (matches what DataVec's reader handled in practice): little-endian,
uncompressed record batches of primitive columns — int8/16/32/64 (signed
and unsigned), float16/32/64, bool — plus utf8 strings (→ str) and binary
(→ raw bytes, never decoded). Nulls surface via the validity bitmap (float
columns → NaN, others → ``None`` in object output). Dictionary encoding,
compression and nested types raise a clear error.
"""

from __future__ import annotations

import struct
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

_CONT = 0xFFFFFFFF
_MAGIC = b"ARROW1"


# ---------------------------------------------------------------------------
# Minimal flatbuffer accessors
# ---------------------------------------------------------------------------

class _FB:
    """Positioned flatbuffer table accessor."""

    def __init__(self, buf: bytes, pos: int):
        self.buf = buf
        self.pos = pos

    @classmethod
    def root(cls, buf: bytes) -> "_FB":
        (off,) = struct.unpack_from("<I", buf, 0)
        return cls(buf, off)

    def _field_off(self, field_id: int) -> int:
        """Offset of field (relative to table pos), 0 if absent."""
        (soff,) = struct.unpack_from("<i", self.buf, self.pos)
        vt = self.pos - soff
        (vt_size,) = struct.unpack_from("<H", self.buf, vt)
        slot = 4 + 2 * field_id
        if slot + 2 > vt_size:
            return 0
        (off,) = struct.unpack_from("<H", self.buf, vt + slot)
        return off

    def scalar(self, field_id: int, fmt: str, default=0):
        off = self._field_off(field_id)
        if not off:
            return default
        return struct.unpack_from("<" + fmt, self.buf, self.pos + off)[0]

    def table(self, field_id: int) -> Optional["_FB"]:
        off = self._field_off(field_id)
        if not off:
            return None
        p = self.pos + off
        (rel,) = struct.unpack_from("<I", self.buf, p)
        return _FB(self.buf, p + rel)

    def string(self, field_id: int) -> Optional[str]:
        t = self.table(field_id)
        if t is None:
            return None
        (n,) = struct.unpack_from("<I", t.buf, t.pos)
        return t.buf[t.pos + 4:t.pos + 4 + n].decode()

    def vector(self, field_id: int) -> Tuple[int, int]:
        """(element count, position of first element); (0, -1) if absent."""
        t = self.table(field_id)
        if t is None:
            return 0, -1
        (n,) = struct.unpack_from("<I", t.buf, t.pos)
        return n, t.pos + 4

    def vector_tables(self, field_id: int) -> List["_FB"]:
        n, p = self.vector(field_id)
        out = []
        for i in range(n):
            (rel,) = struct.unpack_from("<I", self.buf, p + 4 * i)
            out.append(_FB(self.buf, p + 4 * i + rel))
        return out


# ---------------------------------------------------------------------------
# Arrow flatbuffer schemas (field ids from format/{Message,Schema}.fbs)
# ---------------------------------------------------------------------------

# Message: version(0), header_type(1), header(2), bodyLength(3)
# Schema:  endianness(0), fields(1)
# Field:   name(0), nullable(1), type_type(2), type(3), dictionary(4), children(5)
# Int:     bitWidth(0), is_signed(1)
# FloatingPoint: precision(0)
# RecordBatch: length(0), nodes(1), buffers(2), compression(3)

_TYPE_NULL, _TYPE_INT, _TYPE_FLOAT, _TYPE_BINARY, _TYPE_UTF8, _TYPE_BOOL = (
    1, 2, 3, 4, 5, 6)

_HEADER_SCHEMA, _HEADER_DICT, _HEADER_BATCH = 1, 2, 3


class _Field:
    def __init__(self, name: str, dtype: Any, kind: str):
        self.name = name
        self.dtype = dtype     # numpy dtype for primitives
        self.kind = kind       # 'primitive' | 'bool' | 'utf8'


def _parse_schema(tbl: _FB) -> List[_Field]:
    fields = []
    for f in tbl.vector_tables(1):
        name = f.string(0) or ""
        ttype = f.scalar(2, "B")
        t = f.table(3)
        if ttype == _TYPE_INT:
            bits = t.scalar(0, "i", 0) if t else 32
            # Schema.fbs: `is_signed: bool` — flatbuffer default is FALSE,
            # so signed columns carry it explicitly and unsigned omit it.
            signed = bool(t.scalar(1, "?", False)) if t else True
            dtype = np.dtype(("i" if signed else "u") + str(bits // 8))
            fields.append(_Field(name, dtype, "primitive"))
        elif ttype == _TYPE_FLOAT:
            prec = t.scalar(0, "h", 1) if t else 1
            dtype = {0: np.float16, 1: np.float32, 2: np.float64}[prec]
            fields.append(_Field(name, np.dtype(dtype), "primitive"))
        elif ttype == _TYPE_BOOL:
            fields.append(_Field(name, np.dtype(bool), "bool"))
        elif ttype == _TYPE_UTF8:
            fields.append(_Field(name, None, "utf8"))
        elif ttype == _TYPE_BINARY:
            fields.append(_Field(name, None, "binary"))  # raw bytes, no decode
        else:
            raise ValueError(
                f"arrow reader: unsupported column type id {ttype} for "
                f"field {name!r} (primitives, bool and utf8 are supported)")
        if f.vector_tables(5):
            raise ValueError(f"arrow reader: nested field {name!r} unsupported")
        if f.table(4) is not None:
            raise ValueError(
                f"arrow reader: dictionary-encoded field {name!r} unsupported")
    return fields


def _bitmap_get(buf: memoryview, i: int) -> bool:
    return bool(buf[i >> 3] & (1 << (i & 7)))


def _unpack_bitmap(buf: memoryview, length: int) -> np.ndarray:
    """Vectorized little-endian bitmap → bool[length]."""
    raw = np.frombuffer(buf, dtype=np.uint8, count=(length + 7) // 8)
    return np.unpackbits(raw, bitorder="little")[:length].astype(bool)


def _decode_batch(batch: _FB, body: memoryview,
                  fields: List[_Field]) -> Dict[str, np.ndarray]:
    if batch.table(3) is not None:
        raise ValueError("arrow reader: compressed record batches unsupported")
    n_nodes, nodes_pos = batch.vector(1)       # FieldNode structs: 16 bytes
    n_bufs, bufs_pos = batch.vector(2)         # Buffer structs: 16 bytes
    assert n_nodes == len(fields), (n_nodes, len(fields))

    def node(i):
        length, nulls = struct.unpack_from("<qq", batch.buf,
                                           nodes_pos + 16 * i)
        return length, nulls

    def buf(i):
        off, length = struct.unpack_from("<qq", batch.buf, bufs_pos + 16 * i)
        return body[off:off + length]

    out: Dict[str, np.ndarray] = {}
    bi = 0
    for fi, field in enumerate(fields):
        length, null_count = node(fi)
        validity = buf(bi); bi += 1
        valid = (_unpack_bitmap(validity, length) if null_count
                 else np.ones(length, bool))
        if field.kind == "primitive":
            data = buf(bi); bi += 1
            arr = np.frombuffer(data, dtype=field.dtype, count=length).copy()
            if null_count:
                if arr.dtype.kind == "f":
                    arr[~valid] = np.nan
                else:
                    obj = arr.astype(object)
                    obj[~valid] = None
                    arr = obj
        elif field.kind == "bool":
            data = buf(bi); bi += 1
            arr = _unpack_bitmap(data, length)
            if null_count:
                obj = arr.astype(object)
                obj[~valid] = None
                arr = obj
        else:  # utf8 / binary
            offsets = buf(bi); bi += 1
            data = buf(bi); bi += 1
            offs = np.frombuffer(offsets, dtype=np.int32, count=length + 1)
            vals: List[Any] = []
            for i in range(length):
                if not valid[i]:
                    vals.append(None)
                else:
                    chunk = bytes(data[offs[i]:offs[i + 1]])
                    vals.append(chunk.decode() if field.kind == "utf8"
                                else chunk)
            arr = np.array(vals, dtype=object)
        out[field.name] = arr
    return out


def _iter_messages(buf: bytes, pos: int = 0):
    """Yield (header_type, message_fb, body memoryview) per encapsulated
    message until EOS / end of buffer."""
    mv = memoryview(buf)
    n = len(buf)
    while pos + 8 <= n:
        (first,) = struct.unpack_from("<I", buf, pos)
        if first == _CONT:
            (meta_len,) = struct.unpack_from("<I", buf, pos + 4)
            meta_start = pos + 8
        else:  # pre-1.0 framing: no continuation marker
            meta_len = first
            meta_start = pos + 4
        if meta_len == 0:      # end-of-stream
            return
        msg = _FB.root(buf[meta_start:meta_start + meta_len])
        header_type = msg.scalar(1, "B")
        body_len = msg.scalar(3, "q")
        body_start = meta_start + meta_len
        yield header_type, msg, mv[body_start:body_start + body_len]
        pos = body_start + body_len


def read_arrow_stream(data: bytes) -> Dict[str, np.ndarray]:
    """Decode an Arrow IPC STREAM into {column: np.ndarray} (batches
    concatenated)."""
    fields: Optional[List[_Field]] = None
    batches: List[Dict[str, np.ndarray]] = []
    for header_type, msg, body in _iter_messages(data):
        if header_type == _HEADER_SCHEMA:
            fields = _parse_schema(msg.table(2))
        elif header_type == _HEADER_BATCH:
            if fields is None:
                raise ValueError("arrow reader: record batch before schema")
            batches.append(_decode_batch(msg.table(2), body, fields))
        elif header_type == _HEADER_DICT:
            raise ValueError("arrow reader: dictionary batches unsupported")
    if fields is None:
        raise ValueError("arrow reader: no schema message found")
    if not batches:
        return {f.name: np.array([]) for f in fields}
    return {f.name: np.concatenate([b[f.name] for b in batches])
            for f in fields}


def read_arrow_file(path) -> Dict[str, np.ndarray]:
    """Decode an Arrow FILE (Feather V2): magic-framed stream + footer."""
    data = Path(path).read_bytes()
    if not data.startswith(_MAGIC) or not data.endswith(_MAGIC):
        raise ValueError(f"{path}: not an Arrow file (missing ARROW1 magic)")
    # The stream section sits after 'ARROW1\0\0'; messages framing is
    # self-delimiting, so the footer needn't be parsed for sequential reads.
    return read_arrow_stream(data[8:])


def _read_any(path, use_pyarrow: bool):
    if use_pyarrow:
        import pyarrow as pa
        import pyarrow.ipc

        with pa.ipc.open_file(path) as rd:
            tbl = rd.read_all()
        return {name: np.asarray(col) for name, col in
                zip(tbl.column_names, tbl.columns)}
    return read_arrow_file(path)


class ArrowRecordReader:
    """↔ org.datavec.arrow.recordreader.ArrowRecordReader: iterate an Arrow
    file's rows as records (lists of values, column order preserved)."""

    def __init__(self, use_pyarrow: bool = False):
        self._use_pyarrow = use_pyarrow
        self._columns: Dict[str, np.ndarray] = {}
        self._names: List[str] = []
        self._i = 0
        self._n = 0

    def initialize(self, path):
        self._columns = _read_any(path, self._use_pyarrow)
        self._names = list(self._columns)
        self._n = len(next(iter(self._columns.values()))) if self._columns else 0
        self._i = 0
        return self

    @property
    def column_names(self) -> List[str]:
        return list(self._names)

    def has_next(self) -> bool:
        return self._i < self._n

    def next(self) -> List[Any]:
        if not self.has_next():
            raise StopIteration
        row = [self._columns[c][self._i] for c in self._names]
        self._i += 1
        return row

    def reset(self):
        self._i = 0

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next()
