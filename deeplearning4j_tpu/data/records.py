"""Record readers (↔ DataVec's record API, SURVEY §2.4).

ref: org.datavec.api.records.reader.{RecordReader, SequenceRecordReader}
and impls (CSVRecordReader, LineRecordReader, CollectionRecordReader,
CSVSequenceRecordReader), org.datavec.api.split.FileSplit, and the DL4J
bridge org.deeplearning4j.datasets.datavec.RecordReaderDataSetIterator.

A record is a list of python values (↔ List<Writable>); a sequence record
is a list of records. Readers are plain iterators with reset() — the
TPU-relevant part is the bridge at the bottom, which turns records into
dense numpy minibatches ready for jax.device_put (all dtype conversion
happens host-side, once, not per-op like the reference's Writable boxing).
"""

from __future__ import annotations

import csv
import io
import pathlib
from typing import Callable, Iterable, Iterator, List, Optional, Sequence, Union

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet


class RecordReader:
    """Iterable of records with reset (↔ org.datavec RecordReader)."""

    def __iter__(self) -> Iterator[List]:
        raise NotImplementedError

    def reset(self) -> None:  # most readers are re-iterable; stateful ones override
        pass

    def map_records(self, fn: Callable[[List], List]) -> "MappedRecordReader":
        return MappedRecordReader(self, fn)


class MappedRecordReader(RecordReader):
    def __init__(self, base: RecordReader, fn: Callable[[List], List]):
        self.base = base
        self.fn = fn

    def __iter__(self):
        return (self.fn(rec) for rec in self.base)

    def reset(self):
        self.base.reset()


class CollectionRecordReader(RecordReader):
    """↔ CollectionRecordReader: records from an in-memory collection."""

    def __init__(self, records: Sequence[List]):
        self.records = list(records)

    def __iter__(self):
        return iter(self.records)


class LineRecordReader(RecordReader):
    """↔ LineRecordReader: one record per line, single string value."""

    def __init__(self, paths: Union[str, pathlib.Path, Sequence]):
        self.paths = _as_paths(paths)

    def __iter__(self):
        for p in self.paths:
            with open(p, "r") as f:
                for line in f:
                    yield [line.rstrip("\n")]


class CSVRecordReader(RecordReader):
    """↔ CSVRecordReader: delimited text → typed-as-string records.

    skip_lines skips headers; values stay strings (the TransformProcess or
    the dataset bridge handles conversion, like the reference's Writables).
    """

    def __init__(self, paths: Union[str, pathlib.Path, Sequence],
                 *, delimiter: str = ",", skip_lines: int = 0,
                 quotechar: str = '"'):
        self.paths = _as_paths(paths)
        self.delimiter = delimiter
        self.skip_lines = skip_lines
        self.quotechar = quotechar

    def __iter__(self):
        for p in self.paths:
            with open(p, "r", newline="") as f:
                reader = csv.reader(f, delimiter=self.delimiter,
                                    quotechar=self.quotechar)
                for i, row in enumerate(reader):
                    if i < self.skip_lines or not row:
                        continue
                    yield list(row)

    @staticmethod
    def from_string(text: str, *, delimiter: str = ",", skip_lines: int = 0,
                    quotechar: str = '"') -> "CollectionRecordReader":
        reader = csv.reader(io.StringIO(text), delimiter=delimiter,
                            quotechar=quotechar)
        return CollectionRecordReader(
            [list(r) for i, r in enumerate(reader) if i >= skip_lines and r])

    def read_numeric(self):
        """All-numeric fast path: the files as ONE float32 [rows, cols]
        array (rows concatenated across paths). Uses the native mmap
        parser (native/src/fast_io.cpp) when built — the role DataVec's
        JavaCPP-native readers played on the ETL hot path. Files the
        native parser can't take (library absent, skip_lines>1, or a
        native parse error — e.g. quoted numeric fields) fall back to the
        csv-module path, which shares __iter__'s exact dialect handling;
        genuinely non-numeric content raises either way. Empty fields
        parse as NaN."""
        from deeplearning4j_tpu.data import native_csv

        def python_parse(p):
            rows = []
            with open(p, "r", newline="") as f:
                reader = csv.reader(f, delimiter=self.delimiter,
                                    quotechar=self.quotechar)
                for i, row in enumerate(reader):
                    if i < self.skip_lines or not row:
                        continue
                    rows.append([float(v) if v.strip() else float("nan")
                                 for v in row])
            return np.asarray(rows, np.float32).reshape(len(rows), -1)

        mats = []
        for p in self.paths:
            mat = None
            if self.skip_lines <= 1:
                try:
                    mat = native_csv.read_csv_f32(
                        p, skip_header=self.skip_lines == 1,
                        delimiter=self.delimiter)
                except ValueError as e:
                    if "parse error" not in str(e):
                        raise  # ragged/missing-file: same failure per path
                    mat = None  # maybe quoted fields — csv path decides
            if mat is None:
                mat = python_parse(p)
            mats.append(mat)
        return mats[0] if len(mats) == 1 else np.concatenate(mats, axis=0)


class RegexLineRecordReader(RecordReader):
    """↔ org.datavec RegexLineRecordReader: each line matched against a
    regex; capture groups become the record's values. Non-matching lines
    raise (the reference's strict behavior) unless ``skip_unmatched``."""

    def __init__(self, paths: Union[str, pathlib.Path, Sequence],
                 pattern: str, *, skip_lines: int = 0,
                 skip_unmatched: bool = False):
        import re

        self.paths = _as_paths(paths)
        self.pattern = re.compile(pattern)
        self.skip_lines = skip_lines
        self.skip_unmatched = skip_unmatched

    def __iter__(self):
        for p in self.paths:
            with open(p, "r") as f:
                for i, line in enumerate(f):
                    if i < self.skip_lines:
                        continue
                    m = self.pattern.fullmatch(line.rstrip("\n"))
                    if m is None:
                        if self.skip_unmatched:
                            continue
                        raise ValueError(
                            f"line {i} of {p} does not match pattern: "
                            f"{line!r}")
                    yield list(m.groups())


class JsonLineRecordReader(RecordReader):
    """↔ JacksonLineRecordReader: one JSON object per line; ``fields``
    selects and orders the record's values (dotted paths supported)."""

    def __init__(self, paths: Union[str, pathlib.Path, Sequence],
                 fields: Sequence[str]):
        self.paths = _as_paths(paths)
        self.fields = list(fields)

    @staticmethod
    def _get(obj, dotted):
        for part in dotted.split("."):
            obj = obj[part]
        return obj

    def __iter__(self):
        import json

        for p in self.paths:
            with open(p, "r") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    obj = json.loads(line)
                    yield [self._get(obj, fld) for fld in self.fields]


class SVMLightRecordReader(RecordReader):
    """↔ org.datavec SVMLightRecordReader: ``label idx:val idx:val ...``
    sparse lines → dense records [f0..fN-1, label] (label last, matching
    the default label_index=-1 of the dataset bridge)."""

    def __init__(self, paths: Union[str, pathlib.Path, Sequence],
                 num_features: int, *, zero_based: bool = False):
        self.paths = _as_paths(paths)
        self.num_features = num_features
        self.zero_based = zero_based

    def __iter__(self):
        off = 0 if self.zero_based else 1
        for p in self.paths:
            with open(p, "r") as f:
                for line in f:
                    line = line.split("#", 1)[0].strip()
                    if not line:
                        continue
                    parts = line.split()
                    dense = [0.0] * self.num_features
                    for tok in parts[1:]:
                        i, v = tok.split(":")
                        j = int(i) - off
                        if not 0 <= j < self.num_features:
                            raise ValueError(
                                f"feature index {i} out of range for "
                                f"num_features={self.num_features} "
                                f"(zero_based={self.zero_based}): {line!r}")
                        dense[j] = float(v)
                    yield dense + [parts[0]]


class SequenceRecordReader:
    """↔ SequenceRecordReader: iterator of sequences (list of records)."""

    def __iter__(self) -> Iterator[List[List]]:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class CollectionSequenceRecordReader(SequenceRecordReader):
    """↔ CollectionSequenceRecordReader: sequences from memory — the
    bridge from transform.convert_to_sequence/sliding_windows output to
    the padded-batch iterator."""

    def __init__(self, sequences):
        self.sequences = list(sequences)

    def __iter__(self):
        return iter(self.sequences)


class CSVSequenceRecordReader(SequenceRecordReader):
    """↔ CSVSequenceRecordReader: one CSV file per sequence."""

    def __init__(self, paths: Union[str, pathlib.Path, Sequence],
                 *, delimiter: str = ",", skip_lines: int = 0):
        self.paths = _as_paths(paths)
        self.delimiter = delimiter
        self.skip_lines = skip_lines

    def __iter__(self):
        for p in self.paths:
            reader = CSVRecordReader(p, delimiter=self.delimiter,
                                     skip_lines=self.skip_lines)
            yield list(reader)


def _as_paths(paths) -> List[pathlib.Path]:
    """↔ FileSplit: accept a file, a directory (sorted recursive), or a list."""
    if isinstance(paths, (str, pathlib.Path)):
        p = pathlib.Path(paths)
        if p.is_dir():
            return sorted(q for q in p.rglob("*") if q.is_file())
        return [p]
    return [pathlib.Path(p) for p in paths]


# --- DL4J bridge -----------------------------------------------------------


class RecordReaderDataSetIterator:
    """↔ org.deeplearning4j.datasets.datavec.RecordReaderDataSetIterator.

    Converts records to DataSet minibatches: columns [0, label_index) and
    (label_index, end) are features (float32); column label_index is the
    label — one-hot encoded when num_classes is given, float regression
    target(s) otherwise. label_index=-1 means "last column";
    label_index=None means unlabeled (features only).
    """

    def __init__(self, reader: RecordReader, batch_size: int, *,
                 label_index: Optional[int] = -1,
                 num_classes: Optional[int] = None,
                 regression: bool = False):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression

    def _split(self, rec: List):
        if self.label_index is None:
            return [float(v) for v in rec], None
        li = self.label_index if self.label_index >= 0 else len(rec) + self.label_index
        feats = [float(v) for i, v in enumerate(rec) if i != li]
        return feats, rec[li]

    def __iter__(self):
        feats, labels = [], []
        for rec in self.reader:
            f, lb = self._split(rec)
            feats.append(f)
            labels.append(lb)
            if len(feats) == self.batch_size:
                yield self._emit(feats, labels)
                feats, labels = [], []
        if feats:
            yield self._emit(feats, labels)

    def _emit(self, feats, labels) -> DataSet:
        x = np.asarray(feats, np.float32)
        if self.label_index is None:
            return DataSet(x, None)
        if self.regression or self.num_classes is None:
            y = np.asarray([[float(v)] for v in labels], np.float32)
        else:
            idx = np.asarray([int(float(v)) for v in labels])
            y = np.zeros((len(idx), self.num_classes), np.float32)
            y[np.arange(len(idx)), idx] = 1.0
        return DataSet(x, y)

    def reset(self):
        self.reader.reset()


class RecordReaderMultiDataSetIterator:
    """↔ org.deeplearning4j.datasets.datavec.RecordReaderMultiDataSetIterator
    (the Builder's addReader/addInput/addOutput/addOutputOneHot surface):
    compose columns from multiple record readers into NAMED multi-input /
    multi-output minibatches.

    Yields batches shaped for GraphModel training directly —
    ``{"features": {input_name: [N,...]}, "labels": {output_name: ...}}``
    with names matching the graph's input/output vertex names. Readers
    are iterated in lockstep (↔ the reference's aligned-readers
    requirement); unequal lengths raise.

    Builder-style::

        it = (RecordReaderMultiDataSetIterator(batch_size=32)
              .add_reader("csv", CSVRecordReader(path))
              .add_input("csv", 0, 4, name="in_a")     # cols [0, 4)
              .add_input("csv", 4, 8, name="in_b")
              .add_output_one_hot("csv", 8, 3, name="out"))
    """

    def __init__(self, batch_size: int):
        self.batch_size = batch_size
        self._readers: Dict[str, RecordReader] = {}
        self._inputs: List[tuple] = []   # (reader, from, to, name)
        self._outputs: List[tuple] = []  # (reader, from, to, name, classes)

    def add_reader(self, name: str, reader) -> "RecordReaderMultiDataSetIterator":
        if name in self._readers:
            raise ValueError(f"reader {name!r} already registered")
        self._readers[name] = reader
        return self

    def _check_reader(self, rname):
        if rname not in self._readers:
            raise ValueError(f"unknown reader {rname!r}; "
                             f"add_reader first (have {sorted(self._readers)})")

    def _check_fresh_name(self, name):
        taken = ({n for *_, n in self._inputs}
                 | {e[3] for e in self._outputs})
        if name in taken:
            raise ValueError(
                f"input/output name {name!r} already used — duplicate "
                "names would silently overwrite each other's columns")

    def add_input(self, reader: str, col_from: int = 0,
                  col_to: Optional[int] = None, *, name: Optional[str] = None
                  ) -> "RecordReaderMultiDataSetIterator":
        self._check_reader(reader)
        name = name or f"input_{len(self._inputs)}"
        self._check_fresh_name(name)
        self._inputs.append((reader, col_from, col_to, name))
        return self

    def add_output(self, reader: str, col_from: int = 0,
                   col_to: Optional[int] = None, *,
                   name: Optional[str] = None
                   ) -> "RecordReaderMultiDataSetIterator":
        self._check_reader(reader)
        name = name or f"output_{len(self._outputs)}"
        self._check_fresh_name(name)
        self._outputs.append((reader, col_from, col_to, name, None))
        return self

    def add_output_one_hot(self, reader: str, col: int, num_classes: int, *,
                           name: Optional[str] = None
                           ) -> "RecordReaderMultiDataSetIterator":
        self._check_reader(reader)
        name = name or f"output_{len(self._outputs)}"
        self._check_fresh_name(name)
        self._outputs.append((reader, col, col + 1, name, num_classes))
        return self

    def _batches(self):
        names = list(self._readers)
        iters = {n: iter(r) for n, r in self._readers.items()}
        while True:
            rows = {n: [] for n in names}
            for _ in range(self.batch_size):
                recs = {}
                for n in names:
                    recs[n] = next(iters[n], None)
                live = [n for n in names if recs[n] is not None]
                if not live:
                    break
                if len(live) != len(names):
                    raise ValueError(
                        f"readers exhausted unevenly: {sorted(live)} still "
                        f"have records, {sorted(set(names) - set(live))} "
                        "ended (the reference requires aligned readers)")
                for n in names:
                    rows[n].append(recs[n])
            if not rows[names[0]]:
                return
            yield rows

    def __iter__(self):
        from deeplearning4j_tpu.data.dataset import MultiDataSet

        if not self._readers or not self._inputs:
            raise ValueError(
                "configure at least one reader and one input "
                "(add_reader/add_input) before iterating")
        for r in self._readers.values():
            r.reset()
        for rows in self._batches():
            def slab(rname, c0, c1):
                return np.asarray(
                    [[float(v) for v in rec[c0:c1]]
                     for rec in rows[rname]], np.float32)

            feats = {nm: slab(rd, c0, c1)
                     for rd, c0, c1, nm in self._inputs}
            labels = {}
            for rd, c0, c1, nm, classes in self._outputs:
                arr = slab(rd, c0, c1)
                if classes is not None:
                    ids = arr[:, 0].astype(np.int64)
                    if (ids < 0).any() or (ids >= classes).any():
                        raise ValueError(
                            f"one-hot output {nm!r}: class id outside "
                            f"[0, {classes})")
                    arr = np.eye(classes, dtype=np.float32)[ids]
                labels[nm] = arr
            yield MultiDataSet(features=feats, labels=labels)

    def reset(self):
        pass  # fresh iterators each __iter__


class SequenceRecordReaderDataSetIterator:
    """↔ org.deeplearning4j.datasets.datavec.SequenceRecordReaderDataSetIterator:
    sequence records → padded RNN minibatches with masks.

    Modes (the reference's common three):

    - ONE reader + ``label_index``: each timestep's column ``label_index``
      is the per-step label (sequence labeling); remaining columns are
      features.
    - TWO readers (features + labels), ``align="equal_length"``: per-step
      labels from the second reader (must match step counts).
    - TWO readers, ``align="align_end"``: one label record per sequence
      (sequence classification) — the label sits at the LAST live step and
      ``labels_mask`` marks exactly that step (the reference's
      AlignmentMode.ALIGN_END layout; pair with RnnOutputLayer + masked
      eval, or a LastTimeStep head).

    Sequences pad to the batch max length; ``features_mask`` [N,T] marks
    live steps. ``num_classes`` one-hots integer labels; ``regression``
    keeps them as floats.
    """

    def __init__(self, reader: SequenceRecordReader, batch_size: int, *,
                 labels_reader: Optional[SequenceRecordReader] = None,
                 label_index: Optional[int] = None,
                 num_classes: Optional[int] = None,
                 regression: bool = False,
                 align: str = "equal_length"):
        if (labels_reader is None) == (label_index is None):
            raise ValueError(
                "exactly one of labels_reader / label_index is required")
        if align not in ("equal_length", "align_end"):
            raise ValueError(f"align {align!r}; "
                             "valid: equal_length|align_end")
        if align == "align_end" and labels_reader is None:
            raise ValueError("align_end needs a separate labels_reader")
        if not regression and num_classes is None:
            raise ValueError("classification needs num_classes "
                             "(or set regression=True)")
        self.reader = reader
        self.labels_reader = labels_reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.align = align

    def _label_array(self, vals):
        a = np.asarray(vals, np.float32)
        if self.regression:
            return a.reshape(len(vals), -1)
        ids = a.reshape(-1).astype(np.int64)
        if (ids < 0).any() or (ids >= self.num_classes).any():
            raise ValueError(
                f"label id outside [0, {self.num_classes})")
        # O(t*C) one-hot (an np.eye would be C x C — quadratic in the
        # label space)
        y = np.zeros((len(ids), self.num_classes), np.float32)
        y[np.arange(len(ids)), ids] = 1.0
        return y

    def __iter__(self):
        self.reader.reset()
        feats_it = iter(self.reader)
        labs_it = None
        if self.labels_reader is not None:
            self.labels_reader.reset()
            labs_it = iter(self.labels_reader)
        while True:
            seqs, labs = [], []
            for _ in range(self.batch_size):
                seq = next(feats_it, None)
                if seq is None:
                    break
                lab = next(labs_it, None) if labs_it is not None else None
                if labs_it is not None and lab is None:
                    raise ValueError("labels reader exhausted early")
                seqs.append(seq)
                labs.append(lab)
            if not seqs:
                return
            yield self._emit(seqs, labs)

    def _emit(self, seqs, labs):
        n = len(seqs)
        t_max = max(len(s) for s in seqs)
        fmask = np.zeros((n, t_max), np.float32)
        feats = None
        labels = None
        lmask = np.zeros((n, t_max), np.float32)
        for i, seq in enumerate(seqs):
            t = len(seq)
            fmask[i, :t] = 1.0
            if self.label_index is not None:
                # normalize negatives (label_index=-1 = last column, the
                # RecordReaderDataSetIterator convention) or the filter
                # below would silently leak the label into the features
                li = (self.label_index if self.label_index >= 0
                      else len(seq[0]) + self.label_index)
                rows = [[float(v) for j, v in enumerate(r)
                         if j != li] for r in seq]
                lab_vals = [r[li] for r in seq]
            else:
                rows = [[float(v) for v in r] for r in seq]
            if feats is None:
                feats = np.zeros((n, t_max, len(rows[0])), np.float32)
            feats[i, :t] = rows

            if self.label_index is not None:
                la = self._label_array(lab_vals)          # [t, C]
                lmask[i, :t] = 1.0
            elif self.align == "equal_length":
                if len(labs[i]) != t:
                    raise ValueError(
                        f"labels sequence length {len(labs[i])} != "
                        f"features length {t} (use align='align_end' for "
                        "per-sequence labels)")
                la = self._label_array([r[0] if len(r) == 1 else r
                                        for r in labs[i]])
                lmask[i, :t] = 1.0
            else:  # align_end: one label record at the LAST live step
                if len(labs[i]) != 1:
                    raise ValueError(
                        "align_end expects one label record per sequence")
                la_last = self._label_array(
                    [labs[i][0][0] if len(labs[i][0]) == 1
                     else labs[i][0]])                    # [1, C]
                la = np.zeros((t, la_last.shape[-1]), np.float32)
                la[t - 1] = la_last[0]
                lmask[i, t - 1] = 1.0
            if labels is None:
                labels = np.zeros((n, t_max, la.shape[-1]), np.float32)
            labels[i, :t] = la
        return DataSet(feats, labels, features_mask=fmask,
                       labels_mask=lmask)

    def reset(self):
        pass  # fresh iterators each __iter__
