"""ctypes shim over the native IO fast paths (native/src/fast_io.cpp).

↔ the reference's native-backed readers (DataVec's hot paths run through
JavaCPP-wrapped C++; SURVEY §2.4/§2.8.12): numeric CSV → float32 matrix
in one mmapped pass, ~an order of magnitude faster than the Python
csv+float() path on large files. The general (typed/quoted) path stays
in data/records.py; this is the fast lane `CSVRecordReader(numeric=True)`
takes when the library is built.

Build: ``make -C native lib/libdl4j_tpu_io.so`` (no PJRT/tensorflow
dependency for this library; plain ``make -C native`` builds it first and
then attempts the PJRT runtime). When the .so is absent, ``available()``
is False and callers fall back silently.
"""

from __future__ import annotations

import ctypes
import os
from pathlib import Path
from typing import Optional

import numpy as np

_LIB_PATH = Path(__file__).resolve().parents[2] / "native" / "lib" / \
    "libdl4j_tpu_io.so"
_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    path = os.environ.get("DL4J_TPU_IO_LIB", str(_LIB_PATH))
    if not os.path.exists(path):
        return None
    lib = ctypes.CDLL(path)
    lib.dl4j_csv_dims.restype = ctypes.c_int
    lib.dl4j_csv_dims.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
    lib.dl4j_csv_read_f32.restype = ctypes.c_int
    lib.dl4j_csv_read_f32.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char,
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int64]
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


_ERRORS = {1: "open/stat failed", 2: "ragged rows", 3: "parse error",
           4: "row count changed between passes"}


def read_csv_f32(path, *, skip_header: bool = False,
                 delimiter: str = ",") -> Optional[np.ndarray]:
    """Parse an all-numeric CSV into a float32 [rows, cols] array via the
    native reader. Returns None when the native library isn't built
    (caller falls back); raises ValueError on malformed content."""
    lib = _load()
    if lib is None:
        return None
    if len(delimiter) != 1:
        raise ValueError(f"single-char delimiter required: {delimiter!r}")
    p = str(path).encode()
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = lib.dl4j_csv_dims(p, int(skip_header),
                           delimiter.encode(), ctypes.byref(rows),
                           ctypes.byref(cols))
    if rc:
        raise ValueError(
            f"native csv dims failed on {path}: {_ERRORS.get(rc, rc)}")
    out = np.empty((rows.value, cols.value), np.float32)
    if out.size:
        rc = lib.dl4j_csv_read_f32(
            p, int(skip_header), delimiter.encode(),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            rows.value, cols.value)
        if rc:
            raise ValueError(
                f"native csv read failed on {path}: {_ERRORS.get(rc, rc)}")
    return out
