"""Minibatch containers (↔ org.nd4j.linalg.dataset.{DataSet, MultiDataSet}).

A DataSet is a pytree (registered dataclass) so it can flow directly into a
jitted train step and be device_put with a sharding in one call — the
TPU-native replacement for the reference's workspace-attached INDArray
batches.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DataSet:
    """↔ org.nd4j.linalg.dataset.DataSet (features, labels + masks)."""

    features: Any
    labels: Any
    features_mask: Optional[Any] = None
    labels_mask: Optional[Any] = None

    @property
    def num_examples(self) -> int:
        return self.features.shape[0]

    def as_dict(self) -> Dict[str, Any]:
        d = {"features": self.features, "labels": self.labels}
        if self.labels_mask is not None:
            d["mask"] = self.labels_mask
        return d

    def split(self, n: int):
        """Split into n equal shards along batch (host-side)."""
        fs = np.array_split(np.asarray(self.features), n)
        ls = np.array_split(np.asarray(self.labels), n)
        return [DataSet(f, l) for f, l in zip(fs, ls)]


def as_batch_dict(batch) -> Dict[str, Any]:
    """Coerce DataSet-likes, (x, y) tuples, or ready dicts into the batch
    dict the loss functions consume."""
    if isinstance(batch, dict):
        return batch
    if hasattr(batch, "features") and hasattr(batch, "labels"):
        d = {"features": batch.features, "labels": batch.labels}
        mask = getattr(batch, "labels_mask", None)
        if mask is not None:
            d["mask"] = mask
        return d
    if isinstance(batch, (tuple, list)) and len(batch) == 2:
        return {"features": batch[0], "labels": batch[1]}
    raise TypeError(f"cannot interpret batch of type {type(batch)}")


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MultiDataSet:
    """↔ org.nd4j.linalg.dataset.MultiDataSet (N features, M labels)."""

    features: Sequence[Any]
    labels: Sequence[Any]
    features_masks: Optional[Sequence[Any]] = None
    labels_masks: Optional[Sequence[Any]] = None
