"""Columnar + SQL record readers (↔ datavec-arrow ArrowRecordReader and
datavec-jdbc JDBCRecordReader; SURVEY §2.4 "other data domains").

TPU-first: the reference routes Arrow record batches and JDBC ResultSets
through per-value Writable boxing. Here columnar data stays columnar —
numpy column arrays end-to-end — and only the record-API view is row-wise,
so the dataset bridge can slice dense minibatches without materializing
Python rows. The SQL reader uses the stdlib ``sqlite3`` driver (the
environment's no-new-deps rule); the reader API mirrors JDBCRecordReader
(query + column metadata) so other DB-API drivers drop in.
"""

from __future__ import annotations

import pathlib
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from deeplearning4j_tpu.data.records import RecordReader


class ColumnarRecordReader(RecordReader):
    """↔ ArrowRecordReader: named column arrays viewed as records.

    Accepts {name: array} (the in-memory "record batch"), or an ``.npz``
    path holding the columns. Column order follows ``schema`` when given.
    """

    def __init__(self, columns: Union[Dict[str, Sequence], str, pathlib.Path],
                 schema: Optional[Sequence[str]] = None):
        if isinstance(columns, (str, pathlib.Path)):
            with np.load(columns) as z:
                columns = {k: z[k] for k in z.files}
        self.columns = {k: np.asarray(v) for k, v in columns.items()}
        lens = {len(v) for v in self.columns.values()}
        if len(lens) > 1:
            raise ValueError(f"ragged columns: lengths {sorted(lens)}")
        self.names = list(schema) if schema is not None else list(self.columns)
        missing = [n for n in self.names if n not in self.columns]
        if missing:
            raise ValueError(f"schema names missing from columns: {missing}")
        self._n = lens.pop() if lens else 0

    def __len__(self):
        return self._n

    def __iter__(self):
        cols = [self.columns[n] for n in self.names]
        for i in range(self._n):
            yield [c[i].item() if c[i].shape == () else c[i] for c in cols]

    # columnar fast path (what the reference's Arrow batches can't give the
    # JVM without copying): dense matrices straight from the columns
    def features_matrix(self, names: Optional[Sequence[str]] = None) -> np.ndarray:
        names = list(names) if names is not None else self.names
        return np.stack([np.asarray(self.columns[n], np.float32)
                         for n in names], axis=1)


class SQLRecordReader(RecordReader):
    """↔ JDBCRecordReader: records from a SQL query.

    ``conn`` is any DB-API connection (default path: stdlib sqlite3 opened
    on ``database``). The query runs at iteration (and again on reset),
    mirroring the reference's fetch-on-next semantics.
    """

    def __init__(self, query: str, *, database: Optional[str] = None,
                 conn=None, params: Sequence = ()):
        if conn is None:
            if database is None:
                raise ValueError("need a database path or an open conn")
            import sqlite3

            # check_same_thread=False: iteration may happen on a prefetch
            # worker (AsyncDataSetIterator); access is still serialized per
            # cursor by the reader's own iteration
            conn = sqlite3.connect(database, check_same_thread=False)
            self._owns = True
        else:
            self._owns = False
        self.conn = conn
        self.query = query
        self.params = tuple(params)
        self.column_names: Optional[List[str]] = None

    def __iter__(self):
        cur = self.conn.cursor()
        try:
            cur.execute(self.query, self.params)
            if cur.description:
                self.column_names = [d[0] for d in cur.description]
            for row in cur:
                yield list(row)
        finally:
            cur.close()

    def close(self):
        if self._owns:
            self.conn.close()
