"""MNIST loader (↔ org.deeplearning4j.datasets.iterator.impl.MnistDataSetIterator
+ MnistDataFetcher).

The reference auto-downloads idx files; this environment has no network, so
the loader searches standard locations for idx or npz files and otherwise
falls back to a deterministic synthetic stand-in with MNIST's exact shapes
and a learnable structure (class-dependent template + noise) so convergence
tests and benchmarks exercise the real compute path.
"""

from __future__ import annotations

import gzip
import os
import struct
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

SEARCH_DIRS = [
    "/root/data/mnist",
    "/root/datasets/mnist",
    os.path.expanduser("~/.cache/mnist"),
    os.path.expanduser("~/.deeplearning4j/mnist"),
]

_FILES = {
    "train_images": ["train-images-idx3-ubyte", "train-images-idx3-ubyte.gz"],
    "train_labels": ["train-labels-idx1-ubyte", "train-labels-idx1-ubyte.gz"],
    "test_images": ["t10k-images-idx3-ubyte", "t10k-images-idx3-ubyte.gz"],
    "test_labels": ["t10k-labels-idx1-ubyte", "t10k-labels-idx1-ubyte.gz"],
}


def _read_idx(path: Path) -> np.ndarray:
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        magic, = struct.unpack(">I", f.read(4))
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


def _find_real() -> Optional[dict]:
    for d in SEARCH_DIRS:
        dd = Path(d)
        if not dd.is_dir():
            continue
        found = {}
        for key, names in _FILES.items():
            for n in names:
                if (dd / n).exists():
                    found[key] = dd / n
                    break
        if len(found) == 4:
            return found
        npz = dd / "mnist.npz"
        if npz.exists():
            return {"npz": npz}
    return None


def _synthetic(n_train: int, n_test: int, seed: int = 7):
    """Deterministic learnable stand-in: each class is a fixed random 28×28
    template revealed through noise. Linear+conv models can reach >95% on it,
    so convergence tests remain meaningful."""
    rng = np.random.default_rng(seed)
    templates = rng.normal(0.0, 1.0, (10, 28, 28)).astype(np.float32)

    def make(n, seed2):
        r = np.random.default_rng(seed2)
        y = r.integers(0, 10, n)
        noise = r.normal(0.0, 1.0, (n, 28, 28)).astype(np.float32)
        x = 1.0 * templates[y] + 0.5 * noise
        x = (x - x.min()) / (x.max() - x.min())  # into [0,1] like pixel/255
        return (x * 255).astype(np.uint8), y.astype(np.int64)

    xtr, ytr = make(n_train, seed + 1)
    xte, yte = make(n_test, seed + 2)
    return (xtr, ytr), (xte, yte)


def load_mnist(
    *,
    n_train: Optional[int] = None,
    n_test: Optional[int] = None,
    normalize: bool = True,
    one_hot: bool = True,
    flat: bool = False,
) -> Tuple[Tuple[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray], bool]:
    """Returns ((x_train, y_train), (x_test, y_test), is_real).

    Images are [N,28,28,1] float32 in [0,1] (NHWC; ``flat`` → [N,784]);
    labels one-hot [N,10] float32 (or int ids if one_hot=False).
    """
    real = _find_real()
    if real is not None:
        if "npz" in real:
            with np.load(real["npz"]) as z:
                xtr, ytr = z["x_train"], z["y_train"]
                xte, yte = z["x_test"], z["y_test"]
        else:
            xtr = _read_idx(real["train_images"])
            ytr = _read_idx(real["train_labels"])
            xte = _read_idx(real["test_images"])
            yte = _read_idx(real["test_labels"])
        is_real = True
    else:
        (xtr, ytr), (xte, yte) = _synthetic(n_train or 60000, n_test or 10000)
        is_real = False

    if n_train:
        xtr, ytr = xtr[:n_train], ytr[:n_train]
    if n_test:
        xte, yte = xte[:n_test], yte[:n_test]

    def prep(x, y):
        x = x.astype(np.float32)
        if normalize:
            x = x / 255.0
        x = x.reshape(x.shape[0], -1) if flat else x.reshape(x.shape[0], 28, 28, 1)
        if one_hot:
            oh = np.zeros((y.shape[0], 10), np.float32)
            oh[np.arange(y.shape[0]), y] = 1.0
            y = oh
        return x, y

    return prep(xtr, ytr), prep(xte, yte), is_real
