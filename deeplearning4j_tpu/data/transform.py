"""Schema-typed transform engine (↔ DataVec TransformProcess, SURVEY §2.4).

ref: org.datavec.api.transform.{schema.Schema, TransformProcess} and its
local executor (datavec-local LocalTransformExecutor). The reference builds
a serializable op pipeline over typed columns (remove/convert/filter/
normalize/math) executed locally or on Spark. Here the pipeline is the same
idea — a list of serializable column ops, each also transforming the
schema — executed locally (a Spark analogue is unnecessary: at TPU scale
the transform output feeds the host input pipeline per process, and
parallelism across hosts is per-host data sharding, not a Spark cluster).
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

COLUMN_TYPES = ("string", "integer", "double", "categorical", "long", "time")


@dataclasses.dataclass
class Column:
    name: str
    type: str = "string"
    categories: Optional[List[str]] = None  # for categorical


class Schema:
    """↔ org.datavec.api.transform.schema.Schema (builder pattern kept)."""

    def __init__(self, columns: Optional[List[Column]] = None):
        self.columns = columns or []

    # builder-style adders
    def add_string_column(self, name):
        self.columns.append(Column(name, "string"))
        return self

    def add_integer_column(self, name):
        self.columns.append(Column(name, "integer"))
        return self

    def add_double_column(self, name):
        self.columns.append(Column(name, "double"))
        return self

    def add_categorical_column(self, name, categories: Sequence[str]):
        self.columns.append(Column(name, "categorical", list(categories)))
        return self

    def names(self) -> List[str]:
        return [c.name for c in self.columns]

    def index_of(self, name: str) -> int:
        try:
            return self.names().index(name)
        except ValueError:
            raise KeyError(f"no column {name!r}; have {self.names()}")

    def column(self, name: str) -> Column:
        return self.columns[self.index_of(name)]

    def copy(self) -> "Schema":
        return Schema([dataclasses.replace(c) for c in self.columns])

    def to_dict(self):
        return {"columns": [dataclasses.asdict(c) for c in self.columns]}

    @staticmethod
    def from_dict(d):
        return Schema([Column(**c) for c in d["columns"]])

    def __repr__(self):
        cols = ", ".join(f"{c.name}:{c.type}" for c in self.columns)
        return f"Schema({cols})"


# --- transform ops ---------------------------------------------------------
# Each op: apply(records, schema) -> records AND out_schema(schema) -> schema.
# Ops are dataclasses → JSON round-trip like the reference's Jackson serde.

_OP_REGISTRY: Dict[str, type] = {}


def _register(cls):
    _OP_REGISTRY[cls.__name__] = cls
    return cls


@_register
@dataclasses.dataclass
class RemoveColumns:
    names: List[str]

    def out_schema(self, s: Schema) -> Schema:
        return Schema([c for c in s.copy().columns if c.name not in self.names])

    def apply(self, records, s: Schema):
        idxs = {s.index_of(n) for n in self.names}
        return [[v for i, v in enumerate(r) if i not in idxs] for r in records]


@_register
@dataclasses.dataclass
class KeepColumns:
    names: List[str]

    def out_schema(self, s: Schema) -> Schema:
        return Schema([c for c in s.copy().columns if c.name in self.names])

    def apply(self, records, s: Schema):
        idxs = [s.index_of(n) for n in s.names() if n in self.names]
        return [[r[i] for i in idxs] for r in records]


@_register
@dataclasses.dataclass
class RenameColumn:
    old: str
    new: str

    def out_schema(self, s: Schema) -> Schema:
        out = s.copy()
        out.columns[s.index_of(self.old)].name = self.new
        return out

    def apply(self, records, s: Schema):
        return records


@_register
@dataclasses.dataclass
class ConvertToDouble:
    names: List[str]

    def out_schema(self, s: Schema) -> Schema:
        out = s.copy()
        for n in self.names:
            out.columns[s.index_of(n)].type = "double"
        return out

    def apply(self, records, s: Schema):
        idxs = [s.index_of(n) for n in self.names]
        out = []
        for r in records:
            r = list(r)
            for i in idxs:
                r[i] = float(r[i])
            out.append(r)
        return out


@_register
@dataclasses.dataclass
class CategoricalToInteger:
    """↔ CategoricalToIntegerTransform: category → its index."""

    names: List[str]

    def out_schema(self, s: Schema) -> Schema:
        out = s.copy()
        for n in self.names:
            col = out.columns[s.index_of(n)]
            if col.type != "categorical" or not col.categories:
                raise ValueError(f"column {n!r} is not categorical")
            col.type = "integer"
        return out

    def apply(self, records, s: Schema):
        maps = {s.index_of(n): {c: i for i, c in enumerate(s.column(n).categories)}
                for n in self.names}
        out = []
        for r in records:
            r = list(r)
            for i, m in maps.items():
                r[i] = m[r[i]]
            out.append(r)
        return out


@_register
@dataclasses.dataclass
class CategoricalToOneHot:
    """↔ CategoricalToOneHotTransform: expands the column to K 0/1 columns."""

    name: str

    def out_schema(self, s: Schema) -> Schema:
        i = s.index_of(self.name)
        col = s.column(self.name)
        if col.type != "categorical" or not col.categories:
            raise ValueError(f"column {self.name!r} is not categorical")
        cols = s.copy().columns
        onehot = [Column(f"{self.name}[{c}]", "integer") for c in col.categories]
        return Schema(cols[:i] + onehot + cols[i + 1:])

    def apply(self, records, s: Schema):
        i = s.index_of(self.name)
        cats = s.column(self.name).categories
        m = {c: j for j, c in enumerate(cats)}
        out = []
        for r in records:
            hot = [0] * len(cats)
            hot[m[r[i]]] = 1
            out.append(list(r[:i]) + hot + list(r[i + 1:]))
        return out


@_register
@dataclasses.dataclass
class FilterInvalid:
    """Drop records with missing/NaN values in the given columns."""

    names: List[str]

    def out_schema(self, s: Schema) -> Schema:
        return s.copy()

    def apply(self, records, s: Schema):
        idxs = [s.index_of(n) for n in self.names]

        def ok(r):
            for i in idxs:
                v = r[i]
                if v is None or v == "":
                    return False
                if isinstance(v, float) and math.isnan(v):
                    return False
            return True

        return [r for r in records if ok(r)]


@_register
@dataclasses.dataclass
class FilterByCondition:
    """↔ ConditionFilter. condition: (column op value) kept serializable."""

    column: str
    op: str  # "lt" | "lte" | "gt" | "gte" | "eq" | "neq" | "in"
    value: Any
    keep_matching: bool = False  # reference semantics: filter REMOVES matches

    _OPS = {
        "lt": lambda a, b: a < b, "lte": lambda a, b: a <= b,
        "gt": lambda a, b: a > b, "gte": lambda a, b: a >= b,
        "eq": lambda a, b: a == b, "neq": lambda a, b: a != b,
        "in": lambda a, b: a in b,
    }

    def out_schema(self, s: Schema) -> Schema:
        return s.copy()

    def apply(self, records, s: Schema):
        i = s.index_of(self.column)
        f = self._OPS[self.op]
        keep = self.keep_matching
        return [r for r in records if f(r[i], self.value) == keep]


@_register
@dataclasses.dataclass
class DoubleMathOp:
    """↔ DoubleMathOpTransform: column = column <op> scalar."""

    column: str
    op: str  # add sub mul div pow
    value: float

    _OPS = {
        "add": lambda a, b: a + b, "sub": lambda a, b: a - b,
        "mul": lambda a, b: a * b, "div": lambda a, b: a / b,
        "pow": lambda a, b: a ** b,
    }

    def out_schema(self, s: Schema) -> Schema:
        out = s.copy()
        out.columns[s.index_of(self.column)].type = "double"
        return out

    def apply(self, records, s: Schema):
        i = s.index_of(self.column)
        f = self._OPS[self.op]
        out = []
        for r in records:
            r = list(r)
            r[i] = f(float(r[i]), self.value)
            out.append(r)
        return out


@_register
@dataclasses.dataclass
class Normalize:
    """↔ the transform-side normalizers: minmax or standardize, with stats
    either given or fit via TransformProcess.fit()."""

    column: str
    mode: str = "standardize"  # or "minmax"
    mean: Optional[float] = None
    std: Optional[float] = None
    min: Optional[float] = None
    max: Optional[float] = None

    def out_schema(self, s: Schema) -> Schema:
        out = s.copy()
        out.columns[s.index_of(self.column)].type = "double"
        return out

    def fit(self, records, s: Schema):
        vals = np.asarray([float(r[s.index_of(self.column)]) for r in records])
        if self.mode == "standardize":
            self.mean, self.std = float(vals.mean()), float(vals.std() + 1e-12)
        else:
            self.min, self.max = float(vals.min()), float(vals.max())

    def apply(self, records, s: Schema):
        i = s.index_of(self.column)
        if self.mode == "standardize":
            if self.mean is None:
                raise ValueError(f"Normalize({self.column}): call fit() first")
            f = lambda v: (float(v) - self.mean) / self.std
        else:
            if self.min is None:
                raise ValueError(f"Normalize({self.column}): call fit() first")
            rng = (self.max - self.min) or 1.0
            f = lambda v: (float(v) - self.min) / rng
        out = []
        for r in records:
            r = list(r)
            r[i] = f(r[i])
            out.append(r)
        return out


class TransformProcess:
    """↔ org.datavec.api.transform.TransformProcess (builder + executor).

    Build with chained calls, then ``fit`` (for stateful normalizers) and
    ``execute``; ``final_schema`` gives the output schema. JSON round-trip
    via to_json/from_json like the reference.
    """

    def __init__(self, initial_schema: Schema, steps: Optional[List] = None):
        self.initial_schema = initial_schema
        self.steps = steps or []

    def _add(self, op) -> "TransformProcess":
        self.steps.append(op)
        return self

    def add(self, op) -> "TransformProcess":
        """Append any transform implementing apply/out_schema — the
        extension point for custom transforms (↔ TransformProcess.Builder
        .transform(Transform)); used by e.g. data/geo.py."""
        return self._add(op)

    # builder sugar mirroring reference method names
    def remove_columns(self, *names):
        return self._add(RemoveColumns(list(names)))

    def keep_columns(self, *names):
        return self._add(KeepColumns(list(names)))

    def rename_column(self, old, new):
        return self._add(RenameColumn(old, new))

    def convert_to_double(self, *names):
        return self._add(ConvertToDouble(list(names)))

    def categorical_to_integer(self, *names):
        return self._add(CategoricalToInteger(list(names)))

    def categorical_to_one_hot(self, name):
        return self._add(CategoricalToOneHot(name))

    def filter_invalid(self, *names):
        return self._add(FilterInvalid(list(names)))

    def filter_by_condition(self, column, op, value, keep_matching=False):
        return self._add(FilterByCondition(column, op, value, keep_matching))

    def double_math_op(self, column, op, value):
        return self._add(DoubleMathOp(column, op, value))

    def normalize(self, column, mode="standardize", **stats):
        return self._add(Normalize(column, mode, **stats))

    # -- execution ---------------------------------------------------------

    def schemas(self) -> List[Schema]:
        out = [self.initial_schema]
        for op in self.steps:
            out.append(op.out_schema(out[-1]))
        return out

    @property
    def final_schema(self) -> Schema:
        return self.schemas()[-1]

    def fit(self, records) -> "TransformProcess":
        """Compute stats for stateful steps against `records` (applied
        through the preceding steps first, like normalizer fit order)."""
        records = [list(r) for r in records]
        schemas = self.schemas()
        for op, schema in zip(self.steps, schemas):
            if hasattr(op, "fit"):
                op.fit(records, schema)
            records = op.apply(records, schema)
        return self

    def execute(self, records) -> List[List]:
        """↔ LocalTransformExecutor.execute."""
        records = [list(r) for r in records]
        schemas = self.schemas()
        for op, schema in zip(self.steps, schemas):
            records = op.apply(records, schema)
        return records

    def to_matrix(self, records) -> np.ndarray:
        """Execute and densify to float32 (feeds the dataset iterators)."""
        return np.asarray(self.execute(records), np.float32)

    # -- serde -------------------------------------------------------------

    def to_json(self) -> str:
        return json.dumps({
            "schema": self.initial_schema.to_dict(),
            "steps": [{"op": type(s).__name__, **dataclasses.asdict(s)}
                      for s in self.steps],
        }, indent=1)

    @staticmethod
    def from_json(text: str) -> "TransformProcess":
        d = json.loads(text)
        steps = []
        for sd in d["steps"]:
            cls = _OP_REGISTRY[sd.pop("op")]
            steps.append(cls(**sd))
        return TransformProcess(Schema.from_dict(d["schema"]), steps)


# --- join + group-by reduction (↔ org.datavec.api.transform.join.Join and
# org.datavec.api.transform.reduce.Reducer, executed by
# LocalTransformExecutor in the reference) ----------------------------------


def join(left_records, left_schema: Schema, right_records,
         right_schema: Schema, *, key: Union[str, Sequence[str]],
         join_type: str = "inner") -> Tuple[List[List], Schema]:
    """↔ Join: combine two record sets on key column(s).

    join_type: 'inner' | 'left' | 'right' | 'full'. Output columns: key(s),
    then left non-keys, then right non-keys; missing side fills None.
    Right-side duplicates multiply rows (relational semantics, like the
    reference's Spark/local join executors).
    """
    keys = [key] if isinstance(key, str) else list(key)
    if join_type not in ("inner", "left", "right", "full"):
        raise ValueError(f"unknown join_type {join_type!r}")
    li = [left_schema.index_of(k) for k in keys]
    ri = [right_schema.index_of(k) for k in keys]
    l_rest = [i for i in range(len(left_schema.columns)) if i not in li]
    r_rest = [i for i in range(len(right_schema.columns)) if i not in ri]

    out_schema = Schema()
    for k, i in zip(keys, li):
        out_schema.columns.append(dataclasses.replace(left_schema.columns[i]))
    for i in l_rest:
        out_schema.columns.append(dataclasses.replace(left_schema.columns[i]))
    taken = set(out_schema.names())
    for i in r_rest:
        col = dataclasses.replace(right_schema.columns[i])
        if col.name in taken:
            # Both sides carry a non-key column of this name: disambiguate
            # (silently shadowing would make index_of always hit the left),
            # re-suffixing until unique.
            base, n = f"right_{col.name}", 2
            name = base
            while name in taken:
                name = f"{base}_{n}"
                n += 1
            col = dataclasses.replace(col, name=name)
        taken.add(col.name)
        out_schema.columns.append(col)

    rindex: Dict[tuple, List] = {}
    for r in right_records:
        rindex.setdefault(tuple(r[i] for i in ri), []).append(r)

    out: List[List] = []
    matched_right = set()
    for l in left_records:
        k = tuple(l[i] for i in li)
        matches = rindex.get(k, [])
        if matches:
            matched_right.add(k)
            for r in matches:
                out.append(list(k) + [l[i] for i in l_rest]
                           + [r[i] for i in r_rest])
        elif join_type in ("left", "full"):
            out.append(list(k) + [l[i] for i in l_rest]
                       + [None] * len(r_rest))
    if join_type in ("right", "full"):
        for k, rows in rindex.items():
            if k in matched_right:
                continue
            for r in rows:
                out.append(list(k) + [None] * len(l_rest)
                           + [r[i] for i in r_rest])
    return out, out_schema


_REDUCE_OPS = {
    "sum": lambda vs: float(np.sum(vs)),
    "mean": lambda vs: float(np.mean(vs)),
    "min": lambda vs: float(np.min(vs)),
    "max": lambda vs: float(np.max(vs)),
    "stdev": lambda vs: float(np.std(vs, ddof=1)) if len(vs) > 1 else 0.0,
    "count": len,
    "first": lambda vs: vs[0],
    "last": lambda vs: vs[-1],
}


def reduce_by_key(records, schema: Schema, *, key: Union[str, Sequence[str]],
                  ops: Dict[str, str]) -> Tuple[List[List], Schema]:
    """↔ Reducer: group rows by key column(s), aggregate the named columns.

    ``ops`` maps column name → one of sum/mean/min/max/stdev/count/first/
    last. Output columns: key(s) then aggregates in ``ops`` order, named
    '<op>(<column>)' like the reference's reduced-column naming.
    """
    keys = [key] if isinstance(key, str) else list(key)
    ki = [schema.index_of(k) for k in keys]
    numeric_ops = ("sum", "mean", "min", "max", "stdev")
    col_idx = {}
    for col, op in ops.items():
        col_idx[col] = schema.index_of(col)  # validates existence
        if op not in _REDUCE_OPS:
            raise ValueError(
                f"unknown reduce op {op!r}; have {sorted(_REDUCE_OPS)}")
        if op in numeric_ops and schema.column(col).type not in (
                "integer", "double", "long"):
            raise ValueError(
                f"reduce op {op!r} needs a numeric column; "
                f"{col!r} is {schema.column(col).type!r}")

    groups: Dict[tuple, List[List]] = {}  # insertion-ordered
    for r in records:
        groups.setdefault(tuple(r[i] for i in ki), []).append(r)

    out_schema = Schema()
    for k, i in zip(keys, ki):
        out_schema.columns.append(dataclasses.replace(schema.columns[i]))
    for col, op in ops.items():
        name = f"{op}({col})"
        if op == "count":
            out_schema.add_integer_column(name)
        elif op in ("first", "last"):
            out_schema.columns.append(
                dataclasses.replace(schema.column(col), name=name))
        else:
            out_schema.add_double_column(name)

    out = []
    for k, rows in groups.items():
        rec = list(k)
        for col, op in ops.items():
            ci = col_idx[col]
            vals = [r[ci] for r in rows]
            if op == "count":
                # None = missing (outer-join unmatched side): not counted.
                vals = [v for v in vals if v is not None]
            elif op not in ("first", "last"):
                # Numeric aggregates exclude missing values (the reference
                # Reducer's null handling); an all-missing group -> None.
                vals = [float(v) for v in vals if v is not None]
                if not vals:
                    rec.append(None)
                    continue
            rec.append(_REDUCE_OPS[op](vals))
        out.append(rec)
    return out, out_schema


def convert_to_sequence(records, schema: Schema, *,
                        key: Union[str, Sequence[str]],
                        order_by: Optional[str] = None,
                        numeric_order: bool = True,
                        ascending: bool = True):
    """↔ TransformProcess.convertToSequence(keyCols, comparator): group a
    flat record stream by key column(s) into SEQUENCE records, ordered
    within each group by ``order_by`` (numeric or lexicographic).

    Returns (sequences, keys, out_schema): ``sequences`` is a list of
    sequence records (each a list of records, key columns REMOVED — they
    are the sequence's identity; ``keys`` carries them in the same
    order); ``out_schema`` describes the per-step columns after key
    removal (reduce_by_key's convention — downstream label_index math
    needs it). Feed the result to a CollectionSequenceRecordReader →
    SequenceRecordReaderDataSetIterator for padded RNN batches.
    """
    keys = [key] if isinstance(key, str) else list(key)
    kidx = [schema.index_of(k) for k in keys]
    oidx = schema.index_of(order_by) if order_by is not None else None
    if (oidx is not None and numeric_order
            and schema.column(order_by).type == "string"):
        raise ValueError(
            f"order_by column {order_by!r} is a string column; pass "
            "numeric_order=False for lexicographic ordering")
    groups: Dict[tuple, list] = {}
    for rec in records:
        groups.setdefault(tuple(rec[i] for i in kidx), []).append(rec)
    drop = set(kidx)
    out_schema = Schema([dataclasses.replace(c)
                         for i, c in enumerate(schema.columns)
                         if i not in drop])
    out_seqs, out_keys = [], []
    for gk, rows in groups.items():  # dicts preserve insertion order
        if oidx is not None:
            try:
                sort_key = ((lambda r: float(r[oidx])) if numeric_order
                            else (lambda r: str(r[oidx])))
                rows = sorted(rows, key=sort_key, reverse=not ascending)
            except ValueError as e:
                raise ValueError(
                    f"order_by column {order_by!r} has non-numeric "
                    f"values; pass numeric_order=False ({e})") from None
        out_seqs.append([[v for i, v in enumerate(r) if i not in drop]
                         for r in rows])
        out_keys.append(gk if len(gk) > 1 else gk[0])
    return out_seqs, out_keys, out_schema


def sliding_windows(sequences, *, size: int, step: Optional[int] = None,
                    drop_last: bool = True):
    """↔ the reference's time-window functions (OverlappingTimeWindow in
    spirit, index-based): split each sequence record into windows of
    ``size`` steps advancing by ``step`` (default: non-overlapping).
    ``drop_last=False`` keeps a shorter tail window."""
    if size < 1:
        raise ValueError(f"size must be >= 1, got {size}")
    step = size if step is None else step
    if step < 1:
        raise ValueError(f"step must be >= 1, got {step}")
    out = []
    for seq in sequences:
        i = 0
        while i < len(seq):
            win = seq[i:i + size]
            if len(win) == size:
                out.append(win)
                i += step
            else:  # tail shorter than size: keep at most one, if asked
                if not drop_last:
                    out.append(win)
                break
    return out
