"""Image input pipeline (↔ DataVec image, SURVEY §2.4 / §2.8 item 12).

ref: org.datavec.image.recordreader.ImageRecordReader +
org.datavec.image.loader.NativeImageLoader (JavaCPP OpenCV) +
org.datavec.image.transform.* (crop/flip/rotate/scale, PipelineImageTransform)
and org.datavec.api.io.labels.ParentPathLabelGenerator.

Decode runs host-side on native OpenCV when available (cv2 — the same
library the reference binds via JavaCPP) with a PIL fallback; augmentation
is pure numpy. The output is NHWC float32, the TPU-friendly layout (↔ the
reference's NCHW default; conv layers here are NHWC natively). Device
transfer/overlap is the AsyncDataSetIterator's job (data/iterators.py), so
ImageRecordReader stays a pure host producer — the role split the reference
uses (RecordReader produces, AsyncDataSetIterator prefetches).
"""

from __future__ import annotations

import pathlib
from typing import Callable, List, Optional, Sequence, Tuple, Union

import numpy as np

try:
    import cv2

    _HAS_CV2 = True
except Exception:  # pragma: no cover
    cv2 = None
    _HAS_CV2 = False

from deeplearning4j_tpu.data.dataset import DataSet

IMAGE_EXTENSIONS = {".png", ".jpg", ".jpeg", ".bmp", ".gif", ".ppm", ".webp"}


# --- label generators (↔ org.datavec.api.io.labels.*) ----------------------


class ParentPathLabelGenerator:
    """Label = name of the file's parent directory."""

    def __call__(self, path: pathlib.Path) -> str:
        return path.parent.name


class PatternPathLabelGenerator:
    """Label = path-stem split by `pattern`, taking `index`
    (↔ PatternPathLabelGenerator)."""

    def __init__(self, pattern: str = "_", index: int = 0):
        self.pattern = pattern
        self.index = index

    def __call__(self, path: pathlib.Path) -> str:
        return path.stem.split(self.pattern)[self.index]


# --- decode ----------------------------------------------------------------


def load_image(path, *, height: int, width: int, channels: int = 3) -> np.ndarray:
    """Decode + resize one image to [H, W, C] float32 in [0, 255]
    (↔ NativeImageLoader.asMatrix; normalization is the normalizer's job)."""
    path = str(path)
    if _HAS_CV2:
        flag = cv2.IMREAD_COLOR if channels == 3 else cv2.IMREAD_GRAYSCALE
        img = cv2.imread(path, flag)
        if img is None:
            raise IOError(f"cannot decode image {path}")
        img = cv2.resize(img, (width, height), interpolation=cv2.INTER_AREA)
        if channels == 3:
            img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
        else:
            img = img[..., None]
    else:  # pragma: no cover - PIL fallback
        from PIL import Image

        with Image.open(path) as im:
            im = im.convert("RGB" if channels == 3 else "L")
            im = im.resize((width, height))
            img = np.asarray(im)
            if channels == 1:
                img = img[..., None]
    return img.astype(np.float32)


# --- transforms (↔ org.datavec.image.transform.*) --------------------------


class ImageTransform:
    def __call__(self, img: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        raise NotImplementedError


class FlipImageTransform(ImageTransform):
    """↔ FlipImageTransform: horizontal (axis=1) or vertical (axis=0)."""

    def __init__(self, axis: int = 1, probability: float = 0.5):
        self.axis = axis
        self.probability = probability

    def __call__(self, img, rng):
        if rng.random() < self.probability:
            return np.flip(img, axis=self.axis)
        return img


class RotateImageTransform(ImageTransform):
    """↔ RotateImageTransform: rotation by a random angle in ±max_deg."""

    def __init__(self, max_deg: float = 15.0):
        self.max_deg = max_deg

    def __call__(self, img, rng):
        angle = float(rng.uniform(-self.max_deg, self.max_deg))
        if not _HAS_CV2:  # pragma: no cover - 90°-step fallback
            k = int(round(angle / 90.0)) % 4
            return np.rot90(img, k).copy() if k else img
        h, w = img.shape[:2]
        m = cv2.getRotationMatrix2D((w / 2, h / 2), angle, 1.0)
        out = cv2.warpAffine(img, m, (w, h), flags=cv2.INTER_LINEAR,
                             borderMode=cv2.BORDER_REFLECT)
        return out[..., None] if img.ndim == 3 and img.shape[2] == 1 else out


class CropImageTransform(ImageTransform):
    """↔ CropImageTransform: random crop by up to `margin` px per side,
    resized back to the original size."""

    def __init__(self, margin: int = 4):
        self.margin = margin

    def __call__(self, img, rng):
        h, w = img.shape[:2]
        t, b = rng.integers(0, self.margin + 1, 2)
        l, r = rng.integers(0, self.margin + 1, 2)
        cropped = img[t:h - b or h, l:w - r or w]
        if _HAS_CV2:
            out = cv2.resize(cropped, (w, h), interpolation=cv2.INTER_LINEAR)
            return out[..., None] if img.ndim == 3 and img.shape[2] == 1 else out
        pad_h, pad_w = h - cropped.shape[0], w - cropped.shape[1]
        return np.pad(cropped, ((0, pad_h), (0, pad_w), (0, 0)), mode="edge")


class ScaleImageTransform(ImageTransform):
    """Multiply pixel values by a random factor in [1-delta, 1+delta]
    (brightness jitter; ↔ ScaleImageTransform's spirit)."""

    def __init__(self, delta: float = 0.2):
        self.delta = delta

    def __call__(self, img, rng):
        return img * float(rng.uniform(1 - self.delta, 1 + self.delta))


class PipelineImageTransform(ImageTransform):
    """↔ PipelineImageTransform: sequence of (transform, probability)."""

    def __init__(self, steps: Sequence, shuffle: bool = False):
        self.steps = [s if isinstance(s, tuple) else (s, 1.0) for s in steps]
        self.shuffle = shuffle

    def __call__(self, img, rng):
        steps = list(self.steps)
        if self.shuffle:
            rng.shuffle(steps)
        for t, p in steps:
            if rng.random() < p:
                img = t(img, rng)
        return img


# --- reader + iterator -----------------------------------------------------


class ImageRecordReader:
    """↔ org.datavec.image.recordreader.ImageRecordReader.

    Walks `root` (or an explicit file list), decodes to [H,W,C] float32 and
    yields (image, label_string) pairs. Labels come from `label_generator`
    (default: parent directory name).
    """

    def __init__(self, height: int, width: int, channels: int = 3,
                 label_generator: Optional[Callable] = None):
        self.height = height
        self.width = width
        self.channels = channels
        self.label_generator = label_generator or ParentPathLabelGenerator()
        self.paths: List[pathlib.Path] = []
        self.labels: List[str] = []

    def initialize(self, source: Union[str, pathlib.Path, Sequence]) -> "ImageRecordReader":
        if isinstance(source, (str, pathlib.Path)):
            root = pathlib.Path(source)
            self.paths = sorted(
                p for p in root.rglob("*")
                if p.is_file() and p.suffix.lower() in IMAGE_EXTENSIONS)
        else:
            self.paths = [pathlib.Path(p) for p in source]
        self.labels = sorted({self.label_generator(p) for p in self.paths})
        return self

    def num_labels(self) -> int:
        return len(self.labels)

    def read_index(self, i: int):
        """Decode entry i → (image [H,W,C] float32, label string). The one
        decode path, shared by __iter__ and the batch iterator."""
        p = self.paths[i]
        img = load_image(p, height=self.height, width=self.width,
                         channels=self.channels)
        return img, self.label_generator(p)

    def __iter__(self):
        for i in range(len(self.paths)):
            yield self.read_index(i)

    def reset(self):
        pass


class ImageDataSetIterator:
    """Minibatch iterator over an ImageRecordReader: NHWC float32 features +
    one-hot labels (↔ RecordReaderDataSetIterator specialized for images).

    `transform` (ImageTransform) is applied per image with the iterator's
    rng; `shuffle` reshuffles file order each epoch.
    """

    def __init__(self, reader: ImageRecordReader, batch_size: int, *,
                 transform: Optional[ImageTransform] = None,
                 shuffle: bool = True, seed: int = 0,
                 normalizer: Optional[Callable] = None,
                 num_workers: int = 0):
        self.reader = reader
        self.batch_size = batch_size
        self.transform = transform
        self.shuffle = shuffle
        self.normalizer = normalizer
        # num_workers > 0: decode a batch's images on a thread pool —
        # cv2/PIL release the GIL during JPEG decode, so workers scale on
        # cores. This is the host-decode-throughput lever SURVEY §7.4
        # names as the usual pod-scale input bottleneck (the reference's
        # NativeImageLoader got the same effect from native decode +
        # async prefetch); wrap with AsyncDataSetIterator to also overlap
        # whole batches with device compute.
        self.num_workers = int(num_workers)
        self._rng = np.random.default_rng(seed)
        self._label_to_idx = {l: i for i, l in enumerate(reader.labels)}

    def __len__(self):
        return -(-len(self.reader.paths) // self.batch_size)

    def _decoded(self, order):
        """Yield (img, label) in `order` — sequentially, or decoded ahead
        by a worker pool with bounded lookahead (order preserved)."""
        if self.num_workers <= 0:
            for i in order:
                yield self.reader.read_index(int(i))
            return
        import concurrent.futures as cf
        from collections import deque

        pool = cf.ThreadPoolExecutor(self.num_workers)
        try:
            pending = deque()
            lookahead = max(2 * self.num_workers, self.batch_size)
            it = iter(order)
            for i in it:
                pending.append(pool.submit(self.reader.read_index, int(i)))
                if len(pending) >= lookahead:
                    break
            for i in it:
                yield pending.popleft().result()
                pending.append(pool.submit(self.reader.read_index, int(i)))
            while pending:
                yield pending.popleft().result()
        finally:
            # early abandonment (break/exception upstream) must not stall
            # on queued decodes
            pool.shutdown(wait=False, cancel_futures=True)

    def __iter__(self):
        order = np.arange(len(self.reader.paths))
        if self.shuffle:
            self._rng.shuffle(order)
        batch_x, batch_y = [], []
        for img, label in self._decoded(order):
            if self.transform is not None:
                img = self.transform(img, self._rng)
            batch_x.append(img)
            batch_y.append(self._label_to_idx[label])
            if len(batch_x) == self.batch_size:
                yield self._emit(batch_x, batch_y)
                batch_x, batch_y = [], []
        if batch_x:
            yield self._emit(batch_x, batch_y)

    def _emit(self, xs, ys):
        x = np.stack(xs).astype(np.float32)
        if self.normalizer is not None:
            x = self.normalizer(x)
        y = np.zeros((len(ys), self.reader.num_labels()), np.float32)
        y[np.arange(len(ys)), ys] = 1.0
        return DataSet(x, y)

    def reset(self):
        pass
