"""Audio data domain (↔ datavec-audio: WavFileRecordReader +
AudioRecordReader with MFCC/spectrogram feature extraction via
musicg/jlibrosa in the reference; SURVEY §2.4 "other data domains").

TPU-first: WAV parsing is stdlib (``wave``) + numpy; feature extraction
(STFT power spectrogram, mel filterbank, MFCC) is pure numpy/jnp-free
host-side code producing dense [frames, coeffs] arrays ready for the
dataset bridge — the heavy math (the model) runs on device, the feature
extractor is IO-bound and stays on host like every other reader.
"""

from __future__ import annotations

import pathlib
import wave
from typing import List, Optional, Sequence, Union

import numpy as np

from deeplearning4j_tpu.data.records import RecordReader


def read_wav(path) -> tuple:
    """(samples float32 in [-1,1] shaped [n] (mono-mixed), sample_rate)."""
    with wave.open(str(path), "rb") as w:
        rate = w.getframerate()
        n = w.getnframes()
        width = w.getsampwidth()
        channels = w.getnchannels()
        raw = w.readframes(n)
    if width == 2:
        x = np.frombuffer(raw, "<i2").astype(np.float32) / 32768.0
    elif width == 1:
        x = (np.frombuffer(raw, "u1").astype(np.float32) - 128.0) / 128.0
    elif width == 4:
        x = np.frombuffer(raw, "<i4").astype(np.float32) / 2147483648.0
    else:
        raise ValueError(f"unsupported WAV sample width {width}")
    if channels > 1:
        x = x.reshape(-1, channels).mean(axis=1)
    return x, rate


def _frame(x: np.ndarray, frame_length: int, hop: int) -> np.ndarray:
    if len(x) < frame_length:  # short clip: zero-pad to one full frame
        x = np.pad(x, (0, frame_length - len(x)))
    n = 1 + (len(x) - frame_length) // hop
    idx = np.arange(frame_length)[None, :] + hop * np.arange(n)[:, None]
    return x[idx]


def spectrogram(x: np.ndarray, *, frame_length: int = 400, hop: int = 160,
                window: str = "hann") -> np.ndarray:
    """Power spectrogram [frames, frame_length//2 + 1]."""
    frames = _frame(np.asarray(x, np.float32), frame_length, hop)
    if window == "hann":
        frames = frames * np.hanning(frame_length).astype(np.float32)
    spec = np.abs(np.fft.rfft(frames, axis=-1)) ** 2
    return spec.astype(np.float32)


def mel_filterbank(num_filters: int, frame_length: int, sample_rate: int,
                   fmin: float = 0.0, fmax: Optional[float] = None) -> np.ndarray:
    """[num_filters, frame_length//2+1] triangular mel filters (HTK mel)."""
    fmax = fmax or sample_rate / 2

    def hz_to_mel(f):
        return 2595.0 * np.log10(1.0 + np.asarray(f) / 700.0)

    def mel_to_hz(m):
        return 700.0 * (10.0 ** (np.asarray(m) / 2595.0) - 1.0)

    n_bins = frame_length // 2 + 1
    mel_pts = np.linspace(hz_to_mel(fmin), hz_to_mel(fmax), num_filters + 2)
    hz_pts = mel_to_hz(mel_pts)
    bins = np.floor((frame_length + 1) * hz_pts / sample_rate).astype(int)
    fb = np.zeros((num_filters, n_bins), np.float32)
    for i in range(num_filters):
        lo, mid, hi = bins[i], bins[i + 1], bins[i + 2]
        for b in range(lo, mid):
            if mid > lo:
                fb[i, b] = (b - lo) / (mid - lo)
        for b in range(mid, hi):
            if hi > mid:
                fb[i, b] = (hi - b) / (hi - mid)
    return fb


def mfcc(x: np.ndarray, sample_rate: int, *, num_coeffs: int = 13,
         num_filters: int = 26, frame_length: int = 400,
         hop: int = 160) -> np.ndarray:
    """[frames, num_coeffs] mel-frequency cepstral coefficients (log-mel →
    type-II DCT), the reference's AudioRecordReader feature set."""
    spec = spectrogram(x, frame_length=frame_length, hop=hop)
    fb = mel_filterbank(num_filters, frame_length, sample_rate)
    logmel = np.log(np.maximum(spec @ fb.T, 1e-10))
    n = num_filters
    dct = np.cos(np.pi * np.arange(num_coeffs)[:, None]
                 * (np.arange(n) + 0.5)[None, :] / n)
    return (logmel @ dct.T).astype(np.float32)


class WavFileRecordReader(RecordReader):
    """↔ WavFileRecordReader: one record per file = [feature_array, label?].

    features: 'waveform' | 'spectrogram' | 'mfcc'. ``label_fn(path)`` maps a
    file to its label (↔ ParentPathLabelGenerator-style usage).
    """

    def __init__(self, paths: Union[str, Sequence], *,
                 features: str = "mfcc", label_fn=None, **feature_kw):
        if features not in ("waveform", "spectrogram", "mfcc"):
            raise ValueError(f"unknown feature kind {features!r}")
        if isinstance(paths, (str, pathlib.Path)):
            p = pathlib.Path(paths)
            paths = sorted(p.glob("**/*.wav")) if p.is_dir() else [p]
        self.paths = [pathlib.Path(p) for p in paths]
        self.features = features
        self.label_fn = label_fn
        self.feature_kw = feature_kw

    def __iter__(self):
        for p in self.paths:
            x, rate = read_wav(p)
            if self.features == "waveform":
                feats = x
            elif self.features == "spectrogram":
                feats = spectrogram(x, **self.feature_kw)
            else:
                feats = mfcc(x, rate, **self.feature_kw)
            rec: List = [feats]
            if self.label_fn is not None:
                rec.append(self.label_fn(p))
            yield rec


class FrameSequenceRecordReader(RecordReader):
    """↔ datavec-data-codec's VideoRecordReader role: a video is a directory
    of frame images (the codec-decode step happens offline — this
    environment ships no codec libs, and the reference's JCodec path existed
    to produce exactly these frame sequences). One record per video:
    [frames array [T, H, W, C], label?].
    """

    def __init__(self, root, *, height: int, width: int, channels: int = 3,
                 max_frames: Optional[int] = None, label_fn=None):
        from deeplearning4j_tpu.data.image import load_image

        self._load = load_image
        self.root = pathlib.Path(root)
        self.height, self.width, self.channels = height, width, channels
        self.max_frames = max_frames
        self.label_fn = label_fn
        exts = (".png", ".jpg", ".jpeg", ".bmp", ".npy")
        self.videos = sorted(
            d for d in self.root.iterdir()
            if d.is_dir() and any(p.suffix.lower() in exts
                                  for p in d.iterdir()))

    def __iter__(self):
        for vid in self.videos:
            frames = sorted(p for p in vid.iterdir()
                            if p.suffix.lower() in
                            (".png", ".jpg", ".jpeg", ".bmp", ".npy"))
            if self.max_frames:
                frames = frames[:self.max_frames]
            arrs = []
            for f in frames:
                if f.suffix.lower() == ".npy":
                    a = np.load(f).astype(np.float32)
                else:
                    a = self._load(f, height=self.height, width=self.width,
                                   channels=self.channels)
                arrs.append(a)
            rec: List = [np.stack(arrs)]
            if self.label_fn is not None:
                rec.append(self.label_fn(vid))
            yield rec
