"""Data/ETL layer (↔ DataVec + the deeplearning4j dataset iterators).

- records: RecordReader API (CSV/line/collection/sequence) + DataSet bridge
- transform: Schema + TransformProcess column-op pipeline
- image: ImageRecordReader, augmentation transforms, label generators
- iterators: minibatch + async-prefetch (device double-buffering)
- normalizers: fit/transform feature scalers
"""

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.data.iterators import (
    ArrayDataSetIterator,
    AsyncDataSetIterator,
    ShardedDataSetIterator,
    ShrinkPolicy,
    TransformIterator,
    derive_shard,
    maybe_auto_prefetch,
)
# transient-IO retry wrapper (lives in resilience/, re-exported here so
# data pipelines compose it like any other iterator wrapper)
from deeplearning4j_tpu.resilience.retry import RetryingIterator, retrying
from deeplearning4j_tpu.data.audio import (
    WavFileRecordReader,
    mel_filterbank,
    mfcc,
    read_wav,
    spectrogram,
)
from deeplearning4j_tpu.data.columnar import (
    ColumnarRecordReader,
    SQLRecordReader,
)
from deeplearning4j_tpu.data.datasets import (
    load_cifar10,
    load_cifar100,
    load_emnist,
    load_iris,
    load_tiny_imagenet,
)
from deeplearning4j_tpu.data.mnist import load_mnist
from deeplearning4j_tpu.data.normalizers import (
    ImageMeanSubtraction,
    ImagePreProcessingScaler,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
)
from deeplearning4j_tpu.data.excel import ExcelRecordReader, write_xlsx
from deeplearning4j_tpu.data.records import (
    CollectionRecordReader,
    CSVRecordReader,
    CSVSequenceRecordReader,
    JsonLineRecordReader,
    LineRecordReader,
    RecordReader,
    RecordReaderDataSetIterator,
    RecordReaderMultiDataSetIterator,
    CollectionSequenceRecordReader,
    SequenceRecordReaderDataSetIterator,
    RegexLineRecordReader,
    SequenceRecordReader,
    SVMLightRecordReader,
)
from deeplearning4j_tpu.data.transform import (
    Schema,
    TransformProcess,
    convert_to_sequence,
    sliding_windows,
)
from deeplearning4j_tpu.data.arrow import ArrowRecordReader, read_arrow_file
from deeplearning4j_tpu.data.geo import (
    CoordinatesDistanceTransform,
    GeoJsonPointReader,
    haversine_m,
)
from deeplearning4j_tpu.data.image import (
    ImageDataSetIterator,
    ImageRecordReader,
    ParentPathLabelGenerator,
    PatternPathLabelGenerator,
    PipelineImageTransform,
)

__all__ = [
    "DataSet", "MultiDataSet",
    "ArrayDataSetIterator", "AsyncDataSetIterator", "TransformIterator",
    "ShardedDataSetIterator",
    "load_mnist", "load_cifar10", "load_cifar100", "load_emnist",
    "load_iris", "load_tiny_imagenet",
    "WavFileRecordReader", "read_wav", "spectrogram", "mfcc",
    "mel_filterbank",
    "ColumnarRecordReader", "SQLRecordReader",
    "ExcelRecordReader", "write_xlsx",
    "ImageMeanSubtraction", "ImagePreProcessingScaler",
    "NormalizerMinMaxScaler", "NormalizerStandardize",
    "RecordReader", "CollectionRecordReader", "CSVRecordReader",
    "LineRecordReader", "SequenceRecordReader", "CSVSequenceRecordReader",
    "RecordReaderDataSetIterator", "RecordReaderMultiDataSetIterator",
    "SequenceRecordReaderDataSetIterator", "CollectionSequenceRecordReader", "RegexLineRecordReader",
    "JsonLineRecordReader", "SVMLightRecordReader",
    "Schema", "TransformProcess", "convert_to_sequence", "sliding_windows",
    "ArrowRecordReader", "read_arrow_file",
    "CoordinatesDistanceTransform", "GeoJsonPointReader", "haversine_m",
    "ImageRecordReader", "ImageDataSetIterator",
    "ParentPathLabelGenerator", "PatternPathLabelGenerator",
    "PipelineImageTransform",
]
