"""Data/ETL layer (↔ DataVec + the deeplearning4j dataset iterators)."""

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.data.iterators import (
    ArrayDataSetIterator,
    AsyncDataSetIterator,
    TransformIterator,
)
from deeplearning4j_tpu.data.mnist import load_mnist
from deeplearning4j_tpu.data.normalizers import (
    ImageMeanSubtraction,
    ImagePreProcessingScaler,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
)

__all__ = [
    "DataSet", "MultiDataSet",
    "ArrayDataSetIterator", "AsyncDataSetIterator", "TransformIterator",
    "load_mnist",
    "ImageMeanSubtraction", "ImagePreProcessingScaler",
    "NormalizerMinMaxScaler", "NormalizerStandardize",
]
