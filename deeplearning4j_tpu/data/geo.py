"""Geo readers + coordinate transforms (↔ datavec-geo).

ref: org.datavec.api.transform.transform.geo.{CoordinatesDistanceTransform,
IPAddressToCoordinatesTransform, LocationToCoordinatesTransform} and the
datavec-geo module. The MaxMind GeoIP lookup needs an external licensed
database — absent here, ``IPAddressToCoordinatesTransform`` raises with
instructions — while the coordinate math and point readers are full
implementations:

- ``GeoJsonPointReader``: dependency-free GeoJSON ``FeatureCollection``
  reader yielding [lon, lat, *properties] records for the transform engine.
- ``CoordinatesDistanceTransform``: derived-column transform computing the
  distance between two coordinate columns (reference semantics: coordinates
  serialized as delimited strings, euclidean by default; haversine meters
  supported for lat/lon).
- ``haversine_m`` / ``parse_point``: the underlying math, exposed.

Transforms plug into data/transform.py's TransformProcess (same
apply/out_schema protocol, registered for JSON round-trip).
"""

from __future__ import annotations

import dataclasses
import json
import math
from pathlib import Path
from typing import Any, List, Optional

from deeplearning4j_tpu.data.transform import Column, Schema, _register

_EARTH_RADIUS_M = 6_371_008.8  # IUGG mean radius


def parse_point(value: Any, delimiter: str = ":") -> List[float]:
    """Parse a delimited coordinate string ('lat:lon' or 'x:y:z' …) into
    floats; passes through list/tuple input."""
    if isinstance(value, (list, tuple)):
        return [float(v) for v in value]
    return [float(p) for p in str(value).split(delimiter)]


def haversine_m(lat1: float, lon1: float, lat2: float, lon2: float) -> float:
    """Great-circle distance in meters between two (lat, lon) points."""
    p1, p2 = math.radians(lat1), math.radians(lat2)
    dp = math.radians(lat2 - lat1)
    dl = math.radians(lon2 - lon1)
    a = (math.sin(dp / 2) ** 2
         + math.cos(p1) * math.cos(p2) * math.sin(dl / 2) ** 2)
    return 2 * _EARTH_RADIUS_M * math.asin(math.sqrt(a))


@_register
@dataclasses.dataclass
class CoordinatesDistanceTransform:
    """↔ CoordinatesDistanceTransform: new column = distance between two
    delimited-coordinate columns.

    ``metric``: 'euclidean' (reference default, any dimensionality) or
    'haversine' (2-D lat:lon, meters).
    """

    new_name: str
    first_column: str
    second_column: str
    delimiter: str = ":"
    metric: str = "euclidean"

    def out_schema(self, s: Schema) -> Schema:
        out = s.copy()
        out.columns.append(Column(self.new_name, "double"))
        return out

    def apply(self, records, s: Schema):
        i = s.index_of(self.first_column)
        j = s.index_of(self.second_column)
        out = []
        for r in records:
            a = parse_point(r[i], self.delimiter)
            b = parse_point(r[j], self.delimiter)
            if self.metric == "haversine":
                d = haversine_m(a[0], a[1], b[0], b[1])
            elif self.metric == "euclidean":
                d = math.sqrt(sum((x - y) ** 2 for x, y in zip(a, b)))
            else:
                raise ValueError(f"unknown metric {self.metric!r}")
            out.append(list(r) + [d])
        return out


@_register
@dataclasses.dataclass
class IPAddressToCoordinatesTransform:
    """↔ IPAddressToCoordinatesTransform (MaxMind GeoIP2). The GeoLite2
    database is licensed/external and not present in this environment; the
    transform exists for API parity and raises with setup instructions."""

    column: str
    delimiter: str = ":"

    def out_schema(self, s: Schema) -> Schema:
        return s.copy()

    def apply(self, records, s: Schema):
        raise RuntimeError(
            "IPAddressToCoordinatesTransform needs a MaxMind GeoLite2 "
            "database (geoip2 reader + .mmdb file); neither ships in this "
            "environment. Provide a custom transform wrapping your geo "
            "database, or resolve IPs offline before ingest.")


class GeoJsonPointReader:
    """Read Point features from a GeoJSON FeatureCollection.

    Records are [lon, lat, *property values] (GeoJSON's native coordinate
    order); ``schema()`` describes the columns so TransformProcess can take
    over. Non-point geometries are skipped unless ``strict``.
    """

    def __init__(self, property_names: Optional[List[str]] = None,
                 strict: bool = False):
        self.property_names = property_names
        self.strict = strict
        self._rows: List[List[Any]] = []
        self._props: List[str] = []
        self._i = 0

    def initialize(self, path):
        doc = json.loads(Path(path).read_text())
        if doc.get("type") != "FeatureCollection":
            raise ValueError(f"{path}: not a GeoJSON FeatureCollection")
        feats = doc.get("features", [])
        if self.property_names is not None:
            self._props = list(self.property_names)
        else:
            keys: List[str] = []
            for f in feats:
                for k in (f.get("properties") or {}):
                    if k not in keys:
                        keys.append(k)
            self._props = keys
        self._rows = []
        for f in feats:
            geom = f.get("geometry") or {}
            if geom.get("type") != "Point":
                if self.strict:
                    raise ValueError(
                        f"non-Point geometry {geom.get('type')!r} in {path}")
                continue
            lon, lat = geom["coordinates"][:2]
            props = f.get("properties") or {}
            self._rows.append([float(lon), float(lat)]
                              + [props.get(k) for k in self._props])
        self._i = 0
        return self

    def schema(self) -> Schema:
        s = Schema().add_double_column("lon").add_double_column("lat")
        for k in self._props:
            s.add_string_column(k)
        return s

    def has_next(self) -> bool:
        return self._i < len(self._rows)

    def next(self) -> List[Any]:
        if not self.has_next():
            raise StopIteration
        r = self._rows[self._i]
        self._i += 1
        return r

    def reset(self):
        self._i = 0

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next()
