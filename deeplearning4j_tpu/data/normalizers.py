"""Data normalizers (↔ org.nd4j.linalg.dataset.api.preprocessor.*).

ref: NormalizerStandardize (fit mean/std, transform), NormalizerMinMaxScaler,
ImagePreProcessingScaler (pixel /255 range map), VGG16ImagePreProcessor
(mean subtraction). Same fit/transform/revert lifecycle; state is plain
numpy (host-side ETL), serializable to npz alongside checkpoints.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet


class NormalizerStandardize:
    """↔ NormalizerStandardize: per-feature z-score."""

    def __init__(self):
        self.mean = None
        self.std = None

    def fit(self, features: np.ndarray):
        axes = tuple(range(features.ndim - 1))
        self.mean = np.asarray(features).mean(axis=axes)
        self.std = np.asarray(features).std(axis=axes) + 1e-8
        return self

    def transform(self, ds: DataSet) -> DataSet:
        return DataSet((ds.features - self.mean) / self.std, ds.labels,
                       ds.features_mask, ds.labels_mask)

    def revert_features(self, features):
        return features * self.std + self.mean

    def save(self, path):
        np.savez(path, mean=self.mean, std=self.std)

    @classmethod
    def load(cls, path):
        z = np.load(path)
        n = cls()
        n.mean, n.std = z["mean"], z["std"]
        return n

    __call__ = transform


class NormalizerMinMaxScaler:
    """↔ NormalizerMinMaxScaler: map features into [lo, hi]."""

    def __init__(self, lo: float = 0.0, hi: float = 1.0):
        self.lo, self.hi = lo, hi
        self.fmin = None
        self.fmax = None

    def fit(self, features: np.ndarray):
        axes = tuple(range(features.ndim - 1))
        self.fmin = np.asarray(features).min(axis=axes)
        self.fmax = np.asarray(features).max(axis=axes)
        return self

    def transform(self, ds: DataSet) -> DataSet:
        scale = (self.hi - self.lo) / np.maximum(self.fmax - self.fmin, 1e-8)
        f = (ds.features - self.fmin) * scale + self.lo
        return DataSet(f, ds.labels, ds.features_mask, ds.labels_mask)

    __call__ = transform


class ImagePreProcessingScaler:
    """↔ ImagePreProcessingScaler: uint8 pixels → [lo, hi] (default [0,1])."""

    def __init__(self, lo: float = 0.0, hi: float = 1.0, max_pixel: float = 255.0):
        self.lo, self.hi, self.max_pixel = lo, hi, max_pixel

    def fit(self, features):
        return self

    def transform(self, ds: DataSet) -> DataSet:
        f = np.asarray(ds.features, np.float32) / self.max_pixel
        f = f * (self.hi - self.lo) + self.lo
        return DataSet(f, ds.labels, ds.features_mask, ds.labels_mask)

    __call__ = transform


class ImageMeanSubtraction:
    """↔ VGG16ImagePreProcessor: per-channel mean subtraction (and optional
    std division — covers ImageNet preprocessing)."""

    def __init__(self, mean, std=None):
        self.mean = np.asarray(mean, np.float32)
        self.std = None if std is None else np.asarray(std, np.float32)

    def fit(self, features):
        return self

    def transform(self, ds: DataSet) -> DataSet:
        f = np.asarray(ds.features, np.float32) - self.mean
        if self.std is not None:
            f = f / self.std
        return DataSet(f, ds.labels, ds.features_mask, ds.labels_mask)

    __call__ = transform
