"""Built-in dataset fetchers beyond MNIST (↔ deeplearning4j-datasets
fetchers/iterators: Cifar10Fetcher + Cifar10DataSetIterator,
EmnistDataSetIterator, IrisDataSetIterator, TinyImageNetFetcher;
SURVEY §2.5 Datasets row).

Same contract as data/mnist.py: the reference auto-downloads archives; this
environment has no network, so each loader searches standard on-disk
locations for the real files and otherwise falls back to a deterministic
SYNTHETIC stand-in with the dataset's exact shapes/classes and a learnable
structure (class template + noise), so convergence tests and benchmarks
exercise the real compute path either way. The third return value
``is_real`` says which you got.
"""

from __future__ import annotations

import os
import pickle
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.data.mnist import _read_idx

Split = Tuple[np.ndarray, np.ndarray]


def _search(names) -> Optional[Path]:
    roots = [
        "/root/data", "/root/datasets",
        os.path.expanduser("~/.cache"),
        os.path.expanduser("~/.deeplearning4j"),
    ]
    for root in roots:
        for name in names:
            p = Path(root) / name
            if p.exists():
                return p
    return None


def _synthetic_images(n_train, n_test, *, shape, num_classes, seed):
    """Class-template-plus-noise images in [0,255] uint8 (learnable: a
    small convnet separates the templates through the noise)."""
    rng = np.random.default_rng(seed)
    templates = rng.normal(0.0, 1.0, (num_classes,) + shape).astype(np.float32)

    def make(n, seed2):
        r = np.random.default_rng(seed2)
        y = r.integers(0, num_classes, n)
        x = templates[y] + 0.5 * r.normal(0.0, 1.0, (n,) + shape).astype(np.float32)
        x = (x - x.min()) / (x.max() - x.min())
        return (x * 255).astype(np.uint8), y.astype(np.int64)

    return make(n_train, seed + 1), make(n_test, seed + 2)


def _prep(x, y, *, num_classes, normalize, one_hot, image_shape):
    x = x.astype(np.float32)
    if normalize:
        x = x / 255.0
    x = x.reshape((x.shape[0],) + image_shape)
    if one_hot:
        oh = np.zeros((y.shape[0], num_classes), np.float32)
        oh[np.arange(y.shape[0]), y.astype(int)] = 1.0
        y = oh
    return x, y


# --- CIFAR -----------------------------------------------------------------


def _read_cifar10_batches(d: Path):
    xs, ys = [], []
    for name in [f"data_batch_{i}" for i in range(1, 6)]:
        with open(d / name, "rb") as f:
            b = pickle.load(f, encoding="bytes")
        xs.append(b[b"data"])
        ys.extend(b[b"labels"])
    xtr = np.concatenate(xs)
    with open(d / "test_batch", "rb") as f:
        b = pickle.load(f, encoding="bytes")
    return (xtr, np.array(ys)), (b[b"data"], np.array(b[b"labels"]))


def _cifar_to_nhwc(x):
    return x.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)


def load_cifar10(*, n_train: Optional[int] = None, n_test: Optional[int] = None,
                 normalize: bool = True, one_hot: bool = True
                 ) -> Tuple[Split, Split, bool]:
    """↔ Cifar10DataSetIterator. Images [N,32,32,3] float32, 10 classes."""
    d = _search(["cifar-10-batches-py", "cifar10/cifar-10-batches-py"])
    npz = _search(["cifar10.npz", "cifar10/cifar10.npz"])
    if d is not None and (d / "test_batch").exists():
        (xtr, ytr), (xte, yte) = _read_cifar10_batches(d)
        xtr, xte = _cifar_to_nhwc(xtr), _cifar_to_nhwc(xte)
        is_real = True
    elif npz is not None:
        with np.load(npz) as z:
            xtr, ytr, xte, yte = (z["x_train"], z["y_train"],
                                  z["x_test"], z["y_test"])
        is_real = True
    else:
        ((xtr, ytr), (xte, yte)) = _synthetic_images(
            n_train or 50000, n_test or 10000, shape=(32, 32, 3),
            num_classes=10, seed=11)
        is_real = False
    if n_train:
        xtr, ytr = xtr[:n_train], ytr[:n_train]
    if n_test:
        xte, yte = xte[:n_test], yte[:n_test]
    kw = dict(num_classes=10, normalize=normalize, one_hot=one_hot,
              image_shape=(32, 32, 3))
    return _prep(xtr, ytr, **kw), _prep(xte, yte, **kw), is_real


def load_cifar100(*, n_train: Optional[int] = None, n_test: Optional[int] = None,
                  normalize: bool = True, one_hot: bool = True
                  ) -> Tuple[Split, Split, bool]:
    """CIFAR-100 fine labels; [N,32,32,3], 100 classes."""
    d = _search(["cifar-100-python", "cifar100/cifar-100-python"])
    if d is not None and (d / "test").exists():
        def rd(name):
            with open(d / name, "rb") as f:
                b = pickle.load(f, encoding="bytes")
            return _cifar_to_nhwc(b[b"data"]), np.array(b[b"fine_labels"])

        xtr, ytr = rd("train")
        xte, yte = rd("test")
        is_real = True
    else:
        ((xtr, ytr), (xte, yte)) = _synthetic_images(
            n_train or 50000, n_test or 10000, shape=(32, 32, 3),
            num_classes=100, seed=13)
        is_real = False
    if n_train:
        xtr, ytr = xtr[:n_train], ytr[:n_train]
    if n_test:
        xte, yte = xte[:n_test], yte[:n_test]
    kw = dict(num_classes=100, normalize=normalize, one_hot=one_hot,
              image_shape=(32, 32, 3))
    return _prep(xtr, ytr, **kw), _prep(xte, yte, **kw), is_real


# --- EMNIST ----------------------------------------------------------------

EMNIST_CLASSES = {"byclass": 62, "bymerge": 47, "balanced": 47, "letters": 26,
                  "digits": 10, "mnist": 10}


def load_emnist(split: str = "balanced", *, n_train: Optional[int] = None,
                n_test: Optional[int] = None, normalize: bool = True,
                one_hot: bool = True) -> Tuple[Split, Split, bool]:
    """↔ EmnistDataSetIterator(Set.<SPLIT>). Images [N,28,28,1].

    Splits and class counts follow the reference enum
    (BYCLASS 62 / BYMERGE 47 / BALANCED 47 / LETTERS 26 / DIGITS 10 /
    MNIST 10). Letters labels are rebased to 0..25 like the reference.
    """
    if split not in EMNIST_CLASSES:
        raise ValueError(f"unknown EMNIST split {split!r}; "
                         f"have {sorted(EMNIST_CLASSES)}")
    classes = EMNIST_CLASSES[split]
    found = {}
    for kind, io in (("train", "images"), ("train", "labels"),
                     ("test", "images"), ("test", "labels")):
        dim = 3 if io == "images" else 1
        stem = f"emnist-{split}-{kind}-{io}-idx{dim}-ubyte"
        p = _search([f"emnist/{stem}", f"emnist/{stem}.gz", stem, f"{stem}.gz"])
        if p is not None:
            found[(kind, io)] = p
    if len(found) == 4:
        xtr = _read_idx(found[("train", "images")])
        ytr = _read_idx(found[("train", "labels")]).astype(np.int64)
        xte = _read_idx(found[("test", "images")])
        yte = _read_idx(found[("test", "labels")]).astype(np.int64)
        # EMNIST idx images are transposed relative to MNIST orientation
        xtr = xtr.transpose(0, 2, 1)
        xte = xte.transpose(0, 2, 1)
        if split == "letters":  # stored 1-indexed
            ytr, yte = ytr - 1, yte - 1
        is_real = True
    else:
        ((xtr, ytr), (xte, yte)) = _synthetic_images(
            n_train or 10000, n_test or 2000, shape=(28, 28),
            num_classes=classes, seed=17)
        is_real = False
    if n_train:
        xtr, ytr = xtr[:n_train], ytr[:n_train]
    if n_test:
        xte, yte = xte[:n_test], yte[:n_test]
    kw = dict(num_classes=classes, normalize=normalize, one_hot=one_hot,
              image_shape=(28, 28, 1))
    return _prep(xtr, ytr, **kw), _prep(xte, yte, **kw), is_real


# --- Iris ------------------------------------------------------------------


def load_iris(*, test_frac: float = 0.2, one_hot: bool = True, seed: int = 0
              ) -> Tuple[Split, Split, bool]:
    """↔ IrisDataSetIterator. Features [N,4] float32, 3 classes,
    stratified train/test split.

    Real data: an ``iris.csv``/``iris.data`` (sepal_l,sepal_w,petal_l,
    petal_w,label) in the search dirs. Fallback: a deterministic 150-sample
    stand-in drawn from per-class Gaussians with the published per-class
    feature means/stds of the real dataset — same separability character
    (setosa linearly separable, versicolor/virginica overlapping).
    """
    p = _search(["iris/iris.csv", "iris/iris.data", "iris.csv", "iris.data"])
    if p is not None:
        rows = []
        labels = []
        name_to_id = {}
        for line in p.read_text().strip().splitlines():
            parts = [s.strip() for s in line.replace(";", ",").split(",")]
            if len(parts) < 5 or not parts[0][:1].isdigit():
                continue  # header / blank / delimiter-only rows
            rows.append([float(v) for v in parts[:4]])
            lab = parts[4]
            if lab not in name_to_id:
                name_to_id[lab] = len(name_to_id)
            labels.append(name_to_id[lab])
        x = np.asarray(rows, np.float32)
        y = np.asarray(labels, np.int64)
        is_real = True
    else:
        # per-class N(mean, std) on the 4 features (published summary stats)
        means = np.array([[5.01, 3.43, 1.46, 0.25],
                          [5.94, 2.77, 4.26, 1.33],
                          [6.59, 2.97, 5.55, 2.03]], np.float32)
        stds = np.array([[0.35, 0.38, 0.17, 0.11],
                         [0.52, 0.31, 0.47, 0.20],
                         [0.64, 0.32, 0.55, 0.27]], np.float32)
        r = np.random.default_rng(seed + 42)
        x = np.concatenate([means[c] + stds[c] * r.normal(size=(50, 4))
                            for c in range(3)]).astype(np.float32)
        y = np.repeat(np.arange(3), 50).astype(np.int64)
        is_real = False

    # stratified shuffle/split
    r = np.random.default_rng(seed)
    tr_idx, te_idx = [], []
    for c in np.unique(y):
        idx = r.permutation(np.where(y == c)[0])
        k = max(1, int(len(idx) * test_frac))
        te_idx.extend(idx[:k])
        tr_idx.extend(idx[k:])
    tr_idx, te_idx = np.array(tr_idx), np.array(te_idx)

    def enc(yy):
        if not one_hot:
            return yy
        oh = np.zeros((yy.shape[0], 3), np.float32)
        oh[np.arange(yy.shape[0]), yy] = 1.0
        return oh

    return ((x[tr_idx], enc(y[tr_idx])), (x[te_idx], enc(y[te_idx])), is_real)


# --- TinyImageNet ----------------------------------------------------------


def load_tiny_imagenet(*, n_train: Optional[int] = None,
                       n_test: Optional[int] = None, normalize: bool = True,
                       one_hot: bool = True) -> Tuple[Split, Split, bool]:
    """↔ TinyImageNetDataSetIterator. Images [N,64,64,3], 200 classes.
    Real-data path expects a pre-packed ``tiny-imagenet.npz``; the raw
    per-file archive layout is served by data/image.py's directory reader."""
    npz = _search(["tiny-imagenet.npz", "tiny-imagenet-200/tiny-imagenet.npz"])
    if npz is not None:
        with np.load(npz) as z:
            xtr, ytr, xte, yte = (z["x_train"], z["y_train"],
                                  z["x_test"], z["y_test"])
        is_real = True
    else:
        ((xtr, ytr), (xte, yte)) = _synthetic_images(
            n_train or 5000, n_test or 1000, shape=(64, 64, 3),
            num_classes=200, seed=23)
        is_real = False
    if n_train:
        xtr, ytr = xtr[:n_train], ytr[:n_train]
    if n_test:
        xte, yte = xte[:n_test], yte[:n_test]
    kw = dict(num_classes=200, normalize=normalize, one_hot=one_hot,
              image_shape=(64, 64, 3))
    return _prep(xtr, ytr, **kw), _prep(xte, yte, **kw), is_real
