"""Excel (.xlsx) record reader.

ref: datavec-excel ExcelRecordReader (SURVEY §2.4 "other data domains" —
Excel is named reference surface). The reference wraps Apache POI; this
environment has no spreadsheet dependency, and none is needed: an .xlsx
file IS a zip of XML parts (ECMA-376). This reader handles the subset real
data files use — sharedStrings, inline strings, numeric cells, per-sheet
rows — with the stdlib ``zipfile`` + ``xml.etree`` only, mirroring the
repo's dependency-free ONNX/TB codecs.
"""

from __future__ import annotations

import pathlib
import zipfile
from typing import List, Optional, Sequence, Union
from xml.etree import ElementTree

from deeplearning4j_tpu.data.records import RecordReader, _as_paths

_NS = "{http://schemas.openxmlformats.org/spreadsheetml/2006/main}"
_REL_NS = ("{http://schemas.openxmlformats.org/officeDocument/2006/"
           "relationships}")


def _col_index(cell_ref: str) -> int:
    """'A1' → 0, 'BC12' → 54 (0-based column)."""
    col = 0
    for ch in cell_ref:
        if ch.isdigit():
            break
        col = col * 26 + (ord(ch.upper()) - ord("A") + 1)
    return col - 1


def _shared_strings(zf: zipfile.ZipFile) -> List[str]:
    try:
        data = zf.read("xl/sharedStrings.xml")
    except KeyError:
        return []
    root = ElementTree.fromstring(data)
    return [_rich_text(si) for si in root.findall(f"{_NS}si")]


def _rich_text(el) -> str:
    """Cell text from an <si>/<is> element: plain <t> plus rich-text runs
    <r><t>; phonetic guides <rPh> are furigana annotations, NOT cell text —
    excluded (direct-children walk, not .iter())."""
    parts = [t.text or "" for t in el.findall(f"{_NS}t")]
    for run in el.findall(f"{_NS}r"):
        parts.extend(t.text or "" for t in run.findall(f"{_NS}t"))
    return "".join(parts)


def _sheet_paths(zf: zipfile.ZipFile, sheet: Optional[Union[int, str]]
                 ) -> List[str]:
    wb = ElementTree.fromstring(zf.read("xl/workbook.xml"))
    rels = ElementTree.fromstring(zf.read("xl/_rels/workbook.xml.rels"))
    rel_map = {
        r.get("Id"): r.get("Target")
        for r in rels.findall(
            "{http://schemas.openxmlformats.org/package/2006/relationships}"
            "Relationship")
    }
    sheets = []
    for sh in wb.find(f"{_NS}sheets").findall(f"{_NS}sheet"):
        target = rel_map.get(sh.get(f"{_REL_NS}id"), "").lstrip("/")
        if target and not target.startswith("xl/"):
            target = f"xl/{target}"
        sheets.append((sh.get("name"), target))
    if sheet is None:
        return [t for _, t in sheets]
    if isinstance(sheet, int):
        return [sheets[sheet][1]]
    for name, t in sheets:
        if name == sheet:
            return [t]
    raise ValueError(
        f"sheet {sheet!r} not found; have {[n for n, _ in sheets]}")


class ExcelRecordReader(RecordReader):
    """↔ org.datavec.poi.excel.ExcelRecordReader: one record per row.

    Values: numeric cells → float, string cells → str, empty cells →
    ``None``; rows pad to the widest row across ALL selected sheets/files
    so the dataset bridge always sees rectangular records.
    ``sheet``: None = every sheet in order (the reference iterates all),
    an int index, or a sheet name. ``skip_rows`` skips headers per sheet.
    """

    def __init__(self, paths: Union[str, pathlib.Path, Sequence],
                 *, sheet: Optional[Union[int, str]] = None,
                 skip_rows: int = 0):
        self.paths = _as_paths(paths)
        self.sheet = sheet
        self.skip_rows = skip_rows

    def _rows(self, zf: zipfile.ZipFile, sheet_path: str, strings: List[str]):
        root = ElementTree.fromstring(zf.read(sheet_path))
        data = root.find(f"{_NS}sheetData")
        if data is None:
            return
        for i, row in enumerate(data.findall(f"{_NS}row")):
            if i < self.skip_rows:
                continue
            rec: List = []
            for c in row.findall(f"{_NS}c"):
                ref = c.get("r", "")
                # r= is optional per ECMA-376: default to the next column
                idx = _col_index(ref) if ref else len(rec)
                while len(rec) <= idx:
                    rec.append(None)
                ctype = c.get("t", "n")
                v = c.find(f"{_NS}v")
                if ctype == "inlineStr":
                    is_el = c.find(f"{_NS}is")
                    rec[idx] = (_rich_text(is_el)
                                if is_el is not None else None)
                elif v is None or v.text is None:
                    rec[idx] = None
                elif ctype == "s":
                    rec[idx] = strings[int(v.text)]
                elif ctype in ("str", "d"):  # formula string / ISO date
                    rec[idx] = v.text
                elif ctype == "b":
                    rec[idx] = bool(int(v.text))
                elif ctype == "e":  # formula error cell (#DIV/0! etc.)
                    rec[idx] = None
                else:  # 'n' numeric (or untyped)
                    rec[idx] = float(v.text)
            yield rec

    def _iter_raw(self):
        for p in self.paths:
            with zipfile.ZipFile(p) as zf:
                strings = _shared_strings(zf)
                for sheet_path in _sheet_paths(zf, self.sheet):
                    yield from self._rows(zf, sheet_path, strings)

    def __iter__(self):
        # True two-pass: pass 1 scans only row widths, pass 2 re-parses and
        # yields padded rows — global width (across sheets AND files, so
        # the dataset bridge never sees ragged records) at O(one row)
        # memory instead of materializing the corpus.
        width = max((len(r) for r in self._iter_raw()), default=0)
        for r in self._iter_raw():
            yield r + [None] * (width - len(r))


def write_xlsx(path: Union[str, pathlib.Path],
               rows: Sequence[Sequence], *, sheet_name: str = "Sheet1"):
    """Minimal single-sheet .xlsx writer (inline strings + numbers) — the
    round-trip partner for tests/fixtures; not a formatting library."""

    def cell_ref(r, c):
        col = ""
        c += 1
        while c:
            c, rem = divmod(c - 1, 26)
            col = chr(ord("A") + rem) + col
        return f"{col}{r + 1}"

    body = []
    for ri, row in enumerate(rows):
        cells = []
        for ci, v in enumerate(row):
            if v is None:
                continue
            ref = cell_ref(ri, ci)
            if isinstance(v, bool):
                cells.append(f'<c r="{ref}" t="b"><v>{int(v)}</v></c>')
            elif isinstance(v, (int, float)):
                cells.append(f'<c r="{ref}"><v>{v}</v></c>')
            else:
                sv = (str(v).replace("&", "&amp;").replace("<", "&lt;")
                      .replace(">", "&gt;"))
                cells.append(
                    f'<c r="{ref}" t="inlineStr"><is><t>{sv}</t></is></c>')
        body.append(f'<row r="{ri + 1}">{"".join(cells)}</row>')
    sheet_xml = (
        '<?xml version="1.0" encoding="UTF-8" standalone="yes"?>'
        f'<worksheet xmlns="{_NS[1:-1]}"><sheetData>{"".join(body)}'
        "</sheetData></worksheet>")
    sn = (sheet_name.replace("&", "&amp;").replace("<", "&lt;")
          .replace(">", "&gt;").replace('"', "&quot;"))
    wb = (
        '<?xml version="1.0" encoding="UTF-8" standalone="yes"?>'
        f'<workbook xmlns="{_NS[1:-1]}" xmlns:r="{_REL_NS[1:-1]}"><sheets>'
        f'<sheet name="{sn}" sheetId="1" r:id="rId1"/>'
        "</sheets></workbook>")
    rels = (
        '<?xml version="1.0" encoding="UTF-8" standalone="yes"?>'
        '<Relationships xmlns="http://schemas.openxmlformats.org/package/'
        '2006/relationships">'
        '<Relationship Id="rId1" Type="http://schemas.openxmlformats.org/'
        'officeDocument/2006/relationships/worksheet" '
        'Target="worksheets/sheet1.xml"/></Relationships>')
    types = (
        '<?xml version="1.0" encoding="UTF-8" standalone="yes"?>'
        '<Types xmlns="http://schemas.openxmlformats.org/package/2006/'
        'content-types">'
        '<Default Extension="rels" ContentType="application/vnd.'
        'openxmlformats-package.relationships+xml"/>'
        '<Default Extension="xml" ContentType="application/xml"/>'
        '<Override PartName="/xl/workbook.xml" ContentType="application/'
        'vnd.openxmlformats-officedocument.spreadsheetml.sheet.main+xml"/>'
        '<Override PartName="/xl/worksheets/sheet1.xml" ContentType='
        '"application/vnd.openxmlformats-officedocument.spreadsheetml.'
        'worksheet+xml"/></Types>')
    root_rels = (
        '<?xml version="1.0" encoding="UTF-8" standalone="yes"?>'
        '<Relationships xmlns="http://schemas.openxmlformats.org/package/'
        '2006/relationships">'
        '<Relationship Id="rId1" Type="http://schemas.openxmlformats.org/'
        'officeDocument/2006/relationships/officeDocument" '
        'Target="xl/workbook.xml"/></Relationships>')
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("[Content_Types].xml", types)
        zf.writestr("_rels/.rels", root_rels)
        zf.writestr("xl/workbook.xml", wb)
        zf.writestr("xl/_rels/workbook.xml.rels", rels)
        zf.writestr("xl/worksheets/sheet1.xml", sheet_xml)
