"""Dataset iterators (↔ org.nd4j.linalg.dataset.api.iterator.DataSetIterator
+ org.deeplearning4j.datasets.iterator.AsyncDataSetIterator).

The reference's AsyncDataSetIterator prefetches batches on a background
thread into a workspace ring; the TPU-native equivalent overlaps host ETL
with device compute via a background thread + ``jax.device_put`` onto a
sharding (H2D happens while the previous step runs — double buffering).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, Optional

import jax
import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet


class ArrayDataSetIterator:
    """In-memory (features, labels) → minibatch iterator
    (↔ ListDataSetIterator / ExistingDataSetIterator)."""

    def __init__(self, features, labels, batch_size: int, *, shuffle: bool = True,
                 seed: int = 0, drop_last: bool = True):
        self.features = np.asarray(features)
        self.labels = np.asarray(labels)
        assert self.features.shape[0] == self.labels.shape[0]
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._rng = np.random.default_rng(seed)
        self._epoch = 0

    def __len__(self):
        n = self.features.shape[0]
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def __iter__(self) -> Iterator[DataSet]:
        n = self.features.shape[0]
        idx = np.arange(n)
        if self.shuffle:
            self._rng.shuffle(idx)
        end = n - (n % self.batch_size) if self.drop_last else n
        for i in range(0, end, self.batch_size):
            sel = idx[i : i + self.batch_size]
            yield DataSet(self.features[sel], self.labels[sel])
        self._epoch += 1

    def reset(self):
        pass  # fresh iterator each __iter__


class AsyncDataSetIterator:
    """Background-thread prefetch wrapper (↔ AsyncDataSetIterator with its
    workspace ring buffer; here the ring is a bounded queue and the
    device-transfer overlap comes from issuing ``jax.device_put`` before the
    consumer needs the batch)."""

    def __init__(self, base: Iterable, prefetch: int = 2, device_put_to=None):
        self.base = base
        self.prefetch = prefetch
        self.device_put_to = device_put_to

    def __iter__(self):
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        sentinel = object()
        stop = threading.Event()
        err: list = []

        def put(item) -> bool:
            # Bounded put that gives up when the consumer abandoned us, so an
            # early `break` in the consumer can't leave this thread blocked
            # holding device buffers alive.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for item in self.base:
                    if self.device_put_to is not None:
                        item = jax.device_put(item, self.device_put_to)
                    if not put(item):
                        return
            except BaseException as e:  # propagate into consumer
                err.append(e)
            finally:
                put(sentinel)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            stop.set()

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()

    def __len__(self):
        return len(self.base)  # type: ignore[arg-type]


class TransformIterator:
    """Apply a per-batch transform fn (↔ the DataSetPreProcessor hook on
    DataSetIterator: normalizers attach this way)."""

    def __init__(self, base: Iterable, fn: Callable[[DataSet], DataSet]):
        self.base = base
        self.fn = fn

    def __iter__(self):
        for b in self.base:
            yield self.fn(b)

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()

    def __len__(self):
        return len(self.base)  # type: ignore[arg-type]
