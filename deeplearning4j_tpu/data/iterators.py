"""Dataset iterators (↔ org.nd4j.linalg.dataset.api.iterator.DataSetIterator
+ org.deeplearning4j.datasets.iterator.AsyncDataSetIterator).

The reference's AsyncDataSetIterator prefetches batches on a background
thread into a workspace ring; the TPU-native equivalent overlaps host ETL
with device compute via a background thread + ``jax.device_put`` onto a
sharding (H2D happens while the previous step runs — double buffering).
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Callable, Iterable, Iterator, Optional

import jax
import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet, as_batch_dict
from deeplearning4j_tpu.resilience.faults import get_fault_injector

# Degraded-mode env plumbing: the elastic supervisor
# (resilience/supervisor.py — same literals there; that module must stay
# importable without jax, this one without it) arms these per generation
# so a relaunched worker re-derives its shard from the NEW
# (worker_id, num_workers) under an explicit policy.
ENV_SHRINK_POLICY = "DL4J_TPU_SHRINK_POLICY"
ENV_BASELINE_NUM_WORKERS = "DL4J_TPU_BASELINE_NUM_WORKERS"
# Starvation remediation (train/trainer.py `_StepTelemetry` detects,
# this wraps): opt-in background prefetch of the training iterator.
ENV_AUTO_PREFETCH = "DL4J_TPU_AUTO_PREFETCH"
ENV_PREFETCH_DEPTH = "DL4J_TPU_PREFETCH_DEPTH"


class ShrinkPolicy:
    """How a shrunken cohort (N baseline workers, n < N survivors)
    re-divides the global batch — the explicit choice degraded-mode
    training forces:

    - ``PRESERVE_GLOBAL_BATCH``: the global batch stays whole; each
      survivor's share grows to ``rows / n``. Optimization dynamics are
      unchanged (same batches, same gradient), per-worker memory and
      step time grow — the default, matching the topology-independent
      checkpoint restore's bitwise-continuity story.
    - ``PRESERVE_PER_WORKER_BATCH``: each survivor keeps its baseline
      share ``rows / N``; the dead slots' rows are dropped, so the
      effective global batch shrinks to ``n * rows / N``. Per-worker
      cost is unchanged, throughput (and the gradient's batch size)
      degrades — for cohorts already at the per-chip memory ceiling.
    """

    PRESERVE_GLOBAL_BATCH = "preserve_global_batch"
    PRESERVE_PER_WORKER_BATCH = "preserve_per_worker_batch"
    ALL = (PRESERVE_GLOBAL_BATCH, PRESERVE_PER_WORKER_BATCH)

    @staticmethod
    def from_env(default: str = PRESERVE_GLOBAL_BATCH) -> str:
        """The supervisor-armed policy (``DL4J_TPU_SHRINK_POLICY``),
        degrading to ``default`` on junk/absent env — a typo'd policy
        must not crash a relaunching cohort."""
        val = os.environ.get(ENV_SHRINK_POLICY, "").strip().lower()
        return val if val in ShrinkPolicy.ALL else default


def baseline_num_workers_from_env() -> Optional[int]:
    """The cohort's FULL size (``DL4J_TPU_BASELINE_NUM_WORKERS``, armed
    by the supervisor) — what ``PRESERVE_PER_WORKER_BATCH`` divides by;
    None when not running under a supervisor."""
    raw = os.environ.get(ENV_BASELINE_NUM_WORKERS)
    try:
        n = int(raw) if raw else 0
    except ValueError:
        return None
    return n if n >= 1 else None


def derive_shard(n_rows: int, worker_id: int, num_workers: int, *,
                 baseline_num_workers: Optional[int] = None,
                 policy: Optional[str] = None) -> slice:
    """This worker's row block of a global batch, re-derived from the
    CURRENT ``(worker_id, num_workers)`` — the pure function both
    :class:`ShardedDataSetIterator` and custom readers use, so a cohort
    relaunched at a different size agrees on the division without any
    cross-worker negotiation.

    ``PRESERVE_GLOBAL_BATCH`` divides ``n_rows`` by ``num_workers``
    (shares grow on a shrunken cohort); ``PRESERVE_PER_WORKER_BATCH``
    divides by ``baseline_num_workers`` (shares stay put; the trailing
    dead slots' rows fall out of the batch)."""
    policy = ShrinkPolicy.from_env() if policy is None else policy
    if policy not in ShrinkPolicy.ALL:
        raise ValueError(f"unknown shrink policy {policy!r}; expected one "
                         f"of {ShrinkPolicy.ALL}")
    if not 0 <= worker_id < num_workers:
        raise ValueError(f"worker_id {worker_id} out of range for "
                         f"num_workers={num_workers}")
    divisor = num_workers
    if policy == ShrinkPolicy.PRESERVE_PER_WORKER_BATCH:
        divisor = baseline_num_workers or num_workers
        if divisor < num_workers:
            raise ValueError(
                f"baseline_num_workers={divisor} smaller than the live "
                f"cohort ({num_workers}) — the baseline is the FULL size")
    per, rem = divmod(n_rows, divisor)
    if rem:
        raise ValueError(
            f"global batch {n_rows} not divisible by {divisor} "
            f"({'baseline ' if divisor != num_workers else ''}workers)")
    return slice(worker_id * per, (worker_id + 1) * per)


class ArrayDataSetIterator:
    """In-memory (features, labels) → minibatch iterator
    (↔ ListDataSetIterator / ExistingDataSetIterator)."""

    def __init__(self, features, labels, batch_size: int, *, shuffle: bool = True,
                 seed: int = 0, drop_last: bool = True):
        self.features = np.asarray(features)
        self.labels = np.asarray(labels)
        assert self.features.shape[0] == self.labels.shape[0]
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self._epoch = 0
        self._in_pass = False

    def __len__(self):
        n = self.features.shape[0]
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def __iter__(self) -> Iterator[DataSet]:
        self._in_pass = True
        n = self.features.shape[0]
        idx = np.arange(n)
        if self.shuffle:
            # permutation derived from (seed, epoch), not a stateful rng:
            # an aborted pass (transient read failure) re-iterates with the
            # SAME order, so resilience.retrying's fast-forward re-delivers
            # the stream exactly; the epoch advances on a completed pass
            # (below) or via reset()/set_epoch()
            np.random.default_rng([self.seed, self._epoch]).shuffle(idx)
        end = n - (n % self.batch_size) if self.drop_last else n
        inj = get_fault_injector()
        for i in range(0, end, self.batch_size):
            if inj.enabled:
                # "data.read" injection point: a transient storage failure
                # surfaces exactly like a real reader's (wrap with
                # resilience.retrying() to survive it)
                inj.maybe_fail("data.read", exc=IOError,
                               msg="injected transient read failure")
            sel = idx[i : i + self.batch_size]
            yield DataSet(self.features[sel], self.labels[sel])
        self._epoch += 1
        self._in_pass = False

    @property
    def epoch(self) -> int:
        return self._epoch

    def set_epoch(self, epoch: int):
        """Pin the shuffle permutation to a logical epoch — recovery
        resumes/rollbacks re-align the data order with a checkpointed
        position (the permutation is a pure function of (seed, epoch))."""
        self._epoch = int(epoch)
        self._in_pass = False

    def reset(self):
        # an abandoned pass (steps_per_epoch break, early stop) still
        # counts as a finished epoch: the next pass must reshuffle, not
        # replay the same permutation prefix forever
        if self._in_pass:
            self._epoch += 1
            self._in_pass = False


class AsyncDataSetIterator:
    """Background-thread prefetch wrapper (↔ AsyncDataSetIterator with its
    workspace ring buffer; here the ring is a bounded queue and the
    device-transfer overlap comes from issuing ``jax.device_put`` before the
    consumer needs the batch)."""

    def __init__(self, base: Iterable, prefetch: int = 2, device_put_to=None):
        self.base = base
        self.prefetch = prefetch
        self.device_put_to = device_put_to

    def __iter__(self):
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        sentinel = object()
        stop = threading.Event()
        err: list = []

        def put(item) -> bool:
            # Bounded put that gives up when the consumer abandoned us, so an
            # early `break` in the consumer can't leave this thread blocked
            # holding device buffers alive.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for item in self.base:
                    if self.device_put_to is not None:
                        item = jax.device_put(item, self.device_put_to)
                    if not put(item):
                        return
            except BaseException as e:  # propagate into consumer
                err.append(e)
            finally:
                put(sentinel)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            stop.set()

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()

    def set_epoch(self, epoch: int):
        """Epoch-pinning pass-through (the recovery layer's shuffle
        realignment protocol — see ``ArrayDataSetIterator.set_epoch``)."""
        if hasattr(self.base, "set_epoch"):
            self.base.set_epoch(epoch)

    @property
    def epoch(self):
        return getattr(self.base, "epoch", 0)

    def __len__(self):
        return len(self.base)  # type: ignore[arg-type]


def maybe_auto_prefetch(data, *, device_put_to=None):
    """Wrap ``data`` in :class:`AsyncDataSetIterator` when the operator
    armed ``DL4J_TPU_AUTO_PREFETCH=1`` — the minimal remediation for a
    firing ``train_data_starved`` detector (the reads that dominated the
    step now overlap it from a background thread). Opt-in because a
    prefetch thread changes teardown/ordering semantics for exotic
    iterators; already-wrapped iterators pass through untouched.
    ``DL4J_TPU_PREFETCH_DEPTH`` sizes the ring (default 2 — double
    buffering)."""
    if os.environ.get(ENV_AUTO_PREFETCH, "").strip().lower() \
            not in ("1", "true", "yes"):
        return data
    if isinstance(data, AsyncDataSetIterator):
        return data
    try:
        depth = int(os.environ.get(ENV_PREFETCH_DEPTH) or 2)
    except ValueError:
        depth = 2
    depth = max(1, depth)
    try:
        from deeplearning4j_tpu.observability.flightrecorder import (
            record_event,
        )

        record_event("data.auto_prefetch", depth=depth,
                     base=type(data).__name__)
    except Exception:  # noqa: BLE001 — telemetry never fails the wrap
        pass
    return AsyncDataSetIterator(data, prefetch=depth,
                                device_put_to=device_put_to)


class TransformIterator:
    """Apply a per-batch transform fn (↔ the DataSetPreProcessor hook on
    DataSetIterator: normalizers attach this way)."""

    def __init__(self, base: Iterable, fn: Callable[[DataSet], DataSet]):
        self.base = base
        self.fn = fn

    def __iter__(self):
        for b in self.base:
            yield self.fn(b)

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()

    def __len__(self):
        return len(self.base)  # type: ignore[arg-type]


class ShardedDataSetIterator:
    """Per-host input sharding for SPMD training (↔ the role Spark
    executors' partition-local iterators / VirtualDataSetIterator played
    under SharedTrainingMaster — recast: no partition shuffling service,
    each process feeds its rows of the global batch and batches emerge as
    GLOBAL jax.Arrays laid out by ``spec`` over ``mesh``).

    Two feeding modes:

    - ``local=False`` (default): ``base`` yields the GLOBAL batch on every
      process (small/synthetic data); each process keeps only its
      contiguous row block before assembly — no duplicate H2D traffic.
    - ``local=True``: ``base`` yields only this process's rows (real
      multi-host pipelines, where each host reads its own files); rows
      across processes concatenate in process order.

    Assembly uses multihost_utils.host_local_array_to_global_array, which
    degenerates to a plain sharded device_put in single-process jobs — the
    same iterator runs unchanged on 1 chip, an 8-device CPU mesh, or a
    multi-host slice. Wrap with AsyncDataSetIterator for prefetch overlap.

    **Elastic degraded mode**: the shard is re-derived from the LIVE
    ``(process_index, process_count)`` on every construction, so a
    cohort relaunched at N-k after a shrink (resilience/supervisor.py)
    re-divides the same global stream with no code change. The division
    rule is an explicit :class:`ShrinkPolicy` — ``shrink_policy`` /
    ``baseline_num_workers`` default to the supervisor-armed env
    (``DL4J_TPU_SHRINK_POLICY`` / ``DL4J_TPU_BASELINE_NUM_WORKERS``),
    preserving the global batch unless told otherwise. ``local=True``
    mode is unaffected (each host already reads only its own rows — a
    shrunken cohort there simply reads fewer hosts' files).
    """

    def __init__(self, base: Iterable, mesh, spec, *, local: bool = False,
                 shrink_policy: Optional[str] = None,
                 baseline_num_workers: Optional[int] = None):
        self.base = base
        self.mesh = mesh
        self.spec = spec
        self.local = local
        self.shrink_policy = (ShrinkPolicy.from_env()
                              if shrink_policy is None else shrink_policy)
        if self.shrink_policy not in ShrinkPolicy.ALL:
            raise ValueError(
                f"unknown shrink policy {self.shrink_policy!r}; expected "
                f"one of {ShrinkPolicy.ALL}")
        self.baseline_num_workers = (
            baseline_num_workers_from_env()
            if baseline_num_workers is None else baseline_num_workers)
        if jax.process_count() > 1:
            # Row blocks are assigned in process order; the assembly places
            # each process's rows at its devices' mesh positions. A mesh
            # whose device order interleaves processes (e.g. a custom
            # ICI-optimized mesh_utils layout) would silently scramble rows
            # across hosts — require process-grouped order (what
            # runtime.distributed.global_mesh() builds).
            procs = [d.process_index for d in mesh.devices.flat]
            if procs != sorted(procs):
                raise ValueError(
                    "mesh device order interleaves processes; build the "
                    "mesh with runtime.distributed.global_mesh() (or any "
                    "process-grouped order) for per-host input sharding")

    def _proc_slice(self, arr):
        if self.local:
            return arr
        n = jax.process_count()
        baseline = self.baseline_num_workers or n
        if n == 1 and baseline == 1:
            return arr
        # the policy-aware division: under PRESERVE_PER_WORKER_BATCH a
        # shrunken cohort (baseline > n) keeps baseline-sized shares and
        # drops the dead slots' rows; PRESERVE_GLOBAL_BATCH grows each
        # survivor's share so the batch (and the gradient) is unchanged
        return arr[derive_shard(arr.shape[0], jax.process_index(), n,
                                baseline_num_workers=baseline,
                                policy=self.shrink_policy)]

    def __iter__(self):
        from deeplearning4j_tpu.runtime.distributed import (
            host_local_to_global,
        )

        for batch in self.base:
            b = as_batch_dict(batch)
            locl = {k: self._proc_slice(np.asarray(v)) for k, v in b.items()}
            yield host_local_to_global(locl, self.mesh,
                                       jax.tree_util.tree_map(
                                           lambda _: self.spec, locl))

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()

    def __len__(self):
        return len(self.base)
