"""Dataset iterators (↔ org.nd4j.linalg.dataset.api.iterator.DataSetIterator
+ org.deeplearning4j.datasets.iterator.AsyncDataSetIterator).

The reference's AsyncDataSetIterator prefetches batches on a background
thread into a workspace ring; the TPU-native equivalent overlaps host ETL
with device compute via a background thread + ``jax.device_put`` onto a
sharding (H2D happens while the previous step runs — double buffering).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, Optional

import jax
import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet, as_batch_dict
from deeplearning4j_tpu.resilience.faults import get_fault_injector


class ArrayDataSetIterator:
    """In-memory (features, labels) → minibatch iterator
    (↔ ListDataSetIterator / ExistingDataSetIterator)."""

    def __init__(self, features, labels, batch_size: int, *, shuffle: bool = True,
                 seed: int = 0, drop_last: bool = True):
        self.features = np.asarray(features)
        self.labels = np.asarray(labels)
        assert self.features.shape[0] == self.labels.shape[0]
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self._epoch = 0
        self._in_pass = False

    def __len__(self):
        n = self.features.shape[0]
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def __iter__(self) -> Iterator[DataSet]:
        self._in_pass = True
        n = self.features.shape[0]
        idx = np.arange(n)
        if self.shuffle:
            # permutation derived from (seed, epoch), not a stateful rng:
            # an aborted pass (transient read failure) re-iterates with the
            # SAME order, so resilience.retrying's fast-forward re-delivers
            # the stream exactly; the epoch advances on a completed pass
            # (below) or via reset()/set_epoch()
            np.random.default_rng([self.seed, self._epoch]).shuffle(idx)
        end = n - (n % self.batch_size) if self.drop_last else n
        inj = get_fault_injector()
        for i in range(0, end, self.batch_size):
            if inj.enabled:
                # "data.read" injection point: a transient storage failure
                # surfaces exactly like a real reader's (wrap with
                # resilience.retrying() to survive it)
                inj.maybe_fail("data.read", exc=IOError,
                               msg="injected transient read failure")
            sel = idx[i : i + self.batch_size]
            yield DataSet(self.features[sel], self.labels[sel])
        self._epoch += 1
        self._in_pass = False

    @property
    def epoch(self) -> int:
        return self._epoch

    def set_epoch(self, epoch: int):
        """Pin the shuffle permutation to a logical epoch — recovery
        resumes/rollbacks re-align the data order with a checkpointed
        position (the permutation is a pure function of (seed, epoch))."""
        self._epoch = int(epoch)
        self._in_pass = False

    def reset(self):
        # an abandoned pass (steps_per_epoch break, early stop) still
        # counts as a finished epoch: the next pass must reshuffle, not
        # replay the same permutation prefix forever
        if self._in_pass:
            self._epoch += 1
            self._in_pass = False


class AsyncDataSetIterator:
    """Background-thread prefetch wrapper (↔ AsyncDataSetIterator with its
    workspace ring buffer; here the ring is a bounded queue and the
    device-transfer overlap comes from issuing ``jax.device_put`` before the
    consumer needs the batch)."""

    def __init__(self, base: Iterable, prefetch: int = 2, device_put_to=None):
        self.base = base
        self.prefetch = prefetch
        self.device_put_to = device_put_to

    def __iter__(self):
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        sentinel = object()
        stop = threading.Event()
        err: list = []

        def put(item) -> bool:
            # Bounded put that gives up when the consumer abandoned us, so an
            # early `break` in the consumer can't leave this thread blocked
            # holding device buffers alive.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for item in self.base:
                    if self.device_put_to is not None:
                        item = jax.device_put(item, self.device_put_to)
                    if not put(item):
                        return
            except BaseException as e:  # propagate into consumer
                err.append(e)
            finally:
                put(sentinel)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            stop.set()

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()

    def __len__(self):
        return len(self.base)  # type: ignore[arg-type]


class TransformIterator:
    """Apply a per-batch transform fn (↔ the DataSetPreProcessor hook on
    DataSetIterator: normalizers attach this way)."""

    def __init__(self, base: Iterable, fn: Callable[[DataSet], DataSet]):
        self.base = base
        self.fn = fn

    def __iter__(self):
        for b in self.base:
            yield self.fn(b)

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()

    def __len__(self):
        return len(self.base)  # type: ignore[arg-type]


class ShardedDataSetIterator:
    """Per-host input sharding for SPMD training (↔ the role Spark
    executors' partition-local iterators / VirtualDataSetIterator played
    under SharedTrainingMaster — recast: no partition shuffling service,
    each process feeds its rows of the global batch and batches emerge as
    GLOBAL jax.Arrays laid out by ``spec`` over ``mesh``).

    Two feeding modes:

    - ``local=False`` (default): ``base`` yields the GLOBAL batch on every
      process (small/synthetic data); each process keeps only its
      contiguous row block before assembly — no duplicate H2D traffic.
    - ``local=True``: ``base`` yields only this process's rows (real
      multi-host pipelines, where each host reads its own files); rows
      across processes concatenate in process order.

    Assembly uses multihost_utils.host_local_array_to_global_array, which
    degenerates to a plain sharded device_put in single-process jobs — the
    same iterator runs unchanged on 1 chip, an 8-device CPU mesh, or a
    multi-host slice. Wrap with AsyncDataSetIterator for prefetch overlap.
    """

    def __init__(self, base: Iterable, mesh, spec, *, local: bool = False):
        self.base = base
        self.mesh = mesh
        self.spec = spec
        self.local = local
        if jax.process_count() > 1:
            # Row blocks are assigned in process order; the assembly places
            # each process's rows at its devices' mesh positions. A mesh
            # whose device order interleaves processes (e.g. a custom
            # ICI-optimized mesh_utils layout) would silently scramble rows
            # across hosts — require process-grouped order (what
            # runtime.distributed.global_mesh() builds).
            procs = [d.process_index for d in mesh.devices.flat]
            if procs != sorted(procs):
                raise ValueError(
                    "mesh device order interleaves processes; build the "
                    "mesh with runtime.distributed.global_mesh() (or any "
                    "process-grouped order) for per-host input sharding")

    def _proc_slice(self, arr):
        n = jax.process_count()
        if n == 1 or self.local:
            return arr
        per = arr.shape[0] // n
        if per * n != arr.shape[0]:
            raise ValueError(
                f"global batch {arr.shape[0]} not divisible by "
                f"{n} processes")
        pid = jax.process_index()
        return arr[pid * per:(pid + 1) * per]

    def __iter__(self):
        from deeplearning4j_tpu.runtime.distributed import (
            host_local_to_global,
        )

        for batch in self.base:
            b = as_batch_dict(batch)
            locl = {k: self._proc_slice(np.asarray(v)) for k, v in b.items()}
            yield host_local_to_global(locl, self.mesh,
                                       jax.tree_util.tree_map(
                                           lambda _: self.spec, locl))

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()

    def __len__(self):
        return len(self.base)
