"""Tokenizers (↔ org.deeplearning4j.text.tokenization.tokenizerfactory.*).

ref: DefaultTokenizerFactory (whitespace/punct split), NGramTokenizerFactory,
TokenPreProcess impls (CommonPreprocessor: lowercase + strip punctuation,
EndingPreProcessor). Pure host-side string processing — no device work.
"""

from __future__ import annotations

import re
from typing import Callable, List, Optional


class CommonPreprocessor:
    """↔ CommonPreprocessor: lowercase, strip punctuation/digits-noise."""

    _PUNCT = re.compile(r"[^\w\s]|_", re.UNICODE)

    def __call__(self, token: str) -> str:
        return self._PUNCT.sub("", token.lower())


class LowCasePreprocessor:
    def __call__(self, token: str) -> str:
        return token.lower()


class DefaultTokenizerFactory:
    """↔ DefaultTokenizerFactory: split on whitespace, optional per-token
    preprocessor."""

    _SPLIT = re.compile(r"\s+")

    def __init__(self, preprocessor: Optional[Callable[[str], str]] = None):
        self.preprocessor = preprocessor

    def tokenize(self, text: str) -> List[str]:
        toks = [t for t in self._SPLIT.split(text.strip()) if t]
        if self.preprocessor is not None:
            toks = [self.preprocessor(t) for t in toks]
        return [t for t in toks if t]

    def __call__(self, text: str) -> List[str]:
        return self.tokenize(text)


class NGramTokenizerFactory(DefaultTokenizerFactory):
    """↔ NGramTokenizerFactory: emits n-grams (joined with '_') from n_min
    to n_max over the base tokens."""

    def __init__(self, n_min: int = 1, n_max: int = 2,
                 preprocessor: Optional[Callable[[str], str]] = None):
        super().__init__(preprocessor)
        self.n_min = n_min
        self.n_max = n_max

    def tokenize(self, text: str) -> List[str]:
        base = super().tokenize(text)
        out: List[str] = []
        for n in range(self.n_min, self.n_max + 1):
            for i in range(len(base) - n + 1):
                out.append("_".join(base[i:i + n]))
        return out
