"""Word2Vec: skip-gram / CBOW with negative sampling (↔ deeplearning4j-nlp
org.deeplearning4j.models.word2vec.Word2Vec + SkipGram/CBOW learning impls,
SURVEY §2.7; the distributed variant replaces the VoidParameterServer
skip-gram shard routing of §2.6 P5).

TPU-first design: the reference trains embeddings with per-pair JVM updates
(SkipGramRequestMessage routed to parameter-server shards). Here training
batches thousands of (center, context, negatives) triples into ONE jit'd
SGNS step — embedding gathers + logistic loss; jax.grad turns the gathers
into scatter-adds, XLA fuses the whole update, and under a mesh the
embedding table shards on the `model` axis (tensor-parallel gather —
the P5 "parameter server for embeddings" capability without a server).
Pair generation (windowing, subsampling, negative draws) stays host-side
numpy, overlapped with device steps by simple pipelining.
"""

from __future__ import annotations

from typing import Callable, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.nlp.tokenization import (
    CommonPreprocessor,
    DefaultTokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import (
    VocabCache,
    build_vocab,
    fixed_shape_batches,
)


class _SGNSModel:
    """Shared skip-gram-negative-sampling machinery (used by Word2Vec and
    ParagraphVectors). Two tables: `in_vecs` (target/center or doc) and
    `out_vecs` (context)."""

    def __init__(self, n_in: int, n_out: int, dim: int, seed: int):
        rs = np.random.RandomState(seed)
        self.in_vecs = ((rs.rand(n_in, dim) - 0.5) / dim).astype(np.float32)
        self.out_vecs = np.zeros((n_out, dim), np.float32)
        # AdaGrad accumulators: batching SGNS sums many per-pair gradients
        # into the same embedding rows; AdaGrad's per-row scaling keeps that
        # stable at any batch size (plain SGD diverges on hot rows).
        self._acc = (np.full((n_in, dim), 1e-6, np.float32),
                     np.full((n_out, dim), 1e-6, np.float32))
        self._step = None

    def _build_step(self, mode: str = "sg", table_shardings=None):
        import jax
        import jax.numpy as jnp

        def sg_loss(tables, batch):
            center, context, negatives = batch
            inv, outv = tables
            v_c = inv[center]                    # [B, D]
            v_o = outv[context]                  # [B, D]
            v_n = outv[negatives]                # [B, K, D]
            pos = jnp.sum(v_c * v_o, -1)
            neg = jnp.einsum("bd,bkd->bk", v_c, v_n)
            # SGNS objective: log σ(pos) + Σ log σ(-neg). SUM over the batch
            # so each pair's embedding rows receive a full word2vec-scale
            # update (classic per-pair SGD batched); mean would divide the
            # effective per-pair lr by the batch size.
            return -jnp.sum(
                jax.nn.log_sigmoid(pos) + jnp.sum(jax.nn.log_sigmoid(-neg), -1))

        def cbow_loss(tables, batch):
            # CBOW: mean of the context-window vectors predicts the center
            # word (↔ the reference's CBOW learning impl).
            contexts, mask, center, negatives = batch
            inv, outv = tables
            v_ctx = inv[contexts] * mask[..., None]          # [B, C, D]
            h = jnp.sum(v_ctx, 1) / jnp.maximum(
                jnp.sum(mask, 1, keepdims=True), 1.0)        # [B, D]
            pos = jnp.sum(h * outv[center], -1)
            neg = jnp.einsum("bd,bkd->bk", h, outv[negatives])
            return -jnp.sum(
                jax.nn.log_sigmoid(pos) + jnp.sum(jax.nn.log_sigmoid(-neg), -1))

        loss_fn = sg_loss if mode == "sg" else cbow_loss

        def step(tables, acc, batch, lr):
            loss, grads = jax.value_and_grad(loss_fn)(tables, batch)
            acc = jax.tree_util.tree_map(lambda a, g: a + g * g, acc, grads)
            new = jax.tree_util.tree_map(
                lambda t, g, a: t - lr * g / jnp.sqrt(a), tables, grads, acc)
            b = batch[0].shape[0]
            return new, acc, loss / b  # report per-example mean

        if table_shardings is not None:
            # P5 parameter-server role: embedding rows sharded on the mesh
            # model axis; GSPMD turns the gathers/scatter-adds of the same
            # step function into the cross-shard collectives the reference
            # routed through VoidParameterServer messages.
            rep = table_shardings[-1]
            self._step = jax.jit(
                step, donate_argnums=(0, 1),
                in_shardings=(table_shardings[:2], table_shardings[:2],
                              rep, rep),
                out_shardings=(table_shardings[:2], table_shardings[:2], rep))
        else:
            self._step = jax.jit(step, donate_argnums=(0, 1))

    def train_epochs(self, batches_fn: Callable[[], Iterable], *, epochs: int,
                     lr: float, lr_min: float, mode: str = "sg",
                     mesh=None) -> List[float]:
        """batches_fn() yields tuples of arrays matching `mode`'s loss:
        sg: (center, context, negatives); cbow: (contexts, mask, center,
        negatives). ``mesh``: shard the embedding tables across the mesh's
        'model' axis (SURVEY §2.6 P5 — the parameter-server-for-embeddings
        role); tables whose vocab doesn't divide the axis stay replicated.
        """
        import jax
        import jax.numpy as jnp

        shardings = None
        if mesh is not None:
            from deeplearning4j_tpu.nlp.sharding import replicated, row_sharding

            shardings = (row_sharding(mesh, self.in_vecs.shape),
                         row_sharding(mesh, self.out_vecs.shape),
                         replicated(mesh))
        step_key = (mode, None if shardings is None else tuple(
            str(s) for s in shardings))
        if getattr(self, "_step_key", None) != step_key:
            self._build_step(mode, table_shardings=shardings)
            self._step_key = step_key
        tables = (jnp.asarray(self.in_vecs), jnp.asarray(self.out_vecs))
        acc = tuple(jnp.asarray(a) for a in self._acc)
        if shardings is not None:
            tables = tuple(jax.device_put(t, s)
                           for t, s in zip(tables, shardings[:2]))
            acc = tuple(jax.device_put(a, s)
                        for a, s in zip(acc, shardings[:2]))
        history = []
        for e in range(epochs):
            cur_lr = lr - (lr - lr_min) * e / max(epochs - 1, 1)
            losses = []
            for batch in batches_fn():
                tables, acc, loss = self._step(
                    tables, acc, tuple(jnp.asarray(a) for a in batch),
                    jnp.float32(cur_lr))
                losses.append(loss)
            if losses:
                # Stack on device: one host fetch per epoch instead of one
                # per batch (per-buffer fetches dominate on the TPU tunnel).
                history.append(float(np.mean(jax.device_get(jnp.stack(losses)))))
        self.in_vecs, self.out_vecs = (np.asarray(t) for t in tables)
        self._acc = tuple(np.asarray(a) for a in acc)
        return history


def _window_pairs(ids: Sequence[int], window: int, rng: np.random.Generator,
                  keep_probs: np.ndarray) -> List[Tuple[int, int]]:
    """Skip-gram training pairs with per-sentence random window shrink and
    frequency subsampling (Mikolov tricks, ↔ SkipGram.iterateSample)."""
    kept = [i for i in ids if keep_probs[i] >= 1.0 or rng.random() < keep_probs[i]]
    pairs = []
    for pos, center in enumerate(kept):
        b = rng.integers(1, window + 1)
        lo = max(0, pos - b)
        hi = min(len(kept), pos + b + 1)
        for j in range(lo, hi):
            if j != pos:
                pairs.append((center, kept[j]))
    return pairs


class Word2Vec:
    """↔ org.deeplearning4j.models.word2vec.Word2Vec (builder pattern kept
    as constructor kwargs).

    Usage::

        w2v = Word2Vec(vector_size=64, window=5, min_word_frequency=2)
        w2v.fit(sentences)                  # iterable of strings or token lists
        w2v.words_nearest("king", 5)
    """

    def __init__(self, *, vector_size: int = 100, window: int = 5,
                 min_word_frequency: int = 5, negative: int = 5,
                 subsample: float = 1e-3, learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4, epochs: int = 1,
                 batch_size: int = 2048, cbow: bool = False, seed: int = 0,
                 tokenizer: Optional[Callable] = None, mesh=None):
        self.vector_size = vector_size
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.negative = negative
        self.subsample = subsample
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.epochs = epochs
        self.batch_size = batch_size
        self.cbow = cbow
        self.seed = seed
        self.mesh = mesh  # P5: shard embedding tables over mesh 'model' axis
        self.tokenizer = tokenizer or DefaultTokenizerFactory(CommonPreprocessor())
        self.vocab: Optional[VocabCache] = None
        self._model: Optional[_SGNSModel] = None

    # -- training ----------------------------------------------------------

    def _tokenize_corpus(self, corpus) -> List[List[str]]:
        out = []
        for item in corpus:
            out.append(self.tokenizer(item) if isinstance(item, str) else list(item))
        return out

    def fit(self, corpus: Iterable) -> List[float]:
        sentences = self._tokenize_corpus(corpus)
        self.vocab = build_vocab(
            sentences, min_word_frequency=self.min_word_frequency,
            subsample=self.subsample)
        if len(self.vocab) < 2:
            raise ValueError("vocabulary too small (check min_word_frequency)")
        encoded = [self.vocab.encode(s) for s in sentences]
        encoded = [s for s in encoded if len(s) > 1]
        n = len(self.vocab)
        self._model = _SGNSModel(n, n, self.vector_size, self.seed)
        rng = np.random.default_rng(self.seed)

        if self.cbow:
            return self._fit_cbow(encoded, rng)

        def batches():
            pairs: List[Tuple[int, int]] = []
            for ids in encoded:
                pairs.extend(_window_pairs(ids, self.window, rng,
                                           self.vocab.keep_probs))
            arr = np.asarray(pairs, np.int32).reshape(-1, 2)
            for sel in fixed_shape_batches(len(arr), self.batch_size, rng,
                                           what="skip-gram pairs"):
                chunk = arr[sel]
                negs = self.vocab.sample_negatives(rng, (len(sel), self.negative))
                yield chunk[:, 0], chunk[:, 1], negs.astype(np.int32)

        return self._model.train_epochs(
            batches, epochs=self.epochs, lr=self.learning_rate,
            lr_min=self.min_learning_rate, mode="sg", mesh=self.mesh)

    def _fit_cbow(self, encoded, rng) -> List[float]:
        """CBOW samples: (padded context window, mask, center word)."""
        width = 2 * self.window

        def samples():
            ctxs, masks, centers = [], [], []
            for ids in encoded:
                kept = [i for i in ids
                        if self.vocab.keep_probs[i] >= 1.0
                        or rng.random() < self.vocab.keep_probs[i]]
                for pos, center in enumerate(kept):
                    b = int(rng.integers(1, self.window + 1))
                    window = (kept[max(0, pos - b):pos]
                              + kept[pos + 1:pos + b + 1])
                    if not window:
                        continue
                    row = np.zeros(width, np.int32)
                    m = np.zeros(width, np.float32)
                    row[:len(window)] = window
                    m[:len(window)] = 1.0
                    ctxs.append(row)
                    masks.append(m)
                    centers.append(center)
            return (np.asarray(ctxs, np.int32), np.asarray(masks, np.float32),
                    np.asarray(centers, np.int32))

        ctxs, masks, centers = samples()

        def batches():
            for sel in fixed_shape_batches(len(centers), self.batch_size, rng,
                                           what="CBOW samples"):
                negs = self.vocab.sample_negatives(rng, (len(sel), self.negative))
                yield ctxs[sel], masks[sel], centers[sel], negs.astype(np.int32)

        return self._model.train_epochs(
            batches, epochs=self.epochs, lr=self.learning_rate,
            lr_min=self.min_learning_rate, mode="cbow", mesh=self.mesh)

    # -- lookups (↔ WordVectors interface) ---------------------------------

    @property
    def vectors(self) -> np.ndarray:
        self._check_fit()
        return self._model.in_vecs

    def _check_fit(self):
        if self._model is None or self.vocab is None:
            raise RuntimeError("call fit() first")

    def has_word(self, w: str) -> bool:
        return self.vocab is not None and w in self.vocab

    def get_word_vector(self, w: str) -> np.ndarray:
        self._check_fit()
        return self._model.in_vecs[self.vocab.id_of(w)]

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        return float(va @ vb / (np.linalg.norm(va) * np.linalg.norm(vb) + 1e-12))

    def words_nearest(self, word_or_vec, top_n: int = 10) -> List[str]:
        """↔ WordVectors.wordsNearest (cosine)."""
        self._check_fit()
        if isinstance(word_or_vec, str):
            vec = self.get_word_vector(word_or_vec)
            exclude = {self.vocab.id_of(word_or_vec)}
        else:
            vec = np.asarray(word_or_vec)
            exclude = set()
        m = self._model.in_vecs
        sims = (m @ vec) / (np.linalg.norm(m, axis=1) * np.linalg.norm(vec) + 1e-12)
        order = np.argsort(-sims)
        out = []
        for i in order:
            if int(i) in exclude:
                continue
            out.append(self.vocab.word_of(int(i)))
            if len(out) == top_n:
                break
        return out

    def analogy(self, a: str, b: str, c: str, top_n: int = 1) -> List[str]:
        """a is to b as c is to ? (king - man + woman ≈ queen)."""
        v = (self.get_word_vector(b) - self.get_word_vector(a)
             + self.get_word_vector(c))
        cands = self.words_nearest(v, top_n + 3)
        skip = {a, b, c}
        return [w for w in cands if w not in skip][:top_n]
