"""NLP layer (↔ deeplearning4j-nlp-parent, SURVEY §2.7).

- tokenization: tokenizer factories + preprocessors
- vocab: vocabulary construction (min frequency, subsampling)
- word2vec: skip-gram / CBOW with negative sampling (jit'd SGNS steps)
- glove: co-occurrence factorization
- paragraph_vectors: PV-DBOW doc embeddings with inference
- serde: word-vector text format round-trip
"""

from deeplearning4j_tpu.nlp.tokenization import (
    CommonPreprocessor,
    DefaultTokenizerFactory,
    NGramTokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache, build_vocab
from deeplearning4j_tpu.nlp.wordpiece import (
    BasicTokenizer,
    BertWordPieceTokenizerFactory,
    WordPieceTokenizer,
    load_vocab,
)
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.nlp.fasttext import FastText, char_ngrams
from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.nlp.serde import load_word_vectors, save_word_vectors

__all__ = [
    "FastText", "char_ngrams",
    "DefaultTokenizerFactory",
    "NGramTokenizerFactory",
    "CommonPreprocessor",
    "BasicTokenizer", "WordPieceTokenizer", "BertWordPieceTokenizerFactory",
    "load_vocab",
    "VocabCache",
    "build_vocab",
    "Word2Vec",
    "Glove",
    "ParagraphVectors",
    "save_word_vectors",
    "load_word_vectors",
]
