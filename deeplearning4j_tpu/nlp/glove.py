"""GloVe (↔ org.deeplearning4j.models.glove.Glove).

Host-side co-occurrence accumulation (symmetric window, 1/d weighting),
then jit'd weighted-least-squares factorization steps over shuffled
(i, j, X_ij) triples with the standard f(x) = (x/x_max)^α weighting. The
reference runs per-pair AdaGrad updates on the JVM; here each batch of
triples is one compiled XLA step with AdaGrad state carried in the pytree.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Callable, Iterable, List, Optional

import numpy as np

from deeplearning4j_tpu.nlp.tokenization import (
    CommonPreprocessor,
    DefaultTokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import (
    VocabCache,
    build_vocab,
    fixed_shape_batches,
)


class Glove:
    def __init__(self, *, vector_size: int = 100, window: int = 5,
                 min_word_frequency: int = 5, x_max: float = 100.0,
                 alpha: float = 0.75, learning_rate: float = 0.05,
                 epochs: int = 5, batch_size: int = 4096, seed: int = 0,
                 tokenizer: Optional[Callable] = None, mesh=None):
        self.vector_size = vector_size
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.x_max = x_max
        self.alpha = alpha
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.mesh = mesh  # P5: shard tables over the mesh 'model' axis
        self.tokenizer = tokenizer or DefaultTokenizerFactory(CommonPreprocessor())
        self.vocab: Optional[VocabCache] = None
        self.vectors: Optional[np.ndarray] = None

    def _cooccurrences(self, encoded: List[List[int]]):
        counts: defaultdict = defaultdict(float)
        for ids in encoded:
            for pos, center in enumerate(ids):
                lo = max(0, pos - self.window)
                for j in range(lo, pos):
                    d = pos - j
                    counts[(center, ids[j])] += 1.0 / d
                    counts[(ids[j], center)] += 1.0 / d
        keys = np.asarray(list(counts.keys()), np.int32).reshape(-1, 2)
        vals = np.asarray(list(counts.values()), np.float32)
        return keys, vals

    def fit(self, corpus: Iterable) -> List[float]:
        import jax
        import jax.numpy as jnp

        sentences = [self.tokenizer(s) if isinstance(s, str) else list(s)
                     for s in corpus]
        self.vocab = build_vocab(sentences,
                                 min_word_frequency=self.min_word_frequency)
        encoded = [self.vocab.encode(s) for s in sentences]
        keys, vals = self._cooccurrences(encoded)
        n, d = len(self.vocab), self.vector_size
        rs = np.random.RandomState(self.seed)
        params = {
            "w": ((rs.rand(n, d) - 0.5) / d).astype(np.float32),
            "wc": ((rs.rand(n, d) - 0.5) / d).astype(np.float32),
            "b": np.zeros((n,), np.float32),
            "bc": np.zeros((n,), np.float32),
        }
        adagrad = jax.tree_util.tree_map(
            lambda p: np.full_like(p, 1e-8), params)
        x_max, alpha, lr = self.x_max, self.alpha, self.learning_rate

        def loss_fn(p, ii, jj, x):
            dot = jnp.sum(p["w"][ii] * p["wc"][jj], -1) + p["b"][ii] + p["bc"][jj]
            f = jnp.minimum((x / x_max) ** alpha, 1.0)
            return jnp.sum(f * jnp.square(dot - jnp.log(x)))

        def step(p, g2, ii, jj, x):
            loss, grads = jax.value_and_grad(loss_fn)(p, ii, jj, x)
            g2 = jax.tree_util.tree_map(lambda a, g: a + g * g, g2, grads)
            p = jax.tree_util.tree_map(
                lambda a, g, acc: a - lr * g / jnp.sqrt(acc), p, grads, g2)
            return p, g2, loss

        if self.mesh is not None:
            # P5 role: all four tables are vocab-major → row-shard them on
            # the mesh 'model' axis (replicate if vocab doesn't divide it).
            from deeplearning4j_tpu.nlp.sharding import replicated, row_sharding

            mesh = self.mesh
            rep = replicated(mesh)
            p_sh = jax.tree_util.tree_map(
                lambda a: row_sharding(mesh, a.shape), params)
            jit_step = jax.jit(
                step, donate_argnums=(0, 1),
                in_shardings=(p_sh, p_sh, rep, rep, rep),
                out_shardings=(p_sh, p_sh, rep))
            params = jax.tree_util.tree_map(
                lambda a, s: jax.device_put(np.asarray(a), s), params, p_sh)
            adagrad = jax.tree_util.tree_map(
                lambda a, s: jax.device_put(np.asarray(a), s), adagrad, p_sh)
        else:
            jit_step = jax.jit(step, donate_argnums=(0, 1))
        p = jax.tree_util.tree_map(jnp.asarray, params)
        g2 = jax.tree_util.tree_map(jnp.asarray, adagrad)
        rng = np.random.default_rng(self.seed)
        history = []
        for _ in range(self.epochs):
            losses = []
            for sel in fixed_shape_batches(len(vals), self.batch_size, rng,
                                           what="co-occurrence pairs"):
                p, g2, loss = jit_step(
                    p, g2, jnp.asarray(keys[sel, 0]), jnp.asarray(keys[sel, 1]),
                    jnp.asarray(vals[sel]))
                losses.append(loss)
            history.append(float(np.mean(jax.device_get(losses))))
        final = jax.device_get(p)
        # standard GloVe: final word vector = w + wc
        self.vectors = np.asarray(final["w"]) + np.asarray(final["wc"])
        return history

    def get_word_vector(self, w: str) -> np.ndarray:
        if self.vectors is None:
            raise RuntimeError("call fit() first")
        return self.vectors[self.vocab.id_of(w)]

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        return float(va @ vb / (np.linalg.norm(va) * np.linalg.norm(vb) + 1e-12))
