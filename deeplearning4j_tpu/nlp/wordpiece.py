"""BERT WordPiece tokenization (↔ deeplearning4j-nlp's
BertWordPieceTokenizerFactory / BertWordPiecePreProcessor, SURVEY §2.7
NLP row — the tokenizer the reference pairs with its BERT import path).

Pipeline matches the original BERT reference implementation (and
HuggingFace's BertTokenizer, which tests use as the oracle):

1. ``BasicTokenizer`` — unicode clean-up, whitespace split, optional
   lower-casing + accent stripping (NFD), punctuation split, CJK
   character isolation;
2. ``WordPieceTokenizer`` — greedy longest-match-first against the
   vocab, ``##`` continuation prefix, ``[UNK]`` for words that cannot
   be composed or exceed ``max_input_chars_per_word``.

``BertWordPieceTokenizerFactory.encode`` assembles the model-ready
[CLS]/[SEP] pair encoding (token_ids/segment_ids/mask, fixed max_len,
static shapes) that ``models.bert`` consumes directly.
"""

from __future__ import annotations

import unicodedata
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple


def load_vocab(path) -> Dict[str, int]:
    """One token per line (the standard vocab.txt format)."""
    out: Dict[str, int] = {}
    for i, line in enumerate(Path(path).read_text(
            encoding="utf-8").splitlines()):
        tok = line.rstrip("\n")
        if tok:
            out[tok] = i
    return out


def _is_whitespace(ch: str) -> bool:
    return ch in " \t\n\r" or unicodedata.category(ch) == "Zs"


def _is_control(ch: str) -> bool:
    if ch in "\t\n\r":
        return False
    return unicodedata.category(ch).startswith("C")


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    # ASCII ranges BERT treats as punctuation even where unicode doesn't
    # (e.g. $, +, ~, `)
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp: int) -> bool:
    return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F
            or 0x2B740 <= cp <= 0x2B81F or 0x2B820 <= cp <= 0x2CEAF
            or 0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F)


class BasicTokenizer:
    """Whitespace/punctuation/CJK pre-tokenizer (BERT reference rules).

    ``never_split``: whitespace-delimited tokens passed through verbatim —
    no lower-casing or punctuation split (how [MASK]/[SEP] markers embedded
    in raw text survive, matching HF's never_split/all_special_tokens)."""

    def __init__(self, lower_case: bool = True,
                 never_split: Optional[Sequence[str]] = None):
        self.lower_case = lower_case
        self.never_split = frozenset(never_split or ())

    def tokenize(self, text: str) -> List[str]:
        cleaned = []
        for ch in text:
            cp = ord(ch)
            if cp == 0 or cp == 0xFFFD or _is_control(ch):
                continue
            if _is_cjk(cp):
                cleaned.extend((" ", ch, " "))
            elif _is_whitespace(ch):
                cleaned.append(" ")
            else:
                cleaned.append(ch)
        tokens = "".join(cleaned).split()
        out: List[str] = []
        for tok in tokens:
            if tok in self.never_split:
                out.append(tok)
                continue
            if self.lower_case:
                tok = tok.lower()
                tok = "".join(c for c in unicodedata.normalize("NFD", tok)
                              if unicodedata.category(c) != "Mn")
            out.extend(self._split_punct(tok))
        return out

    @staticmethod
    def _split_punct(tok: str) -> List[str]:
        pieces: List[List[str]] = [[]]
        for ch in tok:
            if _is_punctuation(ch):
                pieces.append([ch])
                pieces.append([])
            else:
                pieces[-1].append(ch)
        return ["".join(p) for p in pieces if p]


class WordPieceTokenizer:
    """Greedy longest-match-first subword split against a vocab."""

    def __init__(self, vocab: Dict[str, int], unk_token: str = "[UNK]",
                 max_input_chars_per_word: int = 200):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_input_chars_per_word = max_input_chars_per_word

    def tokenize(self, word: str) -> List[str]:
        if len(word) > self.max_input_chars_per_word:
            return [self.unk_token]
        out: List[str] = []
        start = 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = piece
                    break
                end -= 1
            if cur is None:
                return [self.unk_token]
            out.append(cur)
            start = end
        return out


class BertWordPieceTokenizerFactory:
    """↔ BertWordPieceTokenizerFactory: text → WordPiece tokens/ids, plus
    the [CLS]/[SEP] pair encoding models.bert consumes."""

    def __init__(self, vocab, *, lower_case: bool = True,
                 unk_token: str = "[UNK]", cls_token: str = "[CLS]",
                 sep_token: str = "[SEP]", pad_token: str = "[PAD]"):
        self.vocab: Dict[str, int] = (load_vocab(vocab)
                                      if not isinstance(vocab, dict)
                                      else dict(vocab))
        specials = (unk_token, cls_token, sep_token, pad_token, "[MASK]")
        self.basic = BasicTokenizer(lower_case=lower_case,
                                    never_split=specials)
        self.wordpiece = WordPieceTokenizer(self.vocab, unk_token)
        self.inv_vocab = {i: t for t, i in self.vocab.items()}
        self.cls_token, self.sep_token = cls_token, sep_token
        self.pad_token, self.unk_token = pad_token, unk_token

    def tokenize(self, text: str) -> List[str]:
        out: List[str] = []
        for word in self.basic.tokenize(text):
            if word in self.basic.never_split:
                out.append(word)
                continue
            out.extend(self.wordpiece.tokenize(word))
        return out

    def convert_tokens_to_ids(self, tokens: Sequence[str]) -> List[int]:
        unk = self.vocab[self.unk_token]
        return [self.vocab.get(t, unk) for t in tokens]

    def convert_ids_to_tokens(self, ids: Sequence[int]) -> List[str]:
        return [self.inv_vocab.get(int(i), self.unk_token) for i in ids]

    def decode(self, ids: Sequence[int], *,
               skip_special_tokens: bool = True) -> str:
        """ids → text: ``##`` continuations join their predecessor, other
        tokens space-separate (the standard WordPiece detokenizer; exact
        inverse only up to the lossy lower/accent/punct normalization)."""
        specials = {self.cls_token, self.sep_token, self.pad_token}
        out: List[str] = []
        for tok in self.convert_ids_to_tokens(ids):
            if skip_special_tokens and tok in specials:
                continue
            if tok.startswith("##") and out:
                out[-1] += tok[2:]
            else:
                out.append(tok)
        return " ".join(out)

    def encode(self, text_a: str, text_b: Optional[str] = None, *,
               max_len: int = 128) -> Dict[str, "np.ndarray"]:
        """[CLS] a [SEP] (b [SEP]) → fixed-length feature dict
        {token_ids, segment_ids, mask} (models.bert's batch convention;
        stack encodes along axis 0 for a batch)."""
        import numpy as np

        a = self.tokenize(text_a)
        b = self.tokenize(text_b) if text_b is not None else []
        # truncate longest-first to fit specials (BERT reference rule;
        # ties pop from the SECOND sequence, as HF truncate_sequences does)
        budget = max_len - (3 if b else 2)
        while len(a) + len(b) > budget:
            (a if len(a) > len(b) else b).pop()
        toks = [self.cls_token] + a + [self.sep_token]
        segs = [0] * len(toks)
        if b:
            toks += b + [self.sep_token]
            segs += [1] * (len(b) + 1)
        ids = self.convert_tokens_to_ids(toks)
        pad = max_len - len(ids)
        out = {
            "token_ids": np.asarray(
                ids + [self.vocab[self.pad_token]] * pad, np.int32),
            "segment_ids": np.asarray(segs + [0] * pad, np.int32),
            "mask": np.asarray([1.0] * len(ids) + [0.0] * pad, np.float32),
        }
        return out
