"""ParagraphVectors / doc2vec (↔ org.deeplearning4j.models.paragraphvectors
.ParagraphVectors).

PV-DBOW: a document vector predicts the words it contains — the exact SGNS
machinery of word2vec with doc ids as the "center" table (the reference
shares SequenceVectors plumbing the same way). ``infer_vector`` trains a
fresh doc row against frozen word vectors (the standard inference trick).
"""

from __future__ import annotations

from typing import Callable, Iterable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.nlp.tokenization import (
    CommonPreprocessor,
    DefaultTokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import build_vocab, fixed_shape_batches
from deeplearning4j_tpu.nlp.word2vec import _SGNSModel


class ParagraphVectors:
    def __init__(self, *, vector_size: int = 100, min_word_frequency: int = 1,
                 negative: int = 5, learning_rate: float = 0.025,
                 epochs: int = 10, batch_size: int = 2048, seed: int = 0,
                 tokenizer: Optional[Callable] = None):
        self.vector_size = vector_size
        self.min_word_frequency = min_word_frequency
        self.negative = negative
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.tokenizer = tokenizer or DefaultTokenizerFactory(CommonPreprocessor())
        self.vocab = None
        self.labels: List[str] = []
        self._model: Optional[_SGNSModel] = None

    def fit(self, documents: Iterable, labels: Optional[Sequence[str]] = None
            ) -> List[float]:
        docs = [self.tokenizer(d) if isinstance(d, str) else list(d)
                for d in documents]
        self.labels = list(labels) if labels is not None else [
            f"DOC_{i}" for i in range(len(docs))]
        if len(self.labels) != len(docs):
            raise ValueError("labels/documents length mismatch")
        self.vocab = build_vocab(docs, min_word_frequency=self.min_word_frequency)
        encoded = [self.vocab.encode(d) for d in docs]
        self._model = _SGNSModel(len(docs), len(self.vocab),
                                 self.vector_size, self.seed)
        rng = np.random.default_rng(self.seed)

        def batches():
            pairs = [(di, w) for di, ids in enumerate(encoded) for w in ids]
            arr = np.asarray(pairs, np.int32).reshape(-1, 2)
            for sel in fixed_shape_batches(len(arr), self.batch_size, rng,
                                           what="doc-word pairs"):
                chunk = arr[sel]
                negs = self.vocab.sample_negatives(rng, (len(sel), self.negative))
                yield chunk[:, 0], chunk[:, 1], negs.astype(np.int32)

        return self._model.train_epochs(
            batches, epochs=self.epochs, lr=self.learning_rate,
            lr_min=self.learning_rate * 0.01)

    def get_doc_vector(self, label: str) -> np.ndarray:
        if self._model is None:
            raise RuntimeError("call fit() first")
        return self._model.in_vecs[self.labels.index(label)]

    def infer_vector(self, text, *, steps: int = 50,
                     learning_rate: float = 0.05) -> np.ndarray:
        """Train a fresh doc vector against the frozen word table."""
        if self._model is None:
            raise RuntimeError("call fit() first")
        tokens = self.tokenizer(text) if isinstance(text, str) else list(text)
        ids = np.asarray(self.vocab.encode(tokens), np.int32)
        if len(ids) == 0:
            raise ValueError("no in-vocabulary tokens in text")
        rng = np.random.default_rng(self.seed)
        rs = np.random.RandomState(self.seed)
        vec = ((rs.rand(self.vector_size) - 0.5) / self.vector_size).astype(np.float32)
        out = self._model.out_vecs
        for _ in range(steps):
            negs = self.vocab.sample_negatives(rng, (len(ids), self.negative))
            v_o = out[ids]                       # [T, D]
            v_n = out[negs]                      # [T, K, D]
            pos = v_o @ vec                      # [T]
            neg = np.einsum("d,tkd->tk", vec, v_n)
            g_pos = 1.0 / (1.0 + np.exp(-pos)) - 1.0   # σ(pos) − 1
            g_neg = 1.0 / (1.0 + np.exp(-neg))         # σ(neg)
            grad = g_pos @ v_o + np.einsum("tk,tkd->d", g_neg, v_n)
            vec -= learning_rate * grad / len(ids)
        return vec

    def similarity_to_label(self, text, label: str) -> float:
        v = self.infer_vector(text)
        d = self.get_doc_vector(label)
        return float(v @ d / (np.linalg.norm(v) * np.linalg.norm(d) + 1e-12))

    def nearest_labels(self, text, top_n: int = 5) -> List[str]:
        v = self.infer_vector(text)
        m = self._model.in_vecs
        sims = (m @ v) / (np.linalg.norm(m, axis=1) * np.linalg.norm(v) + 1e-12)
        return [self.labels[i] for i in np.argsort(-sims)[:top_n]]
