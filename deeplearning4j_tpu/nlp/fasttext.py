"""FastText-style subword skip-gram embeddings.

ref: deeplearning4j-nlp org.deeplearning4j.models.fasttext.FastText (JNI
wrapper over facebook fastText in the reference; SURVEY §2.7 NLP row
"fastText-ish SequenceVectors") — word vectors composed from hashed
character-n-gram vectors, giving OOV lookup and morphology sharing.

TPU-first: same batched-SGNS shape as word2vec.py, but the center-word
vector is the masked MEAN of (word row + its n-gram bucket rows), all
gathered from one [1+vocab+buckets, D] table in a single jitted step —
jax.grad turns the gathers into scatter-adds and XLA fuses the whole
update. The reference's per-pair C++ loop becomes one device program.
"""

from __future__ import annotations

from typing import Callable, Iterable, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.nlp.tokenization import (
    CommonPreprocessor,
    DefaultTokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import (
    VocabCache,
    build_vocab,
    fixed_shape_batches,
)
from deeplearning4j_tpu.nlp.word2vec import _SGNSModel, _window_pairs


def _fnv1a(s: str) -> int:
    """32-bit FNV-1a over utf-8 bytes (the fastText n-gram hash)."""
    h = 2166136261
    for b in s.encode("utf-8"):
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h


def char_ngrams(word: str, minn: int, maxn: int) -> List[str]:
    """Boundary-marked character n-grams, excluding the full '<word>'."""
    w = f"<{word}>"
    out = []
    for n in range(minn, maxn + 1):
        for i in range(0, len(w) - n + 1):
            g = w[i:i + n]
            if g != w:
                out.append(g)
    return out


class FastText:
    """↔ org.deeplearning4j.models.fasttext.FastText (skip-gram mode).

    Usage::

        ft = FastText(vector_size=64, minn=3, maxn=5)
        ft.fit(sentences)
        ft.get_word_vector("unseenword")   # OOV via subwords
    """

    def __init__(self, *, vector_size: int = 100, window: int = 5,
                 min_word_frequency: int = 5, negative: int = 5,
                 subsample: float = 1e-3, learning_rate: float = 0.05,
                 min_learning_rate: float = 1e-4, epochs: int = 1,
                 batch_size: int = 2048, minn: int = 3, maxn: int = 6,
                 bucket: int = 200_000, max_ngrams: int = 24, seed: int = 0,
                 tokenizer: Optional[Callable] = None):
        self.vector_size = vector_size
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.negative = negative
        self.subsample = subsample
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.epochs = epochs
        self.batch_size = batch_size
        self.minn = minn
        self.maxn = maxn
        self.bucket = bucket
        self.max_ngrams = max_ngrams  # n-gram slots per word (padded/truncated)
        self.seed = seed
        self.tokenizer = tokenizer or DefaultTokenizerFactory(CommonPreprocessor())
        self.vocab: Optional[VocabCache] = None
        self._model: Optional[_SGNSModel] = None  # tables [1+V+bucket, D], [V, D]
        self._ngram_ids: Optional[np.ndarray] = None  # [vocab, 1+max_ngrams]
        self._ngram_mask: Optional[np.ndarray] = None

    # -- subword indexing --------------------------------------------------

    def _subword_row(self, word: str, word_id: Optional[int]):
        """Padded row of table indices for a word: [word_row?, ngram rows...].

        Table layout: row 0 = pad, rows 1..V = words, rows V+1.. = buckets.
        """
        width = 1 + self.max_ngrams
        ids = np.zeros((width,), np.int32)
        mask = np.zeros((width,), np.float32)
        k = 0
        if word_id is not None:
            ids[k], mask[k] = 1 + word_id, 1.0
            k += 1
        for g in char_ngrams(word, self.minn, self.maxn)[: width - k]:
            ids[k] = 1 + len(self.vocab) + _fnv1a(g) % self.bucket
            mask[k] = 1.0
            k += 1
        return ids, mask

    def _build_subword_table(self):
        v = len(self.vocab)
        self._ngram_ids = np.zeros((v, 1 + self.max_ngrams), np.int32)
        self._ngram_mask = np.zeros((v, 1 + self.max_ngrams), np.float32)
        for i, w in enumerate(self.vocab.words):
            self._ngram_ids[i], self._ngram_mask[i] = self._subword_row(w, i)

    # -- training ----------------------------------------------------------

    def _tokenize_corpus(self, corpus) -> List[List[str]]:
        return [self.tokenizer(it) if isinstance(it, str) else list(it)
                for it in corpus]

    def fit(self, corpus: Iterable) -> List[float]:
        """Train. The SGNS objective with a subword-composed center vector
        IS word2vec's CBOW loss shape (masked-mean gather → pos/neg dots),
        so training reuses _SGNSModel verbatim: batches are (ngram_ids,
        ngram_mask, context, negatives) in place of CBOW's (contexts, mask,
        center, negatives). That also inherits the mesh-shardable tables
        (P5 embedding sharding) and AdaGrad state persistence."""
        sentences = self._tokenize_corpus(corpus)
        self.vocab = build_vocab(
            sentences, min_word_frequency=self.min_word_frequency,
            subsample=self.subsample)
        if len(self.vocab) < 2:
            raise ValueError("vocabulary too small (check min_word_frequency)")
        self._build_subword_table()
        encoded = [self.vocab.encode(s) for s in sentences]
        encoded = [s for s in encoded if len(s) > 1]
        v = len(self.vocab)
        self._model = _SGNSModel(1 + v + self.bucket, v, self.vector_size,
                                 self.seed)
        self._model.in_vecs[0] = 0.0  # pad row (masked out everywhere)
        rng = np.random.default_rng(self.seed)

        def batches():
            pairs: List[Tuple[int, int]] = []
            for ids in encoded:
                pairs.extend(_window_pairs(ids, self.window, rng,
                                           self.vocab.keep_probs))
            arr = np.asarray(pairs, np.int32).reshape(-1, 2)
            for sel in fixed_shape_batches(len(arr), self.batch_size, rng,
                                           what="fastText pairs"):
                chunk = arr[sel]
                negs = self.vocab.sample_negatives(
                    rng, (len(sel), self.negative)).astype(np.int32)
                yield (self._ngram_ids[chunk[:, 0]],
                       self._ngram_mask[chunk[:, 0]], chunk[:, 1], negs)

        history = self._model.train_epochs(
            batches, epochs=self.epochs, lr=self.learning_rate,
            lr_min=self.min_learning_rate, mode="cbow")
        self._vocab_mat = None  # invalidate words_nearest cache
        return history

    @property
    def in_vecs(self) -> Optional[np.ndarray]:
        return self._model.in_vecs if self._model is not None else None

    @property
    def out_vecs(self) -> Optional[np.ndarray]:
        return self._model.out_vecs if self._model is not None else None

    # -- lookups (↔ WordVectors interface; OOV supported) ------------------

    def _check_fit(self):
        if self.in_vecs is None or self.vocab is None:
            raise RuntimeError("call fit() first")

    def has_word(self, w: str) -> bool:
        return self.vocab is not None and w in self.vocab

    def get_word_vector(self, w: str) -> np.ndarray:
        """In-vocab: mean of word row + its n-gram rows. OOV: mean of the
        n-gram rows alone (the fastText OOV story)."""
        self._check_fit()
        if w in self.vocab:
            i = self.vocab.id_of(w)
            ids, mask = self._ngram_ids[i], self._ngram_mask[i]
        else:
            ids, mask = self._subword_row(w, None)
        n = max(float(mask.sum()), 1.0)
        return (self.in_vecs[ids] * mask[:, None]).sum(0) / n

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        return float(va @ vb /
                     (np.linalg.norm(va) * np.linalg.norm(vb) + 1e-12))

    def _vocab_matrix(self) -> np.ndarray:
        """[V, D] subword-composed vector per vocab word — one vectorized
        gather over the precomputed ngram tables, cached after fit."""
        if getattr(self, "_vocab_mat", None) is None:
            num = (self.in_vecs[self._ngram_ids]
                   * self._ngram_mask[..., None]).sum(1)
            den = np.maximum(self._ngram_mask.sum(1, keepdims=True), 1.0)
            self._vocab_mat = num / den
        return self._vocab_mat

    def words_nearest(self, word_or_vec, top_n: int = 10) -> List[str]:
        self._check_fit()
        if isinstance(word_or_vec, str):
            query = self.get_word_vector(word_or_vec)
            exclude = {word_or_vec}
        else:
            query = np.asarray(word_or_vec, np.float32)
            exclude = set()
        mat = self._vocab_matrix()
        norms = np.linalg.norm(mat, axis=1) * (np.linalg.norm(query) + 1e-12)
        sims = mat @ query / np.maximum(norms, 1e-12)
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.vocab.word_of(int(i))
            if w not in exclude:
                out.append(w)
            if len(out) == top_n:
                break
        return out
