"""Vocabulary cache (↔ org.deeplearning4j.models.word2vec.wordstore.VocabCache
/ AbstractCache + VocabConstructor).

Counts, min-frequency pruning, index assignment by descending frequency,
subsampling probabilities (Mikolov 2013 eq.), and the unigram^0.75 negative-
sampling table — all host-side numpy; the device only ever sees index
arrays.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np


class VocabCache:
    def __init__(self, words: List[str], counts: np.ndarray, total: int,
                 subsample: float = 0.0):
        self.words = words
        self.counts = counts
        self.total = int(total)
        self.index: Dict[str, int] = {w: i for i, w in enumerate(words)}
        # negative-sampling distribution ∝ count^0.75
        p = counts.astype(np.float64) ** 0.75
        self.neg_probs = p / p.sum()
        # subsampling keep-probability per word (1.0 when disabled)
        if subsample > 0:
            f = counts / max(total, 1)
            keep = (np.sqrt(f / subsample) + 1) * (subsample / np.maximum(f, 1e-12))
            self.keep_probs = np.minimum(keep, 1.0)
        else:
            self.keep_probs = np.ones(len(words))

    def __len__(self):
        return len(self.words)

    def __contains__(self, w):
        return w in self.index

    def id_of(self, w: str) -> int:
        return self.index[w]

    def word_of(self, i: int) -> str:
        return self.words[i]

    def encode(self, tokens: Sequence[str]) -> List[int]:
        return [self.index[t] for t in tokens if t in self.index]

    def sample_negatives(self, rng: np.random.Generator, shape) -> np.ndarray:
        return rng.choice(len(self.words), size=shape, p=self.neg_probs)


def fixed_shape_batches(n_items: int, batch_size: int,
                        rng: Optional[np.random.Generator] = None,
                        what: str = "training items"):
    """Yield index arrays of ONE fixed length (pad-by-wrapping the tail) so
    every device step reuses a single XLA compilation. Shared by the
    word2vec/glove/doc2vec trainers. Raises a clear error on empty input
    (the corpus/pruning produced nothing to train on)."""
    if n_items <= 0:
        raise ValueError(
            f"no {what} to train on — corpus too small or pruned away "
            "(check min_word_frequency / subsample)")
    order = np.arange(n_items) if rng is None else rng.permutation(n_items)
    bs = min(batch_size, n_items)
    for i in range(max(n_items // bs, 1)):
        sel = order[i * bs:(i + 1) * bs]
        if len(sel) < bs:
            sel = np.concatenate([sel, order[:bs - len(sel)]])
        yield sel


def build_vocab(sentences: Iterable[Sequence[str]], *,
                min_word_frequency: int = 1,
                max_vocab_size: Optional[int] = None,
                subsample: float = 0.0) -> VocabCache:
    """↔ VocabConstructor.buildJointVocabulary."""
    counter: Counter = Counter()
    total = 0
    for sent in sentences:
        counter.update(sent)
        total += len(sent)
    items = [(w, c) for w, c in counter.items() if c >= min_word_frequency]
    items.sort(key=lambda wc: (-wc[1], wc[0]))
    if max_vocab_size is not None:
        items = items[:max_vocab_size]
    words = [w for w, _ in items]
    counts = np.asarray([c for _, c in items], np.int64)
    return VocabCache(words, counts, total, subsample)
