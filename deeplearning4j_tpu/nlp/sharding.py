"""Shared P5 embedding-sharding policy (SURVEY §2.6): vocab-major tables
row-shard over the mesh 'model' axis; tables whose leading dim doesn't
divide the axis stay replicated (GSPMD would otherwise require padding).
Used by word2vec and glove — one definition so the fallback rule and any
future padded-sharding support stay in lockstep."""

from __future__ import annotations


def model_axis(mesh) -> str:
    return "model" if "model" in mesh.axis_names else mesh.axis_names[0]


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def row_sharding(mesh, shape):
    """NamedSharding for one vocab-major array of ``shape``."""
    from jax.sharding import NamedSharding, PartitionSpec

    axis = model_axis(mesh)
    if shape[0] % mesh.shape[axis] != 0:
        return replicated(mesh)
    spec = (axis,) + (None,) * (len(shape) - 1)
    return NamedSharding(mesh, PartitionSpec(*spec))
