"""Word-vector serialization (↔ org.deeplearning4j.models.embeddings.loader
.WordVectorSerializer): the standard word2vec text format — header line
"<n> <dim>", then one "<word> v1 v2 ..." line per word."""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np


def save_word_vectors(path, words: List[str], vectors: np.ndarray) -> None:
    vectors = np.asarray(vectors)
    if len(words) != vectors.shape[0]:
        raise ValueError("words/vectors length mismatch")
    with open(path, "w") as f:
        f.write(f"{len(words)} {vectors.shape[1]}\n")
        for w, v in zip(words, vectors):
            f.write(w + " " + " ".join(f"{x:.6g}" for x in v) + "\n")


def load_word_vectors(path) -> Tuple[List[str], np.ndarray]:
    with open(path) as f:
        first = f.readline().split()
        n, d = int(first[0]), int(first[1])
        words, rows = [], []
        for line in f:
            parts = line.rstrip("\n").split(" ")
            words.append(parts[0])
            rows.append([float(x) for x in parts[1:d + 1]])
    if len(words) != n:
        raise ValueError(f"header said {n} words, file has {len(words)}")
    return words, np.asarray(rows, np.float32)
