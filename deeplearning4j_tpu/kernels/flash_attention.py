"""Pallas TPU blockwise (flash) attention kernel.

ref: the reference's only attention is the O(T²)-memory libnd4j
``multi_head_dot_product_attention`` op behind SameDiff attention layers
(SURVEY §5.7) — it materializes the [T,S] score matrix in HBM. This kernel
is the TPU-native replacement: online-softmax tiling keeps only
[block_q, block_k] score tiles in VMEM, so memory is O(T·D) and the two
matmuls per tile run back-to-back on the MXU.

Grid: (batch*heads, q_blocks, kv_blocks), kv innermost so the running
max/denominator/accumulator for one q block live in VMEM scratch across the
kv sweep. Causal masking skips fully-masked kv blocks via ``pl.when``.
Per-example key padding masks ([B,S] 1/0 — the BERT attention-mask case)
are handled *inside* the kernel, so masked batches keep the flash path;
only arbitrary additive ``bias`` falls back to the XLA reference.

Backward: blockwise Pallas kernels (FlashAttention-2 style). The forward
saves the per-row logsumexp (lane-broadcast [BH,T,128] layout, the Mosaic
tiling-friendly shape jax's own TPU flash kernel uses); backward runs two
kernels — dk/dv with a q-block sweep per kv block, dq with a kv-block
sweep per q block — plus one XLA pass for delta = rowsum(dO*O). Scores are
recomputed on-chip, so backward memory stays O(T·D) like forward. The
same kernels run everywhere: compiled on TPU, interpret-mode in CPU tests
(via DL4J_TPU_FORCE_PALLAS=1; plain CPU callers never reach them because
flash_attention dispatches to reference_attention off-TPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from deeplearning4j_tpu.kernels._dispatch import on_tpu as _on_tpu
from deeplearning4j_tpu.kernels._dispatch import (
    flash_block_sizes as _flash_block_sizes,
    flash_min_seq as _flash_min_seq,
    force_pallas as _force_pallas,
    use_pallas as _use_pallas,
)

_NEG_INF = -1e30


def _matmul_dtype(dtype):
    """MXU input dtype for score/value matmuls.

    fp32 operands are cast to bf16 (fp32 accumulation via
    ``preferred_element_type`` is kept): a true-fp32 MXU matmul costs ~6
    passes, while XLA's einsum at its DEFAULT precision runs ONE bf16 pass —
    that asymmetry was most of the r3 kernels_ab 8x forward loss at T=512
    (the XLA reference was single-pass bf16, the kernel six-pass fp32).
    Matching XLA's default keeps the A/B apples-to-apples and the parity
    bound unchanged (both sides now carry bf16 matmul error).
    DL4J_TPU_FLASH_FP32=1 restores true-fp32 matmuls.

    Off-TPU (interpret-mode unit tests) the input dtype is kept: those
    tests pin kernel LOGIC against the fp32 XLA oracle at tight tolerance,
    and numpy emulation has no MXU whose precision policy needs matching.
    DL4J_TPU_FLASH_BF16=1 opts interpret mode into the cast path so the
    policy itself is testable on CPU.
    """
    import os

    if os.environ.get("DL4J_TPU_FLASH_FP32", "") == "1":
        return jnp.float32
    if not _on_tpu() and os.environ.get("DL4J_TPU_FLASH_BF16", "") != "1":
        return dtype
    return jnp.bfloat16 if dtype == jnp.float32 else dtype


def _compiler_params(*semantics):
    """Mosaic grid-dimension semantics (parallel dims enable multi-core
    partitioning on megacore chips and better pipelining); only meaningful
    when compiled for TPU — interpret mode ignores them."""
    if not (_HAS_PLTPU and _on_tpu()):
        return None
    return pltpu.CompilerParams(dimension_semantics=tuple(semantics))


def reference_attention(q, k, v, *, causal=False, bias=None, key_mask=None,
                        scale=None):
    """XLA O(T²) attention; q [B,H,T,D], k/v [B,H,S,D]. fp32 softmax.

    ``key_mask`` [B,S] 1/0 is folded into an additive bias. Fully-masked
    rows produce uniform attention (softmax of constant) — callers never
    read those outputs.
    """
    d = q.shape[-1]
    scale = (d ** -0.5) if scale is None else scale
    s = jnp.einsum("bhtd,bhsd->bhts", q, k).astype(jnp.float32) * scale
    if bias is not None:
        s = s + bias
    if key_mask is not None:
        s = s + jnp.where(key_mask[:, None, None, :] > 0, 0.0, _NEG_INF)
    if causal:
        t_len, s_len = s.shape[-2], s.shape[-1]
        idx_t = jnp.arange(t_len)[:, None]
        idx_s = jnp.arange(s_len)[None, :]
        s = jnp.where(idx_t + (s_len - t_len) >= idx_s, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhts,bhsd->bhtd", p, v)


def _flash_kernel(q_ref, k_ref, v_ref, km_ref, o_ref, lse_ref, m_scr, l_scr,
                  acc_scr, *,
                  scale, causal, has_mask, block_q, block_k, seq_q, seq_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: a kv block whose smallest key index exceeds the largest query
    # index is fully masked — skip its compute entirely.
    q_hi = (qi + 1) * block_q - 1 + (seq_k - seq_q)
    k_lo = ki * block_k
    run = (not causal) or (q_hi >= k_lo)

    @pl.when(run)
    def _compute():
        mm = _matmul_dtype(q_ref.dtype)
        q = q_ref[0].astype(mm)
        k = k_ref[0].astype(mm)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]
        # Mask key padding (seq_k tail + per-example mask) and the causal
        # triangle.
        key_idx = k_lo + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = key_idx < seq_k
        if has_mask:
            mask = mask & (km_ref[0] > 0)  # [1, bk] broadcasts over rows
        if causal:
            query_idx = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            mask = mask & (query_idx + (seq_k - seq_q) >= key_idx)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # Explicitly zero masked probabilities: in a fully-masked block
        # m_new stays _NEG_INF and exp(s - m_new) would be 1, not 0.
        p = jnp.exp(s - m_new) * mask.astype(jnp.float32)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_scr[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(mm), v_ref[0].astype(mm), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == n_k - 1)
    def _finish():
        # Fully-masked rows: l == 0 → output 0 (callers never read them).
        o_ref[0] = (
            acc_scr[:] / jnp.maximum(l_scr[:, :1], 1e-30)
        ).astype(o_ref.dtype)
        if lse_ref is not None:
            # Row logsumexp, lane-broadcast — the backward residual. Fully
            # masked / padded rows get ~-1e30; backward clamps before exp.
            lse_ref[0] = m_scr[:] + jnp.log(jnp.maximum(l_scr[:], 1e-30))


def _round_up(x, m):
    return -(-x // m) * m


def _pad_to(x, axis, multiple):
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _prep_blocks(q, k, v, key_mask, block_q, block_k):
    """Tile-align block sizes and pad operands — shared by fwd and bwd so
    their block geometry can never desynchronize."""
    b, h, t, d = q.shape
    s_len = k.shape[2]
    # Blocks stay (8,128)-tile-aligned even for short sequences.
    block_q = min(block_q, _round_up(t, 8))
    block_k = min(block_k, _round_up(s_len, 128))

    qp = _pad_to(_pad_to(q.reshape(b * h, t, d), 1, block_q), 2, 128)
    kp = _pad_to(_pad_to(k.reshape(b * h, s_len, d), 1, block_k), 2, 128)
    vp = _pad_to(_pad_to(v.reshape(b * h, s_len, d), 1, block_k), 2, 128)

    if key_mask is not None:
        km = _pad_to(key_mask.astype(jnp.float32), 1, block_k)  # [B, tk]
        # [B*H, 1, tk] — tiny; the unit middle dim keeps the Mosaic block
        # shape (1, 1, block_k) legal (second-minor equals the array dim).
        km = jnp.repeat(km, h, axis=0)[:, None, :]
        km_block = block_k
    else:
        km = jnp.ones((b * h, 1, 1), jnp.float32)  # placeholder operand
        km_block = 1
    return qp, kp, vp, km, km_block, block_q, block_k


def _flash_fwd(q, k, v, key_mask, *, causal, scale, block_q, block_k,
               save_lse=False):
    b, h, t, d = q.shape
    s_len = k.shape[2]
    qp, kp, vp, km, km_block, block_q, block_k = _prep_blocks(
        q, k, v, key_mask, block_q, block_k)
    dp = qp.shape[-1]
    tq, tk = qp.shape[1], kp.shape[1]
    has_mask = key_mask is not None

    params = dict(scale=scale, causal=causal, has_mask=has_mask,
                  block_q=block_q, block_k=block_k, seq_q=t, seq_k=s_len)
    if save_lse:
        kernel = functools.partial(_flash_kernel, **params)
        out_specs = [
            pl.BlockSpec((1, block_q, dp), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 128), lambda bh, qi, ki: (bh, qi, 0)),
        ]
        out_shape = [
            jax.ShapeDtypeStruct((b * h, tq, dp), q.dtype),
            jax.ShapeDtypeStruct((b * h, tq, 128), jnp.float32),
        ]
    else:
        def kernel(q_ref, k_ref, v_ref, km_ref, o_ref, m_scr, l_scr, acc_scr):
            return _flash_kernel(q_ref, k_ref, v_ref, km_ref, o_ref, None,
                                 m_scr, l_scr, acc_scr, **params)

        out_specs = pl.BlockSpec((1, block_q, dp),
                                 lambda bh, qi, ki: (bh, qi, 0))
        out_shape = jax.ShapeDtypeStruct((b * h, tq, dp), q.dtype)

    km_index = (lambda bh, qi, ki: (bh, 0, ki)) if has_mask else (
        lambda bh, qi, ki: (bh, 0, 0)
    )
    grid = (b * h, tq // block_q, tk // block_k)
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, dp), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, dp), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, dp), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, 1, km_block), km_index),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, dp), jnp.float32),
        ],
        compiler_params=_compiler_params("parallel", "parallel", "arbitrary"),
        interpret=not _on_tpu(),
    )(qp, kp, vp, km)
    out, lse = res if save_lse else (res, None)
    return out[:, :t, :d].reshape(b, h, t, d), lse


def _bwd_recompute(q_ref, k_ref, v_ref, km_ref, g_ref, lse_ref, delta_ref,
                   qi, ki, *, scale, causal, has_mask, block_q, block_k,
                   seq_q, seq_k):
    """Recompute p and ds for one (q-block, kv-block) pair — the math both
    backward kernels share. Returns (q, k, g, p, ds); matmul inputs in the
    MXU compute dtype (see _matmul_dtype), p/ds stats in fp32."""
    mm = _matmul_dtype(q_ref.dtype)
    q = q_ref[0].astype(mm)
    k = k_ref[0].astype(mm)
    v = v_ref[0].astype(mm)
    g = g_ref[0].astype(mm)
    # Clamp: padded / fully-masked rows carry lse ≈ -1e30; after the
    # query-validity mask below their scores are -1e30 too, so the
    # clamped difference underflows exp to exactly 0 (no inf·0 NaNs).
    lse = jnp.maximum(lse_ref[0][:, :1], -1e20)
    delta = delta_ref[0][:, :1]

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    k_lo = ki * block_k
    key_idx = k_lo + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    query_idx = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    mask = (key_idx < seq_k) & (query_idx < seq_q)
    if has_mask:
        mask = mask & (km_ref[0] > 0)
    if causal:
        mask = mask & (query_idx + (seq_k - seq_q) >= key_idx)
    s = jnp.where(mask, s, _NEG_INF)
    p = jnp.exp(s - lse)  # [bq, bk]; exactly 0 where masked
    dp = jax.lax.dot_general(
        g, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta) * scale
    # p/ds feed straight into MXU matmuls at the call sites — hand them
    # over in the compute dtype (fp32 accumulation happens there).
    return q, k, g, p.astype(mm), ds.astype(mm)


def _causal_block_live(qi, ki, *, causal, block_q, block_k, seq_q, seq_k):
    """False only for kv blocks entirely above the causal diagonal."""
    q_hi = (qi + 1) * block_q - 1 + (seq_k - seq_q)
    return (not causal) or (q_hi >= ki * block_k)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, km_ref, g_ref, lse_ref,
                          delta_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                          **params):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(_causal_block_live(qi, ki, **{k: params[k] for k in (
        "causal", "block_q", "block_k", "seq_q", "seq_k")}))
    def _compute():
        q, k, g, p, ds = _bwd_recompute(
            q_ref, k_ref, v_ref, km_ref, g_ref, lse_ref, delta_ref,
            qi, ki, **params)
        dv_scr[:] += jax.lax.dot_general(
            p, g, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(qi == n_q - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, km_ref, g_ref, lse_ref,
                         delta_ref, dq_ref, dq_scr, **params):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(_causal_block_live(qi, ki, **{k: params[k] for k in (
        "causal", "block_q", "block_k", "seq_q", "seq_k")}))
    def _compute():
        q, k, g, p, ds = _bwd_recompute(
            q_ref, k_ref, v_ref, km_ref, g_ref, lse_ref, delta_ref,
            qi, ki, **params)
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == n_k - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_impl(q, k, v, key_mask, out, lse, g, *, causal, scale,
                    block_q, block_k):
    """Blockwise backward; block geometry shared with fwd via _prep_blocks."""
    b, h, t, d = q.shape
    s_len = k.shape[2]
    qp, kp, vp, km, km_block, block_q, block_k = _prep_blocks(
        q, k, v, key_mask, block_q, block_k)
    gp = _pad_to(_pad_to(g.reshape(b * h, t, d), 1, block_q), 2, 128)
    dp = qp.shape[-1]
    tq, tk = qp.shape[1], kp.shape[1]
    has_mask = key_mask is not None

    delta = jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32), axis=-1)
    delta = _pad_to(delta.reshape(b * h, t), 1, block_q)
    delta = jnp.broadcast_to(delta[:, :, None], (b * h, tq, 128))

    common = dict(scale=scale, causal=causal, has_mask=has_mask,
                  block_q=block_q, block_k=block_k, seq_q=t, seq_k=s_len)
    n_q, n_k = tq // block_q, tk // block_k

    km_index_kq = (lambda bh, ki, qi: (bh, 0, ki)) if has_mask else (
        lambda bh, ki, qi: (bh, 0, 0)
    )
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **common),
        grid=(b * h, n_k, n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, dp), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, dp), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, dp), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, 1, km_block), km_index_kq),
            pl.BlockSpec((1, block_q, dp), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 128), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 128), lambda bh, ki, qi: (bh, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, dp), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, dp), lambda bh, ki, qi: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tk, dp), k.dtype),
            jax.ShapeDtypeStruct((b * h, tk, dp), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, dp), jnp.float32),
            pltpu.VMEM((block_k, dp), jnp.float32),
        ],
        compiler_params=_compiler_params("parallel", "parallel", "arbitrary"),
        interpret=not _on_tpu(),
    )(qp, kp, vp, km, gp, lse, delta)

    km_index_qk = (lambda bh, qi, ki: (bh, 0, ki)) if has_mask else (
        lambda bh, qi, ki: (bh, 0, 0)
    )
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **common),
        grid=(b * h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, dp), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, dp), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, dp), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, 1, km_block), km_index_qk),
            pl.BlockSpec((1, block_q, dp), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 128), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 128), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, dp), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, tq, dp), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, dp), jnp.float32)],
        compiler_params=_compiler_params("parallel", "parallel", "arbitrary"),
        interpret=not _on_tpu(),
    )(qp, kp, vp, km, gp, lse, delta)

    dq = dq[:, :t, :d].reshape(b, h, t, d)
    dk = dk[:, :s_len, :d].reshape(b, h, s_len, d)
    dv = dv[:, :s_len, :d].reshape(b, h, s_len, d)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, key_mask, causal, scale, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, key_mask, causal=causal, scale=scale,
                        block_q=block_q, block_k=block_k)
    return out


def _flash_vjp_fwd(q, k, v, key_mask, causal, scale, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, key_mask, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k, save_lse=True)
    return out, (q, k, v, key_mask, out, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, res, g):
    q, k, v, key_mask, out, lse = res
    dq, dk, dv = _flash_bwd_impl(
        q, k, v, key_mask, out, lse, g,
        causal=causal, scale=scale, block_q=block_q, block_k=block_k,
    )
    dkm = jnp.zeros_like(key_mask) if key_mask is not None else None
    return dq, dk, dv, dkm


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, *, causal: bool = False, scale=None, bias=None,
                    key_mask=None, block_q: int = None, block_k: int = None,
                    backend: str = None):
    """Blockwise attention; q [B,H,T,D], k/v [B,H,S,D] → [B,H,T,D].

    ``key_mask`` [B,S] 1/0 (padding mask) runs inside the kernel — the
    BERT path keeps the flash fast path. Arbitrary additive ``bias``
    forces the XLA fallback.

    ``backend``: None (auto), 'pallas', or 'xla'. Auto dispatch picks XLA's
    fused attention below ``_dispatch.flash_min_seq()`` keys — measured on
    v5e it wins there (kernels_ab 2026-07-30: fwd 8x at T=512) — and the
    Pallas kernel at long sequences where the O(T^2) score materialization
    pressures HBM. DL4J_TPU_FORCE_PALLAS=1 (kernel unit tests) still
    forces the kernel path.
    """
    d = q.shape[-1]
    scale = (d ** -0.5) if scale is None else scale
    if backend not in (None, "pallas", "xla"):
        raise ValueError(f"backend must be None|'pallas'|'xla', got {backend!r}")
    default_bq, default_bk = _flash_block_sizes()
    block_q = default_bq if block_q is None else block_q
    block_k = default_bk if block_k is None else block_k
    # Hard constraints on the kernel path regardless of request (off-TPU
    # without the force env, an explicit 'pallas' also falls back — the
    # compiled kernel only exists on TPU):
    if (bias is not None or q.shape[2] < 8 or not _HAS_PLTPU
            or not _use_pallas()):
        backend = "xla"
    elif backend is None:
        if _force_pallas() or k.shape[2] >= _flash_min_seq():
            backend = "pallas"
        else:
            backend = "xla"
    if backend == "xla":
        return reference_attention(q, k, v, causal=causal, bias=bias,
                                   key_mask=key_mask, scale=scale)
    return _flash(q, k, v, key_mask, causal, scale, block_q, block_k)
