"""Pallas TPU blockwise (flash) attention kernel.

ref: the reference's only attention is the O(T²)-memory libnd4j
``multi_head_dot_product_attention`` op behind SameDiff attention layers
(SURVEY §5.7) — it materializes the [T,S] score matrix in HBM. This kernel
is the TPU-native replacement: online-softmax tiling keeps only
[block_q, block_k] score tiles in VMEM, so memory is O(T·D) and the two
matmuls per tile run back-to-back on the MXU.

Grid: (batch*heads, q_blocks, kv_blocks), kv innermost so the running
max/denominator/accumulator for one q block live in VMEM scratch across the
kv sweep. Causal masking skips fully-masked kv blocks via ``pl.when``.
Per-example key padding masks ([B,S] 1/0 — the BERT attention-mask case)
are handled *inside* the kernel, so masked batches keep the flash path;
only arbitrary additive ``bias`` falls back to the XLA reference.

Backward: custom_vjp recomputing through the XLA reference implementation
(correct by construction; flash backward kernel is a later optimization —
same policy as kernels/lstm_scan.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from deeplearning4j_tpu.kernels._dispatch import on_tpu as _on_tpu
from deeplearning4j_tpu.kernels._dispatch import use_pallas as _use_pallas

_NEG_INF = -1e30


def reference_attention(q, k, v, *, causal=False, bias=None, key_mask=None,
                        scale=None):
    """XLA O(T²) attention; q [B,H,T,D], k/v [B,H,S,D]. fp32 softmax.

    ``key_mask`` [B,S] 1/0 is folded into an additive bias. Fully-masked
    rows produce uniform attention (softmax of constant) — callers never
    read those outputs.
    """
    d = q.shape[-1]
    scale = (d ** -0.5) if scale is None else scale
    s = jnp.einsum("bhtd,bhsd->bhts", q, k).astype(jnp.float32) * scale
    if bias is not None:
        s = s + bias
    if key_mask is not None:
        s = s + jnp.where(key_mask[:, None, None, :] > 0, 0.0, _NEG_INF)
    if causal:
        t_len, s_len = s.shape[-2], s.shape[-1]
        idx_t = jnp.arange(t_len)[:, None]
        idx_s = jnp.arange(s_len)[None, :]
        s = jnp.where(idx_t + (s_len - t_len) >= idx_s, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhts,bhsd->bhtd", p, v)


def _flash_kernel(q_ref, k_ref, v_ref, km_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  scale, causal, has_mask, block_q, block_k, seq_q, seq_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: a kv block whose smallest key index exceeds the largest query
    # index is fully masked — skip its compute entirely.
    q_hi = (qi + 1) * block_q - 1 + (seq_k - seq_q)
    k_lo = ki * block_k
    run = (not causal) or (q_hi >= k_lo)

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]
        # Mask key padding (seq_k tail + per-example mask) and the causal
        # triangle.
        key_idx = k_lo + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = key_idx < seq_k
        if has_mask:
            mask = mask & (km_ref[0] > 0)  # [1, bk] broadcasts over rows
        if causal:
            query_idx = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            mask = mask & (query_idx + (seq_k - seq_q) >= key_idx)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # Explicitly zero masked probabilities: in a fully-masked block
        # m_new stays _NEG_INF and exp(s - m_new) would be 1, not 0.
        p = jnp.exp(s - m_new) * mask.astype(jnp.float32)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_scr[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == n_k - 1)
    def _finish():
        # Fully-masked rows: l == 0 → output 0 (callers never read them).
        o_ref[0] = (
            acc_scr[:] / jnp.maximum(l_scr[:, :1], 1e-30)
        ).astype(o_ref.dtype)


def _round_up(x, m):
    return -(-x // m) * m


def _pad_to(x, axis, multiple):
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _flash_fwd(q, k, v, key_mask, *, causal, scale, block_q, block_k):
    b, h, t, d = q.shape
    s_len = k.shape[2]
    # Blocks stay (8,128)-tile-aligned even for short sequences.
    block_q = min(block_q, _round_up(t, 8))
    block_k = min(block_k, _round_up(s_len, 128))

    qp = _pad_to(_pad_to(q.reshape(b * h, t, d), 1, block_q), 2, 128)
    kp = _pad_to(_pad_to(k.reshape(b * h, s_len, d), 1, block_k), 2, 128)
    vp = _pad_to(_pad_to(v.reshape(b * h, s_len, d), 1, block_k), 2, 128)
    dp = qp.shape[-1]
    tq, tk = qp.shape[1], kp.shape[1]

    has_mask = key_mask is not None
    if has_mask:
        km = _pad_to(key_mask.astype(jnp.float32), 1, block_k)  # [B, tk]
        # [B*H, 1, tk] — tiny; the unit middle dim keeps the Mosaic block
        # shape (1, 1, block_k) legal (second-minor equals the array dim).
        km = jnp.repeat(km, h, axis=0)[:, None, :]
    else:
        km = jnp.ones((b * h, 1, 1), jnp.float32)  # placeholder operand

    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, has_mask=has_mask,
        block_q=block_q, block_k=block_k, seq_q=t, seq_k=s_len,
    )
    km_block = block_k if has_mask else 1
    km_index = (lambda bh, qi, ki: (bh, 0, ki)) if has_mask else (
        lambda bh, qi, ki: (bh, 0, 0)
    )
    grid = (b * h, tq // block_q, tk // block_k)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, dp), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, dp), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, dp), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, 1, km_block), km_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, dp), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, tq, dp), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, dp), jnp.float32),
        ],
        interpret=not _on_tpu(),
    )(qp, kp, vp, km)
    return out[:, :t, :d].reshape(b, h, t, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, key_mask, causal, scale, block_q, block_k):
    return _flash_fwd(q, k, v, key_mask, causal=causal, scale=scale,
                      block_q=block_q, block_k=block_k)


def _flash_vjp_fwd(q, k, v, key_mask, causal, scale, block_q, block_k):
    out = _flash(q, k, v, key_mask, causal, scale, block_q, block_k)
    return out, (q, k, v, key_mask)


def _flash_vjp_bwd(causal, scale, block_q, block_k, res, g):
    q, k, v, key_mask = res
    _, vjp = jax.vjp(
        lambda q, k, v: reference_attention(
            q, k, v, causal=causal, scale=scale, key_mask=key_mask
        ),
        q, k, v,
    )
    dq, dk, dv = vjp(g)
    dkm = jnp.zeros_like(key_mask) if key_mask is not None else None
    return dq, dk, dv, dkm


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, *, causal: bool = False, scale=None, bias=None,
                    key_mask=None, block_q: int = 256, block_k: int = 256):
    """Blockwise attention; q [B,H,T,D], k/v [B,H,S,D] → [B,H,T,D].

    ``key_mask`` [B,S] 1/0 (padding mask) runs inside the kernel — the
    BERT path keeps the flash fast path. Arbitrary additive ``bias``
    forces the XLA fallback.
    """
    d = q.shape[-1]
    scale = (d ** -0.5) if scale is None else scale
    if (bias is not None or q.shape[2] < 8 or not _HAS_PLTPU
            or not _use_pallas()):
        return reference_attention(q, k, v, causal=causal, bias=bias,
                                   key_mask=key_mask, scale=scale)
    return _flash(q, k, v, key_mask, causal, scale, block_q, block_k)
