"""Subpackage."""
