"""Pallas TPU GRU scan kernels (forward + backward).

ref: the cuDNN RNN platform helper covers GRU alongside LSTM (libnd4j
ops/declarable/platform/cudnn + DL4J CudnnLSTMHelper family); this is the
GRU half of the 'cuDNN RNN helper → Pallas scan' role that
kernels/lstm_scan.py fills for LSTM.

Same schedule as the LSTM kernel: grid=(T,), the recurrent weights [H,3H]
resident in VMEM for the whole sequence, ONE MXU matmul (h·RW) per step +
VPU gate math; the input projection x·W for all T steps is one large MXU
GEMM outside the kernel. Cell math matches ops/rnn.gru_cell exactly (gate
order r,z,n; candidate uses r ⊙ (h·RWn) — reset applied AFTER the
recurrent projection):

    r,z = σ(xp_rz + h·RW_rz + b_rz)
    n   = tanh(xp_n + r ⊙ (h·RW_n) + b_n)
    h'  = (1−z) ⊙ n + z ⊙ h

Backward: reversed-time dgrad sweep carrying dh in VMEM scratch and
streaming out dz̃ = [dr_pre, dz_pre, dn_pre] per step; ALL weight/bias
grads are large batched GEMMs/reductions over the saved tensors outside
the kernel (the dgrad-then-wgrad schedule that fixed the LSTM backward's
0.65x — see _make_bwd_kernel in lstm_scan.py). The one GRU-specific twist:
dh−1 needs [dr_pre, dz_pre, r ⊙ dn_pre] · RWᵀ, which is still a single
MXU dot per step.

Off-TPU the public ``gru`` routes to ops/rnn.py (kernels/_dispatch.py);
shapes that don't tile (N % 8, H % 128) also fall back.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend may be absent on CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from deeplearning4j_tpu.kernels._dispatch import on_tpu as _on_tpu
from deeplearning4j_tpu.kernels._dispatch import use_pallas as _use_pallas
from deeplearning4j_tpu.ops import rnn as opsrnn


def _make_fwd_kernel(save_ws: bool):
    """One timestep per grid index; h carried in VMEM scratch."""

    def kernel(*refs):
        xp_ref, rw_ref, b_ref, h0_ref = refs[0:4]
        outs = refs[4:]
        out_ref, hN_ref = outs[0:2]
        if save_ws:
            gates_ref, hpn_ref, h_scr = outs[2:]
        else:
            (h_scr,) = outs[2:]

        t = pl.program_id(0)
        n_t = pl.num_programs(0)

        @pl.when(t == 0)
        def _init():
            h_scr[:] = h0_ref[:]

        h = h_scr[:]
        H = h.shape[-1]

        hproj = jnp.dot(h, rw_ref[:], preferred_element_type=jnp.float32)
        xp = xp_ref[0]
        b = b_ref[0]  # [3H], broadcasts over the batch rows
        rz = jax.nn.sigmoid(xp[:, : 2 * H] + hproj[:, : 2 * H] + b[: 2 * H])
        r = rz[:, :H]
        z = rz[:, H:]
        hpn = hproj[:, 2 * H :]
        n = jnp.tanh(xp[:, 2 * H :] + r * hpn + b[2 * H :])
        h_new = (1.0 - z) * n + z * h

        h_scr[:] = h_new
        out_ref[0] = h_new.astype(out_ref.dtype)
        if save_ws:
            gates_ref[0] = jnp.concatenate([r, z, n], axis=1)
            hpn_ref[0] = hpn

        @pl.when(t == n_t - 1)
        def _final():
            hN_ref[:] = h_new.astype(hN_ref.dtype)

    return kernel


def _gru_pallas_fwd(x_proj_tm, rw, b, h0, save_workspace=False):
    """x_proj_tm: [T,N,3H] time-major.

    Returns (hs [T,N,H], hT) and, with ``save_workspace``, also the
    post-activation gates [T,N,3H] (r,z,n) and the candidate recurrent
    projection h·RW_n [T,N,H] (needed for dr in the backward sweep).
    """
    t_len, n, threeh = x_proj_tm.shape
    h_dim = threeh // 3
    dtype = x_proj_tm.dtype

    b2 = b.reshape(1, threeh).astype(jnp.float32)
    kernel = _make_fwd_kernel(save_workspace)

    in_specs = [
        pl.BlockSpec((1, n, threeh), lambda t: (t, 0, 0)),  # x_proj step t
        pl.BlockSpec((h_dim, threeh), lambda t: (0, 0)),    # RW resident
        pl.BlockSpec((1, threeh), lambda t: (0, 0)),        # bias
        pl.BlockSpec((n, h_dim), lambda t: (0, 0)),         # h0
    ]
    out_specs = [
        pl.BlockSpec((1, n, h_dim), lambda t: (t, 0, 0)),   # hs
        pl.BlockSpec((n, h_dim), lambda t: (0, 0)),         # hT
    ]
    out_shape = [
        jax.ShapeDtypeStruct((t_len, n, h_dim), dtype),
        jax.ShapeDtypeStruct((n, h_dim), dtype),
    ]
    if save_workspace:
        out_specs += [
            pl.BlockSpec((1, n, threeh), lambda t: (t, 0, 0)),  # gates
            pl.BlockSpec((1, n, h_dim), lambda t: (t, 0, 0)),   # h·RW_n
        ]
        out_shape += [
            jax.ShapeDtypeStruct((t_len, n, threeh), jnp.float32),
            jax.ShapeDtypeStruct((t_len, n, h_dim), jnp.float32),
        ]
    scratch = [pltpu.VMEM((n, h_dim), jnp.float32)]

    return pl.pallas_call(
        kernel,
        grid=(t_len,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=not _on_tpu(),
    )(
        x_proj_tm,
        rw.astype(jnp.float32),
        b2,
        h0.astype(jnp.float32),
    )


def _make_bwd_kernel():
    """Reversed-time dgrad step (grid index i processes t = T-1-i via the
    index maps in _gru_pallas_bwd).

    Streams out dz̃_t = [dr_pre, dz_pre, dn_pre] [N,3H]; the dh carry uses
    the rotated vector [dr_pre, dz_pre, r ⊙ dn_pre] · RWᵀ — one MXU dot.
    Weight/bias grads happen outside over the full dz̃ tensor.
    """

    def kernel(gates_ref, hpn_ref, hprev_ref, gh_ref, rw_ref, dxp_ref,
               dh_scr):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            dh_scr[:] = jnp.zeros_like(dh_scr)

        gates = gates_ref[0]
        H = gates.shape[-1] // 3
        r = gates[:, 0 * H : 1 * H]
        z = gates[:, 1 * H : 2 * H]
        n = gates[:, 2 * H : 3 * H]
        hpn = hpn_ref[0]
        h_prev = hprev_ref[0]

        dh_total = gh_ref[0] + dh_scr[:]
        dn = dh_total * (1.0 - z)
        dz = dh_total * (h_prev - n)
        dn_pre = dn * (1.0 - n * n)
        dr = dn_pre * hpn
        dr_pre = dr * r * (1.0 - r)
        dz_pre = dz * z * (1.0 - z)

        dxp_ref[0] = jnp.concatenate([dr_pre, dz_pre, dn_pre], axis=1)
        # dh_{t-1}: direct path + the three recurrent-matmul paths in one
        # dot (the n-gate path carries r ⊙ dn_pre, not dn_pre).
        rot = jnp.concatenate([dr_pre, dz_pre, r * dn_pre], axis=1)
        dh_scr[:] = dh_total * z + jax.lax.dot_general(
            rot, rw_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    return kernel


def _gru_pallas_bwd(gates_tm, hpn_tm, h_prev_tm, gh_tm, rw):
    """Reversed-time dgrad sweep.

    gates_tm [T,N,3H] (r,z,n post-activation), hpn_tm [T,N,H] (h·RW_n),
    h_prev_tm [T,N,H], gh_tm [T,N,H] (upstream grad per step, final-state
    grad folded into the last step). Returns dz̃_tm [T,N,3H].
    """
    t_len, n, threeh = gates_tm.shape
    h_dim = threeh // 3

    rev = lambda i: (t_len - 1 - i, 0, 0)  # noqa: E731 - index map
    const2 = lambda i: (0, 0)  # noqa: E731

    in_specs = [
        pl.BlockSpec((1, n, threeh), rev),     # gates
        pl.BlockSpec((1, n, h_dim), rev),      # h·RW_n
        pl.BlockSpec((1, n, h_dim), rev),      # h_{t-1}
        pl.BlockSpec((1, n, h_dim), rev),      # dL/dh_t
        pl.BlockSpec((h_dim, threeh), const2),  # RW resident
    ]
    out_specs = pl.BlockSpec((1, n, threeh), rev)
    out_shape = jax.ShapeDtypeStruct((t_len, n, threeh), jnp.float32)
    scratch = [pltpu.VMEM((n, h_dim), jnp.float32)]

    return pl.pallas_call(
        _make_bwd_kernel(),
        grid=(t_len,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=not _on_tpu(),
    )(gates_tm, hpn_tm, h_prev_tm, gh_tm, rw.astype(jnp.float32))


def _shapes_tile(n: int, h: int) -> bool:
    return n % 8 == 0 and h % 128 == 0


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _gru_core(x, w_x, w_h, b):
    """Returns (outputs [N,T,H], h_T [N,H])."""
    return _gru_core_fwd_impl(x, w_x, w_h, b)[0]


def _gru_core_fwd_impl(x, w_x, w_h, b, save_workspace=False):
    n, t, _ = x.shape
    h_dim = w_h.shape[0]
    x_proj = jnp.einsum("nti,ih->nth", x, w_x)  # big MXU GEMM outside kernel
    xp_tm = jnp.swapaxes(x_proj, 0, 1).astype(jnp.float32)
    h0 = jnp.zeros((n, h_dim), jnp.float32)
    res = _gru_pallas_fwd(xp_tm, w_h, b, h0, save_workspace=save_workspace)
    hs, hT = res[0:2]
    primal = (jnp.swapaxes(hs, 0, 1).astype(x.dtype), hT)
    ws = (hs, res[2], res[3]) if save_workspace else None
    return primal, ws


def _gru_core_vjp_fwd(x, w_x, w_h, b):
    primal, ws = _gru_core_fwd_impl(x, w_x, w_h, b, save_workspace=True)
    hs_tm, gates_tm, hpn_tm = ws
    return primal, (x, w_x, w_h, b, hs_tm, gates_tm, hpn_tm)


def _gru_core_vjp_bwd(res, g):
    x, w_x, w_h, b, hs_tm, gates_tm, hpn_tm = res
    g_out, ghT = g
    t_len, n, h_dim = hs_tm.shape

    zeros_nh = jnp.zeros((1, n, h_dim), jnp.float32)
    h_prev_tm = jnp.concatenate([zeros_nh, hs_tm[:-1].astype(jnp.float32)], 0)

    gh_tm = jnp.swapaxes(g_out, 0, 1).astype(jnp.float32)
    gh_tm = gh_tm.at[-1].add(ghT.astype(jnp.float32))

    dxp_tm = _gru_pallas_bwd(gates_tm, hpn_tm, h_prev_tm, gh_tm, w_h)

    # Wgrad phase: large MXU GEMMs over the saved tensors. The recurrent
    # weight grad needs the ROTATED vector for its n-columns (the kernel
    # streams raw dn_pre; the candidate matmul consumed r ⊙ h·RW_n).
    r_tm = gates_tm[:, :, :h_dim]
    rot_tm = jnp.concatenate(
        [dxp_tm[:, :, : 2 * h_dim], r_tm * dxp_tm[:, :, 2 * h_dim :]], axis=2)
    drw = jnp.einsum("tnh,tnf->hf", h_prev_tm, rot_tm)
    db = jnp.sum(dxp_tm, axis=(0, 1))
    dx = jnp.einsum("tnh,ih->nti", dxp_tm, w_x.astype(jnp.float32))
    dw_x = jnp.einsum("nti,tnh->ih", x.astype(jnp.float32), dxp_tm)
    return (dx.astype(x.dtype), dw_x.astype(w_x.dtype),
            drw.astype(w_h.dtype), db.astype(b.dtype))


_gru_core.defvjp(_gru_core_vjp_fwd, _gru_core_vjp_bwd)


def gru(x, w_x, w_h, b=None, *, init_h=None):
    """Drop-in replacement for ops/rnn.gru using the Pallas kernels.

    Falls back to the XLA scan when shapes don't tile (N % 8, H % 128),
    when an initial state is supplied (kernel assumes zero init for the
    backward sweep), or off-TPU (kernels/_dispatch.py policy).
    """
    n, t, _ = x.shape
    h_dim = w_h.shape[0]
    if init_h is not None or not _shapes_tile(n, h_dim) or not _use_pallas():
        return opsrnn.gru(x, w_x, w_h, b, init_h=init_h)
    if b is None:
        b = jnp.zeros((3 * h_dim,), jnp.float32)
    outputs, h_t = _gru_core(x, w_x, w_h, b)
    return outputs, h_t
