"""Pallas TPU LSTM scan kernels (forward + backward).

ref: the cuDNN RNN platform helper (libnd4j
ops/declarable/platform/cudnn/lstmLayer.cu + DL4J CudnnLSTMHelper) —
benchmark config #3 'GravesLSTM cuDNN RNN helper → Pallas scan'.

Design: one `pallas_call` with grid=(T,). The recurrent weights [H,4H] and
the per-step carried state (h, c — VMEM scratch) stay resident on-chip for
the whole sequence; each grid step does ONE MXU matmul (h·RW) + VPU gate
math + a [N,4H] slice stream-in / [N,H] stream-out. The input projection
x·W for all timesteps is done OUTSIDE the kernel as one large MXU GEMM
(same schedule cuDNN uses).

Backward: a second Pallas kernel sweeping time REVERSED (index maps flip
t → T-1-t), carrying (dh, dc) in VMEM scratch and accumulating dRW/db/
dpeephole directly in constant-index output blocks that stay VMEM-resident
across the sweep — the cuDNN-style training path. The forward-under-AD
variant saves the post-activation gates and cell states ([T,N,4H]+[T,N,H],
the cuDNN training-workspace analogue) so backward needs no recompute; the
primal (inference) call skips those outputs.

Off-TPU the public ``lstm`` routes to ops/rnn.py (see kernels/_dispatch.py);
shapes that don't tile (N % 8, H % 128) also fall back.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend may be absent on CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from deeplearning4j_tpu.kernels._dispatch import on_tpu as _on_tpu
from deeplearning4j_tpu.kernels._dispatch import use_pallas as _use_pallas
from deeplearning4j_tpu.ops import rnn as opsrnn


def _make_fwd_kernel(peep: bool, save_ws: bool, forget_bias: float):
    """One timestep per grid index; state carried in VMEM scratch."""

    def kernel(*refs):
        xp_ref, rw_ref, b_ref = refs[0:3]
        i0 = 3
        if peep:
            pI_ref, pF_ref, pO_ref = refs[3:6]
            i0 = 6
        h0_ref, c0_ref = refs[i0], refs[i0 + 1]
        outs = refs[i0 + 2:]
        out_ref, hN_ref, cN_ref = outs[0:3]
        if save_ws:
            gates_ref, cs_ref = outs[3:5]
            h_scr, c_scr = outs[5:]
        else:
            h_scr, c_scr = outs[3:]

        t = pl.program_id(0)
        n_t = pl.num_programs(0)

        @pl.when(t == 0)
        def _init():
            h_scr[:] = h0_ref[:]
            c_scr[:] = c0_ref[:]

        h = h_scr[:]
        c_prev = c_scr[:]
        H = h.shape[-1]

        z = (
            xp_ref[0]
            + jnp.dot(h, rw_ref[:], preferred_element_type=jnp.float32)
            + b_ref[0]
        )
        zi = z[:, 0 * H : 1 * H]
        zf = z[:, 1 * H : 2 * H]
        zg = z[:, 2 * H : 3 * H]
        zo = z[:, 3 * H : 4 * H]
        if peep:
            zi = zi + pI_ref[0] * c_prev
            zf = zf + pF_ref[0] * c_prev
        i = jax.nn.sigmoid(zi)
        f = jax.nn.sigmoid(zf + forget_bias)
        g = jnp.tanh(zg)
        c = f * c_prev + i * g
        if peep:
            zo = zo + pO_ref[0] * c
        o = jax.nn.sigmoid(zo)
        h_new = o * jnp.tanh(c)

        h_scr[:] = h_new
        c_scr[:] = c
        out_ref[0] = h_new.astype(out_ref.dtype)
        if save_ws:
            gates_ref[0] = jnp.concatenate([i, f, g, o], axis=1)
            cs_ref[0] = c

        @pl.when(t == n_t - 1)
        def _final():
            hN_ref[:] = h_new.astype(hN_ref.dtype)
            cN_ref[:] = c.astype(cN_ref.dtype)

    return kernel


def _lstm_pallas_fwd(x_proj_tm, rw, b, h0, c0, peepholes, forget_bias,
                     save_workspace=False):
    """x_proj_tm: [T,N,4H] time-major.

    Returns (hs [T,N,H], hT, cT) and, with ``save_workspace``, also the
    post-activation gates [T,N,4H] and cell states [T,N,H].
    """
    t_len, n, fourh = x_proj_tm.shape
    h_dim = fourh // 4
    dtype = x_proj_tm.dtype

    b2 = b.reshape(1, fourh).astype(jnp.float32)
    peep = peepholes is not None
    peep_args = ()
    peep_specs = ()
    if peep:
        peep_args = tuple(p.reshape(1, h_dim).astype(jnp.float32) for p in peepholes)
        peep_specs = tuple(
            pl.BlockSpec((1, h_dim), lambda t: (0, 0)) for _ in range(3)
        )

    kernel = _make_fwd_kernel(peep, save_workspace, float(forget_bias))

    in_specs = [
        pl.BlockSpec((1, n, fourh), lambda t: (t, 0, 0)),  # x_proj step t
        pl.BlockSpec((h_dim, fourh), lambda t: (0, 0)),    # RW resident
        pl.BlockSpec((1, fourh), lambda t: (0, 0)),        # bias
        *peep_specs,
        pl.BlockSpec((n, h_dim), lambda t: (0, 0)),        # h0
        pl.BlockSpec((n, h_dim), lambda t: (0, 0)),        # c0
    ]
    out_specs = [
        pl.BlockSpec((1, n, h_dim), lambda t: (t, 0, 0)),  # hs
        pl.BlockSpec((n, h_dim), lambda t: (0, 0)),        # hT
        pl.BlockSpec((n, h_dim), lambda t: (0, 0)),        # cT
    ]
    out_shape = [
        jax.ShapeDtypeStruct((t_len, n, h_dim), dtype),
        jax.ShapeDtypeStruct((n, h_dim), dtype),
        jax.ShapeDtypeStruct((n, h_dim), dtype),
    ]
    if save_workspace:
        out_specs += [
            pl.BlockSpec((1, n, fourh), lambda t: (t, 0, 0)),  # gates
            pl.BlockSpec((1, n, h_dim), lambda t: (t, 0, 0)),  # cs
        ]
        out_shape += [
            jax.ShapeDtypeStruct((t_len, n, fourh), jnp.float32),
            jax.ShapeDtypeStruct((t_len, n, h_dim), jnp.float32),
        ]
    scratch = [
        pltpu.VMEM((n, h_dim), jnp.float32),
        pltpu.VMEM((n, h_dim), jnp.float32),
    ]

    return pl.pallas_call(
        kernel,
        grid=(t_len,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=not _on_tpu(),
    )(
        x_proj_tm,
        rw.astype(jnp.float32),
        b2,
        *peep_args,
        h0.astype(jnp.float32),
        c0.astype(jnp.float32),
    )


def _make_bwd_kernel(peep: bool):
    """Reversed-time step: grid index i processes t = T-1-i (the index
    maps in _lstm_pallas_bwd do the flip, so refs already hold step t).

    The sweep is dgrad-only (dz per step + the dh/dc carries): weight,
    bias and peephole grads are ONE large batched GEMM / reduction over
    the saved dz tensor OUTSIDE the kernel (the cuDNN dgrad-then-wgrad
    schedule). r3's kernel accumulated dRW/db per step inside the sweep —
    a tiny [H,N]x[N,4H] matmul plus a [H,4H] VMEM read-modify-write every
    timestep on the sequential critical path — and measured 0.65x XLA
    (BASELINE.md kernel A/B); hoisting the wgrad out removes that work
    from the recurrence entirely."""

    def kernel(*refs):
        (gates_ref, cs_ref, csp_ref, gh_ref, gcT_ref, rw_ref) = refs[0:6]
        i0 = 6
        if peep:
            pI_ref, pF_ref, pO_ref = refs[6:9]
            i0 = 9
        dxp_ref = refs[i0]
        dh_scr, dc_scr = refs[i0 + 1:]

        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            dh_scr[:] = jnp.zeros_like(dh_scr)
            dc_scr[:] = gcT_ref[:]

        gates = gates_ref[0]
        H = gates.shape[-1] // 4
        ig = gates[:, 0 * H : 1 * H]
        fg = gates[:, 1 * H : 2 * H]
        gg = gates[:, 2 * H : 3 * H]
        og = gates[:, 3 * H : 4 * H]
        c_t = cs_ref[0]
        c_prev = csp_ref[0]

        dh_total = gh_ref[0] + dh_scr[:]
        tanh_c = jnp.tanh(c_t)
        do = dh_total * tanh_c
        dzo = do * og * (1.0 - og)
        dc = dc_scr[:] + dh_total * og * (1.0 - tanh_c * tanh_c)
        if peep:
            dc = dc + dzo * pO_ref[0]
        di = dc * gg
        df = dc * c_prev
        dg = dc * ig
        dzi = di * ig * (1.0 - ig)
        dzf = df * fg * (1.0 - fg)
        dzg = dg * (1.0 - gg * gg)
        dc_next = dc * fg
        if peep:
            dc_next = dc_next + dzi * pI_ref[0] + dzf * pF_ref[0]

        dz = jnp.concatenate([dzi, dzf, dzg, dzo], axis=1)  # [N,4H]
        dxp_ref[0] = dz
        # dh_{t-1} through the recurrent matmul: dz · RWᵀ.
        dh_scr[:] = jax.lax.dot_general(
            dz, rw_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dc_scr[:] = dc_next

    return kernel


def _lstm_pallas_bwd(gates_tm, cs_tm, c_prev_tm, gh_tm, gcT, rw, peepholes):
    """Reversed-time dgrad sweep.

    gates_tm [T,N,4H], cs_tm/c_prev_tm [T,N,H], gh_tm [T,N,H] (upstream
    grad per step incl. the final-state grad folded into the last step),
    gcT [N,H]. Returns dxp_tm [T,N,4H]; weight/bias/peephole grads are
    computed from it outside (one big GEMM — see _make_bwd_kernel).
    """
    t_len, n, fourh = gates_tm.shape
    h_dim = fourh // 4
    peep = peepholes is not None

    rev = lambda i: (t_len - 1 - i, 0, 0)  # noqa: E731 - index map
    const2 = lambda i: (0, 0)  # noqa: E731

    peep_args = ()
    peep_in_specs = ()
    if peep:
        peep_args = tuple(p.reshape(1, h_dim).astype(jnp.float32) for p in peepholes)
        peep_in_specs = tuple(pl.BlockSpec((1, h_dim), const2) for _ in range(3))

    in_specs = [
        pl.BlockSpec((1, n, fourh), rev),   # gates
        pl.BlockSpec((1, n, h_dim), rev),   # c_t
        pl.BlockSpec((1, n, h_dim), rev),   # c_{t-1}
        pl.BlockSpec((1, n, h_dim), rev),   # dL/dh_t (upstream)
        pl.BlockSpec((n, h_dim), const2),   # dL/dc_T
        pl.BlockSpec((h_dim, fourh), const2),  # RW resident
        *peep_in_specs,
    ]
    out_specs = pl.BlockSpec((1, n, fourh), rev)   # dxp
    out_shape = jax.ShapeDtypeStruct((t_len, n, fourh), jnp.float32)
    scratch = [
        pltpu.VMEM((n, h_dim), jnp.float32),  # dh carry
        pltpu.VMEM((n, h_dim), jnp.float32),  # dc carry
    ]

    return pl.pallas_call(
        _make_bwd_kernel(peep),
        grid=(t_len,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=not _on_tpu(),
    )(
        gates_tm,
        cs_tm,
        c_prev_tm,
        gh_tm,
        gcT,
        rw.astype(jnp.float32),
        *peep_args,
    )


def _shapes_tile(n: int, h: int) -> bool:
    return n % 8 == 0 and (4 * h) % 128 == 0 and h % 128 == 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _lstm_core(x, w_x, w_h, b, peep_stack, forget_bias, has_peep):
    """peep_stack: [3,H] array when has_peep else zeros. Returns the triple
    (outputs [N,T,H], h_T [N,H], c_T [N,H])."""
    return _lstm_core_fwd_impl(x, w_x, w_h, b, peep_stack, forget_bias,
                               has_peep)[0]


def _lstm_core_fwd_impl(x, w_x, w_h, b, peep_stack, forget_bias, has_peep,
                        save_workspace=False):
    n, t, _ = x.shape
    h_dim = w_h.shape[0]
    x_proj = jnp.einsum("nti,ih->nth", x, w_x)  # big MXU GEMM outside kernel
    xp_tm = jnp.swapaxes(x_proj, 0, 1).astype(jnp.float32)
    h0 = jnp.zeros((n, h_dim), jnp.float32)
    c0 = jnp.zeros((n, h_dim), jnp.float32)
    peep = tuple(peep_stack) if has_peep else None
    res = _lstm_pallas_fwd(xp_tm, w_h, b, h0, c0, peep, forget_bias,
                           save_workspace=save_workspace)
    hs, hT, cT = res[0:3]
    primal = (jnp.swapaxes(hs, 0, 1).astype(x.dtype), hT, cT)
    ws = (hs, res[3], res[4]) if save_workspace else None
    return primal, ws


def _lstm_core_vjp_fwd(x, w_x, w_h, b, peep_stack, forget_bias, has_peep):
    primal, ws = _lstm_core_fwd_impl(
        x, w_x, w_h, b, peep_stack, forget_bias, has_peep,
        save_workspace=True,
    )
    hs_tm, gates_tm, cs_tm = ws
    return primal, (x, w_x, w_h, b, peep_stack, hs_tm, gates_tm, cs_tm)


def _lstm_core_vjp_bwd(forget_bias, has_peep, res, g):
    x, w_x, w_h, b, peep_stack, hs_tm, gates_tm, cs_tm = res
    g_out, ghT, gcT = g
    t_len, n, h_dim = hs_tm.shape

    zeros_nh = jnp.zeros((1, n, h_dim), jnp.float32)
    h_prev_tm = jnp.concatenate([zeros_nh, hs_tm[:-1].astype(jnp.float32)], 0)
    c_prev_tm = jnp.concatenate([zeros_nh, cs_tm[:-1]], 0)

    gh_tm = jnp.swapaxes(g_out, 0, 1).astype(jnp.float32)
    gh_tm = gh_tm.at[-1].add(ghT.astype(jnp.float32))

    peep = tuple(peep_stack) if has_peep else None
    dxp_tm = _lstm_pallas_bwd(
        gates_tm, cs_tm, c_prev_tm, gh_tm, gcT.astype(jnp.float32), w_h, peep,
    )

    # Wgrad phase: one large MXU GEMM / reduction each over the full dz
    # tensor (dgrad-then-wgrad — see _make_bwd_kernel docstring).
    drw = jnp.einsum("tnh,tnf->hf", h_prev_tm, dxp_tm)
    db = jnp.sum(dxp_tm, axis=(0, 1))
    dx = jnp.einsum("tnh,ih->nti", dxp_tm, w_x.astype(jnp.float32))
    dw_x = jnp.einsum("nti,tnh->ih", x.astype(jnp.float32), dxp_tm)
    if has_peep:
        h_dim_ = c_prev_tm.shape[-1]
        dzi = dxp_tm[:, :, 0 * h_dim_:1 * h_dim_]
        dzf = dxp_tm[:, :, 1 * h_dim_:2 * h_dim_]
        dzo = dxp_tm[:, :, 3 * h_dim_:4 * h_dim_]
        dpeep_stack = jnp.stack([
            jnp.sum(dzi * c_prev_tm, axis=(0, 1)),
            jnp.sum(dzf * c_prev_tm, axis=(0, 1)),
            jnp.sum(dzo * cs_tm, axis=(0, 1)),
        ])
    else:
        dpeep_stack = jnp.zeros_like(peep_stack)
    return (dx.astype(x.dtype), dw_x.astype(w_x.dtype), drw.astype(w_h.dtype),
            db.astype(b.dtype), dpeep_stack.astype(peep_stack.dtype))


_lstm_core.defvjp(_lstm_core_vjp_fwd, _lstm_core_vjp_bwd)


def lstm(
    x,
    w_x,
    w_h,
    b,
    *,
    peepholes=None,
    forget_bias: float = 0.0,
    init_state=None,
):
    """Drop-in replacement for ops/rnn.lstm using the Pallas kernels.

    Falls back to the XLA scan when shapes don't tile onto the TPU VPU/MXU
    (N % 8 != 0 or H % 128 != 0) or when an initial state is supplied
    (kernel currently assumes zero init for the backward sweep).
    """
    n, t, _ = x.shape
    h_dim = w_h.shape[0]
    if init_state is not None or not _shapes_tile(n, h_dim) or not _use_pallas():
        return opsrnn.lstm(
            x, w_x, w_h, b, peepholes=peepholes, forget_bias=forget_bias,
            init_state=init_state,
        )
    if peepholes is not None:
        peep_stack = jnp.stack(peepholes)
        has_peep = True
    else:
        peep_stack = jnp.zeros((3, h_dim), x.dtype)
        has_peep = False
    outputs, h_t, c_t = _lstm_core(x, w_x, w_h, b, peep_stack, float(forget_bias), has_peep)
    return outputs, opsrnn.LSTMState(h_t, c_t)
