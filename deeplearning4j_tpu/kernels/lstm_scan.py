"""Pallas TPU LSTM scan kernel.

ref: the cuDNN RNN platform helper (libnd4j
ops/declarable/platform/cudnn/lstmLayer.cu + DL4J CudnnLSTMHelper) —
benchmark config #3 'GravesLSTM cuDNN RNN helper → Pallas scan'.

Design: one `pallas_call` with grid=(T,). The recurrent weights [H,4H] and
the per-step carried state (h, c — VMEM scratch) stay resident on-chip for
the whole sequence; each grid step does ONE MXU matmul (h·RW) + VPU gate
math + a [N,4H] slice stream-in / [N,H] stream-out. The input projection
x·W for all timesteps is done OUTSIDE the kernel as one large MXU GEMM
(same schedule cuDNN uses).

Backward: a custom_vjp whose bwd recomputes via the XLA lax.scan
implementation (ops/rnn.py) and differentiates that — correct by
construction; a hand-written backward kernel is a later optimization.

Off-TPU the public ``lstm`` routes to ops/rnn.py (see kernels/_dispatch.py);
shapes that don't tile (N % 8, H % 128) also fall back.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend may be absent on CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from deeplearning4j_tpu.kernels._dispatch import on_tpu as _on_tpu
from deeplearning4j_tpu.kernels._dispatch import use_pallas as _use_pallas
from deeplearning4j_tpu.ops import rnn as opsrnn


def _gates_kernel(xp_ref, rw_ref, b_ref, h0_ref, c0_ref, out_ref,
                  hN_ref, cN_ref, h_scr, c_scr, *, forget_bias, peep):
    """One timestep per grid index; state carried in VMEM scratch."""
    t = pl.program_id(0)
    n_t = pl.num_programs(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    h = h_scr[:]
    c_prev = c_scr[:]
    H = h.shape[-1]

    z = (
        xp_ref[0]
        + jnp.dot(h, rw_ref[:], preferred_element_type=jnp.float32)
        + b_ref[0]
    )
    zi = z[:, 0 * H : 1 * H]
    zf = z[:, 1 * H : 2 * H]
    zg = z[:, 2 * H : 3 * H]
    zo = z[:, 3 * H : 4 * H]
    if peep:
        pI_ref, pF_ref, pO_ref = peep
        zi = zi + pI_ref[0] * c_prev
        zf = zf + pF_ref[0] * c_prev
    i = jax.nn.sigmoid(zi)
    f = jax.nn.sigmoid(zf + forget_bias)
    g = jnp.tanh(zg)
    c = f * c_prev + i * g
    if peep:
        zo = zo + pO_ref[0] * c
    o = jax.nn.sigmoid(zo)
    h_new = o * jnp.tanh(c)

    h_scr[:] = h_new
    c_scr[:] = c
    out_ref[0] = h_new.astype(out_ref.dtype)

    @pl.when(t == n_t - 1)
    def _final():
        hN_ref[:] = h_new.astype(hN_ref.dtype)
        cN_ref[:] = c.astype(cN_ref.dtype)


def _lstm_pallas_fwd(x_proj_tm, rw, b, h0, c0, peepholes, forget_bias):
    """x_proj_tm: [T,N,4H] time-major; returns (hs [T,N,H], (hT, cT))."""
    t_len, n, fourh = x_proj_tm.shape
    h_dim = fourh // 4
    dtype = x_proj_tm.dtype

    b2 = b.reshape(1, fourh).astype(jnp.float32)
    peep = peepholes is not None
    peep_args = ()
    peep_specs = ()
    if peep:
        peep_args = tuple(p.reshape(1, h_dim).astype(jnp.float32) for p in peepholes)
        peep_specs = tuple(
            pl.BlockSpec((1, h_dim), lambda t: (0, 0)) for _ in range(3)
        )

    # Kernel signature depends on whether peephole refs are present.
    if peep:
        def kernel(xp_ref, rw_ref, b_ref, pI_ref, pF_ref, pO_ref, h0_ref, c0_ref,
                   out_ref, hN_ref, cN_ref, h_scr, c_scr):
            return _gates_kernel(
                xp_ref, rw_ref, b_ref, h0_ref, c0_ref, out_ref, hN_ref, cN_ref,
                h_scr, c_scr, forget_bias=float(forget_bias),
                peep=(pI_ref, pF_ref, pO_ref),
            )
    else:
        def kernel(xp_ref, rw_ref, b_ref, h0_ref, c0_ref,
                   out_ref, hN_ref, cN_ref, h_scr, c_scr):
            return _gates_kernel(
                xp_ref, rw_ref, b_ref, h0_ref, c0_ref, out_ref, hN_ref, cN_ref,
                h_scr, c_scr, forget_bias=float(forget_bias), peep=None,
            )

    in_specs = [
        pl.BlockSpec((1, n, fourh), lambda t: (t, 0, 0)),  # x_proj step t
        pl.BlockSpec((h_dim, fourh), lambda t: (0, 0)),    # RW resident
        pl.BlockSpec((1, fourh), lambda t: (0, 0)),        # bias
        *peep_specs,
        pl.BlockSpec((n, h_dim), lambda t: (0, 0)),        # h0
        pl.BlockSpec((n, h_dim), lambda t: (0, 0)),        # c0
    ]
    out_specs = [
        pl.BlockSpec((1, n, h_dim), lambda t: (t, 0, 0)),  # hs
        pl.BlockSpec((n, h_dim), lambda t: (0, 0)),        # hT
        pl.BlockSpec((n, h_dim), lambda t: (0, 0)),        # cT
    ]
    scratch = [
        pltpu.VMEM((n, h_dim), jnp.float32),
        pltpu.VMEM((n, h_dim), jnp.float32),
    ]

    hs, hT, cT = pl.pallas_call(
        kernel,
        grid=(t_len,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=[
            jax.ShapeDtypeStruct((t_len, n, h_dim), dtype),
            jax.ShapeDtypeStruct((n, h_dim), dtype),
            jax.ShapeDtypeStruct((n, h_dim), dtype),
        ],
        scratch_shapes=scratch,
        interpret=not _on_tpu(),
    )(
        x_proj_tm,
        rw.astype(jnp.float32),
        b2,
        *peep_args,
        h0.astype(jnp.float32),
        c0.astype(jnp.float32),
    )
    return hs, hT, cT


def _shapes_tile(n: int, h: int) -> bool:
    return n % 8 == 0 and (4 * h) % 128 == 0 and h % 128 == 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _lstm_core(x, w_x, w_h, b, peep_stack, forget_bias, has_peep):
    """peep_stack: [3,H] array when has_peep else zeros. Returns the triple
    (outputs [N,T,H], h_T [N,H], c_T [N,H])."""
    return _lstm_core_fwd_impl(x, w_x, w_h, b, peep_stack, forget_bias, has_peep)


def _lstm_core_fwd_impl(x, w_x, w_h, b, peep_stack, forget_bias, has_peep):
    n, t, _ = x.shape
    h_dim = w_h.shape[0]
    x_proj = jnp.einsum("nti,ih->nth", x, w_x)  # big MXU GEMM outside kernel
    xp_tm = jnp.swapaxes(x_proj, 0, 1).astype(jnp.float32)
    h0 = jnp.zeros((n, h_dim), jnp.float32)
    c0 = jnp.zeros((n, h_dim), jnp.float32)
    peep = tuple(peep_stack) if has_peep else None
    hs, hT, cT = _lstm_pallas_fwd(xp_tm, w_h, b, h0, c0, peep, forget_bias)
    return jnp.swapaxes(hs, 0, 1).astype(x.dtype), hT, cT


def _lstm_core_vjp_fwd(x, w_x, w_h, b, peep_stack, forget_bias, has_peep):
    out = _lstm_core(x, w_x, w_h, b, peep_stack, forget_bias, has_peep)
    return out, (x, w_x, w_h, b, peep_stack)


def _lstm_core_vjp_bwd(forget_bias, has_peep, res, g):
    x, w_x, w_h, b, peep_stack = res

    def ref_impl(x, w_x, w_h, b, peep_stack):
        peep = tuple(peep_stack) if has_peep else None
        out, final = opsrnn.lstm(x, w_x, w_h, b, peepholes=peep, forget_bias=forget_bias)
        return out, final.h, final.c

    _, vjp = jax.vjp(ref_impl, x, w_x, w_h, b, peep_stack)
    return vjp(g)


_lstm_core.defvjp(_lstm_core_vjp_fwd, _lstm_core_vjp_bwd)


def lstm(
    x,
    w_x,
    w_h,
    b,
    *,
    peepholes=None,
    forget_bias: float = 0.0,
    init_state=None,
):
    """Drop-in replacement for ops/rnn.lstm using the Pallas kernel.

    Falls back to the XLA scan when shapes don't tile onto the TPU VPU/MXU
    (N % 8 != 0 or H % 128 != 0) or when an initial state is supplied
    (kernel currently assumes zero init for the custom-vjp recompute path).
    """
    n, t, _ = x.shape
    h_dim = w_h.shape[0]
    if init_state is not None or not _shapes_tile(n, h_dim) or not _use_pallas():
        return opsrnn.lstm(
            x, w_x, w_h, b, peepholes=peepholes, forget_bias=forget_bias,
            init_state=init_state,
        )
    if peepholes is not None:
        peep_stack = jnp.stack(peepholes)
        has_peep = True
    else:
        peep_stack = jnp.zeros((3, h_dim), x.dtype)
        has_peep = False
    outputs, h_t, c_t = _lstm_core(x, w_x, w_h, b, peep_stack, float(forget_bias), has_peep)
    return outputs, opsrnn.LSTMState(h_t, c_t)
