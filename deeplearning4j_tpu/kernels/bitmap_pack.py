"""Pallas bitmap gradient-compression kernel (fused classify+pack+residual).

ref: libnd4j's encode_bitmap CUDA helper (SURVEY §2.1 gradient-compression
row; §2.8.7 names a "Pallas bitmap-encode demo" as the TPU-native
equivalent for the DCN-constrained cross-slice leg — intra-slice stays
exact ICI all-reduce).

Why a kernel at all: the XLA path (ops/compression.bitmap_encode)
materializes the code plane, the sent plane, and the padded word matrix —
~4x the gradient's bytes of HBM traffic for a codec whose entire point is
bandwidth. This kernel reads each gradient block into VMEM ONCE and emits
only the packed words (n/16 int32) and the residual (n f32): one pass,
no intermediate HBM tensors. Packing = 16 2-bit codes per int32 word,
bit-identical to the XLA codec (parity-tested; decode is shared).

Block layout: the flat gradient is processed in [BLOCK]=2048-element
tiles → 128 packed words per tile (the TPU lane width, so the packed
store is a full-lane write). Input is padded to a BLOCK multiple outside
the kernel; padded elements encode as 0 and are dropped on decode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from deeplearning4j_tpu.kernels._dispatch import on_tpu as _on_tpu
from deeplearning4j_tpu.ops import compression as _xla

BLOCK = 2048  # elements per tile; BLOCK // 16 = 128 packed words (lanes)


def _kernel(g_ref, packed_ref, resid_ref, *, threshold):
    g = g_ref[...].astype(jnp.float32)  # [BLOCK]
    pos = g >= threshold
    neg = g <= -threshold
    code = jnp.where(pos, jnp.uint32(1),
                     jnp.where(neg, jnp.uint32(2), jnp.uint32(0)))
    sent = jnp.where(pos, threshold, jnp.where(neg, -threshold, 0.0))
    resid_ref[...] = g - sent
    words = code.reshape(BLOCK // 16, 16)
    shifts = (jnp.arange(16, dtype=jnp.uint32) * 2)[None, :]
    packed_ref[...] = jnp.sum(
        words << shifts, axis=1, dtype=jnp.uint32).astype(jnp.int32)


def bitmap_encode(grad: jax.Array, threshold: float, *,
                  backend: str = "auto"):
    """Fused bitmap encode. Same contract as ops.compression.bitmap_encode:
    returns (packed int32 [ceil(n/16)], residual shaped like grad).
    backend: "pallas" | "xla" | "auto" (pallas on TPU, xla elsewhere —
    interpret-mode pallas is for tests, not production CPU use)."""
    if backend == "xla" or (backend == "auto" and not _on_tpu()):
        return _xla.bitmap_encode(grad, threshold)

    flat = grad.reshape(-1)
    n = flat.shape[0]
    pad = (-n) % BLOCK
    padded = jnp.pad(flat, (0, pad))
    grid = padded.shape[0] // BLOCK

    packed, resid = pl.pallas_call(
        functools.partial(_kernel, threshold=float(threshold)),
        grid=(grid,),
        in_specs=[pl.BlockSpec((BLOCK,), lambda i: (i,))],
        out_specs=[pl.BlockSpec((BLOCK // 16,), lambda i: (i,)),
                   pl.BlockSpec((BLOCK,), lambda i: (i,))],
        out_shape=[jax.ShapeDtypeStruct((padded.shape[0] // 16,), jnp.int32),
                   jax.ShapeDtypeStruct(padded.shape, jnp.float32)],
        interpret=not _on_tpu(),
    )(padded)
    n_words = (n + 15) // 16
    return packed[:n_words], resid[:n].reshape(grad.shape).astype(grad.dtype)
