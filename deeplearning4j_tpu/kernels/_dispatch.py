"""Pallas kernel dispatch policy.

The compiled Pallas path is used only on real TPU devices. Off-TPU (CPU
CI, the driver's virtual-device dry-run) the kernels' callers take the XLA
reference implementations instead: interpret-mode Pallas is an emulator
meant for unit-testing kernel logic, and is far too slow to sit inside a
jitted train step (a cold BERT step exceeds several minutes).

Kernel unit tests opt back in by setting ``DL4J_TPU_FORCE_PALLAS=1``, which
routes through the kernel in interpret mode so the kernel body itself is
exercised against the XLA oracle on CPU.
"""

from __future__ import annotations

import os

import jax

_TPU_PLATFORMS = ("tpu", "axon")


def on_tpu() -> bool:
    """True when the default jax backend is a real TPU."""
    try:
        return jax.devices()[0].platform in _TPU_PLATFORMS
    except Exception:  # pragma: no cover - backend init failure
        return False


def force_pallas() -> bool:
    """True when tests force the (interpret-mode) Pallas path off-TPU."""
    return os.environ.get("DL4J_TPU_FORCE_PALLAS", "") == "1"


def use_pallas() -> bool:
    """Should callers dispatch to the Pallas kernel at all?"""
    return on_tpu() or force_pallas()


def flash_block_sizes() -> tuple[int, int]:
    """Default (block_q, block_k) for the flash kernel.

    Tunable via DL4J_TPU_FLASH_BLOCK_Q/K so the on-chip kernels_ab sweep
    can promote a winning geometry without a code change. 256x512 default:
    larger kv blocks amortize the per-grid-step overhead along the
    innermost (sequential) dimension while [block_q, block_k] score tiles
    stay comfortably inside VMEM.
    """
    return (int(os.environ.get("DL4J_TPU_FLASH_BLOCK_Q", "256")),
            int(os.environ.get("DL4J_TPU_FLASH_BLOCK_K", "512")))


def flash_min_seq() -> int:
    """Sequence length at/above which attention auto-dispatch prefers the
    Pallas flash kernel over XLA's fused attention.

    Measured on TPU v5e (BENCH kernels_ab, 2026-07-30, B8 H12 T512 D64):
    XLA wins the forward 8x and the backward 1.2x at short sequences —
    the flash kernel's O(T) memory advantage only pays once the T^2 score
    materialization pressures HBM. Override with DL4J_TPU_FLASH_MIN_SEQ.
    """
    return int(os.environ.get("DL4J_TPU_FLASH_MIN_SEQ", "1024"))
