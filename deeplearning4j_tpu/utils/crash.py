"""Crash reporting (↔ org.deeplearning4j.util.CrashReportingUtil; SURVEY
§2.5). On an OOM or training-loop crash the reference writes a diagnostic
dump (memory state, JVM info, network config, iteration count) next to the
model. The TPU-native analogue dumps: device + HBM stats from PJRT
(``device.memory_stats()``), the jax/backend identity, the model/net config
JSON when serializable, the training step, recent losses, and the full
traceback — everything needed to attribute an OOM to a config without a
live session."""

from __future__ import annotations

import datetime
import json
import os
import traceback
from typing import Any, Dict, List, Optional

_LAST_REPORT: Optional[str] = None


def last_crash_report() -> Optional[str]:
    """Path of the most recent crash dump written by this process."""
    return _LAST_REPORT


def _device_info() -> List[Dict[str, Any]]:
    import jax

    infos = []
    try:
        for d in jax.devices():
            info: Dict[str, Any] = {
                "id": d.id,
                "platform": d.platform,
                "device_kind": d.device_kind,
            }
            try:
                stats = d.memory_stats()
            except Exception:  # pragma: no cover - backend-dependent
                stats = None
            if stats:
                info["memory_stats"] = {
                    k: int(v) for k, v in stats.items()
                    if isinstance(v, (int, float))
                }
            infos.append(info)
    except Exception as e:  # pragma: no cover - backend init failure
        infos.append({"error": f"device enumeration failed: {e}"})
    return infos


def write_crash_report(
    directory: str = ".",
    *,
    exception: Optional[BaseException] = None,
    model=None,
    step: Optional[int] = None,
    recent_losses: Optional[List[float]] = None,
    extra: Optional[Dict[str, Any]] = None,
    flight_window_s: Optional[float] = 120.0,
) -> str:
    """Write ``dl4j-tpu-crash-<ts>.json`` and return its path
    (↔ CrashReportingUtil.writeMemoryCrashDump). The report includes the
    flight recorder's trailing ``flight_window_s`` seconds of events
    (None = the whole ring)."""
    global _LAST_REPORT
    import jax

    report: Dict[str, Any] = {
        "timestamp": datetime.datetime.now().isoformat(),
        "jax_version": jax.__version__,
        "backend": jax.default_backend() if _safe_backend() else "unknown",
        "devices": _device_info(),
        "pid": os.getpid(),
    }
    if step is not None:
        report["step"] = int(step)
    if recent_losses:
        report["recent_losses"] = [float(x) for x in recent_losses[-50:]]
    if exception is not None:
        report["exception"] = {
            "type": type(exception).__name__,
            "message": str(exception)[:2000],
            "traceback": traceback.format_exception(
                type(exception), exception, exception.__traceback__),
        }
    if model is not None:
        try:
            from deeplearning4j_tpu.nn.config import config_to_json

            report["model_config"] = json.loads(config_to_json(model.config))
        except Exception:
            report["model_config"] = repr(getattr(model, "config", model))[:4000]
    if extra:
        report["extra"] = extra
    # worker identity: merged cluster dossiers must attribute each
    # report to its worker/generation without parsing logs — the
    # identity rides in the body AND the filename (two reports from two
    # workers of one cohort can no longer collide or need guessing)
    ident_tag = ""
    if os.environ.get("DL4J_TPU_WORKER_ID") is not None:
        try:
            from deeplearning4j_tpu.observability.federation import (
                worker_identity,
            )

            ident = worker_identity()
            report["worker_identity"] = ident
            ident_tag = (f"-w{ident['worker_id']}"
                         f"g{ident['generation']}")
        except Exception:  # noqa: BLE001 - identity never masks the crash
            pass
    try:
        # black-box timeline: the flight recorder's trailing window rides
        # in every crash dump, so "what happened just before?" is
        # answerable from the report alone (observability/flightrecorder)
        from deeplearning4j_tpu.observability.flightrecorder import (
            get_flight_recorder,
        )

        report["flight_recorder"] = get_flight_recorder().dump(
            last_seconds=flight_window_s)
    except Exception:  # noqa: BLE001 - telemetry must never mask the crash
        pass

    os.makedirs(directory, exist_ok=True)
    stamp = datetime.datetime.now().strftime("%Y%m%d-%H%M%S")
    path = os.path.join(
        directory, f"dl4j-tpu-crash-{stamp}{ident_tag}-{os.getpid()}.json")
    with open(path, "w") as fh:
        json.dump(report, fh, indent=2, default=str)
    _LAST_REPORT = path
    try:
        # shared-registry crash counter: dumps reach /metrics scrapers,
        # not just the local filesystem (observability/metrics.py)
        from deeplearning4j_tpu.observability import metrics as _obsm

        if _obsm.enabled():
            _obsm.get_resilience_metrics().crash_reports_total.inc()
    except Exception:  # noqa: BLE001 - telemetry must never mask the crash
        pass
    return path


def _safe_backend() -> bool:
    try:
        import jax

        jax.default_backend()
        return True
    except Exception:  # pragma: no cover
        return False


class CrashReportingListener:
    """Listener variant: track step/losses and dump on fit-loop crash.

    Trainer.fit does not catch exceptions (fail fast); wrap the fit call::

        lst = CrashReportingListener("/tmp/crash")
        try:
            trainer.fit(ts, data, listeners=[lst])
        except Exception as e:
            lst.dump(e, model=model)
            raise
    """

    def __init__(self, directory: str = "."):
        self.directory = directory
        self._step = 0
        self._losses: List[float] = []

    # TrainingListener protocol (duck-typed)
    def on_fit_start(self, trainer, ts):
        self._model = getattr(trainer, "model", None)

    def on_epoch_start(self, epoch):
        pass

    def on_iteration(self, epoch, step, ts, metrics):
        import jax

        self._step = step
        try:
            self._losses.append(float(jax.device_get(metrics["total_loss"])))
        except Exception:
            pass
        return False

    def on_epoch_end(self, epoch, ts):
        return False

    def on_fit_end(self, trainer, ts):
        pass

    def dump(self, exception: BaseException, model=None) -> str:
        return write_crash_report(
            self.directory, exception=exception,
            model=model or getattr(self, "_model", None),
            step=self._step, recent_losses=self._losses)
