"""Pytree ↔ flat-vector utilities.

ref: the reference keeps ALL params in one contiguous flat vector
(MultiLayerNetwork.params()) with layer params as views — an allocation
trick that the TPU design abandons (pytrees shard better and donate
cleanly). These utils provide the flat view for checkpoint compat and
parity tests (↔ MultiLayerNetwork.params() / setParams()).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def flatten_with_names(tree) -> List[Tuple[str, Any]]:
    """[(path string, leaf array)] in deterministic order."""
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [(path_str(p), v) for p, v in leaves]


def to_flat_vector(params) -> jnp.ndarray:
    """↔ MultiLayerNetwork.params(): single 1-D concat of all params."""
    named = flatten_with_names(params)
    return jnp.concatenate([jnp.ravel(v) for _, v in named]) if named else jnp.zeros((0,))


def from_flat_vector(params_template, flat) -> Any:
    """↔ setParams(): scatter a flat vector back into the pytree structure."""
    leaves, treedef = jax.tree_util.tree_flatten(params_template)
    out = []
    off = 0
    for leaf in leaves:
        n = leaf.size
        out.append(jnp.reshape(flat[off : off + n], leaf.shape).astype(leaf.dtype))
        off += n
    if off != flat.shape[0]:
        raise ValueError(f"flat vector length {flat.shape[0]} != param count {off}")
    return jax.tree_util.tree_unflatten(treedef, out)


def num_params(tree) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


def tree_allclose(a, b, rtol=1e-5, atol=1e-6) -> bool:
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    if len(la) != len(lb):
        return False
    return all(np.allclose(x, y, rtol=rtol, atol=atol) for x, y in zip(la, lb))
