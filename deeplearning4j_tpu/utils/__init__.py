"""Subpackage."""
