"""ROC / AUC / calibration evaluation (↔ org.nd4j.evaluation.classification.
{ROC, ROCBinary, ROCMultiClass, EvaluationCalibration}).

ref: the reference's ROC supports an "exact" mode (store every score) and a
"thresholded" mode (fixed threshold steps, O(1) memory). TPU-native design
keeps only the thresholded mode's statistic — per-batch accumulation is a
pair of fixed-size score HISTOGRAMS (positives / negatives per output),
computed on device with one segment-sum per batch (static shapes, jit-able,
and psum-able across data shards exactly like the confusion matrix in
classification.py). Curves, AUC, AUPRC, reliability and ECE are derived
host-side at report time from the histograms; with B bins the derived curve
is identical to the reference's thresholded curve with B steps.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.evaluation.util import select_output

_trapz = getattr(np, "trapezoid", None) or np.trapz  # numpy<2 compat


@functools.partial(jax.jit, static_argnums=(3,))
def _hist_update(pos_hist, neg_hist, scores_and_labels, bins):
    """Accumulate per-class score histograms on device.

    scores_and_labels = (probs [N, C] in [0,1], labels [N, C] in {0,1}).
    Returns updated ([C, bins], [C, bins]) histograms.
    """
    probs, labels = scores_and_labels
    idx = jnp.clip((probs * bins).astype(jnp.int32), 0, bins - 1)  # [N, C]
    c = probs.shape[1]
    # one segment-sum per class-column, flattened to a single call:
    # flat bin id = class * bins + score bin
    flat = idx + jnp.arange(c)[None, :] * bins
    pos = jax.ops.segment_sum(labels.reshape(-1), flat.reshape(-1), c * bins)
    neg = jax.ops.segment_sum((1.0 - labels).reshape(-1), flat.reshape(-1),
                              c * bins)
    return (pos_hist + pos.reshape(c, bins), neg_hist + neg.reshape(c, bins))


def _as_2d(a):
    a = jnp.asarray(a)
    return a[:, None] if a.ndim == 1 else a


class ROCBinary:
    """Per-output-column binary ROC (↔ ROCBinary); the building block for
    ROC (1 column) and ROCMultiClass (one-vs-all columns)."""

    def __init__(self, num_outputs: int = 1, threshold_steps: int = 200):
        self.num_outputs = num_outputs
        self.bins = threshold_steps
        self.pos = jnp.zeros((num_outputs, self.bins), jnp.float32)
        self.neg = jnp.zeros((num_outputs, self.bins), jnp.float32)

    # -- accumulation (device-side) ---------------------------------------

    def eval(self, labels, probs):
        labels = _as_2d(labels).astype(jnp.float32)
        probs = _as_2d(probs)
        if labels.shape != probs.shape:
            raise ValueError(f"shape mismatch {labels.shape} vs {probs.shape}")
        self.pos, self.neg = _hist_update(self.pos, self.neg, (probs, labels),
                                          self.bins)
        return self

    def merge(self, other: "ROCBinary"):
        self.pos = self.pos + other.pos
        self.neg = self.neg + other.neg
        return self

    # -- derived curves (host-side) ---------------------------------------

    def _counts(self, output: int):
        pos = np.asarray(jax.device_get(self.pos[output]), np.float64)
        neg = np.asarray(jax.device_get(self.neg[output]), np.float64)
        return pos, neg

    def roc_curve(self, output: int = 0) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(thresholds, fpr, tpr), thresholds ascending 0..1 (B+1 points).

        Point k is the operating point "predict positive iff score >= k/B":
        TPR = P(score bin >= k | positive), FPR likewise for negatives.
        """
        pos, neg = self._counts(output)
        p_total = max(pos.sum(), 1.0)
        n_total = max(neg.sum(), 1.0)
        # suffix sums: counts with bin index >= k, k = 0..B
        tp = np.concatenate([np.cumsum(pos[::-1])[::-1], [0.0]])
        fp = np.concatenate([np.cumsum(neg[::-1])[::-1], [0.0]])
        thr = np.arange(self.bins + 1) / self.bins
        return thr, fp / n_total, tp / p_total

    def precision_recall_curve(self, output: int = 0):
        """(thresholds, precision, recall); precision=1 at zero predictions
        (↔ reference convention for the empty-positive end of the curve)."""
        pos, neg = self._counts(output)
        p_total = max(pos.sum(), 1.0)
        tp = np.concatenate([np.cumsum(pos[::-1])[::-1], [0.0]])
        fp = np.concatenate([np.cumsum(neg[::-1])[::-1], [0.0]])
        pred = tp + fp
        prec = np.divide(tp, pred, out=np.ones_like(tp), where=pred > 0)
        rec = tp / p_total
        thr = np.arange(self.bins + 1) / self.bins
        return thr, prec, rec

    def auc(self, output: int = 0) -> float:
        """Area under ROC via trapezoid over the thresholded curve
        (↔ ROC.calculateAUC)."""
        _, fpr, tpr = self.roc_curve(output)
        return float(-_trapz(tpr, fpr))  # fpr descends with threshold

    def auc_pr(self, output: int = 0) -> float:
        """Area under precision-recall (↔ ROC.calculateAUCPR)."""
        _, prec, rec = self.precision_recall_curve(output)
        return float(-_trapz(prec, rec))


class ROC(ROCBinary):
    """Binary ROC (↔ org.nd4j.evaluation.classification.ROC, thresholded
    mode). Accepts labels/probs as [N], [N,1], or one-hot/softmax [N,2]
    (positive class = column 1, reference convention)."""

    def __init__(self, threshold_steps: int = 200):
        super().__init__(num_outputs=1, threshold_steps=threshold_steps)

    def eval(self, labels, probs):
        labels = jnp.asarray(labels)
        probs = jnp.asarray(probs)
        if labels.ndim == 2 and labels.shape[1] == 2:
            labels = labels[:, 1]
        if probs.ndim == 2 and probs.shape[1] == 2:
            probs = probs[:, 1]
        return super().eval(labels, probs)


class ROCMultiClass(ROCBinary):
    """One-vs-all ROC per class (↔ ROCMultiClass). labels one-hot [N, C]
    or int ids [N]; probs [N, C] (softmax)."""

    def __init__(self, num_classes: int, threshold_steps: int = 200):
        super().__init__(num_outputs=num_classes, threshold_steps=threshold_steps)

    def eval(self, labels, probs):
        labels = jnp.asarray(labels)
        probs = jnp.asarray(probs)
        if labels.ndim == 1:
            labels = jax.nn.one_hot(labels, self.num_outputs)
        return super().eval(labels, probs)

    def average_auc(self) -> float:
        """Macro-average AUC over classes (↔ calculateAverageAUC)."""
        return float(np.mean([self.auc(i) for i in range(self.num_outputs)]))


class EvaluationCalibration:
    """Calibration statistics (↔ EvaluationCalibration): reliability diagram,
    expected calibration error, residual plot, probability histograms —
    all derived from the same device-side histogram pair."""

    def __init__(self, num_classes: int, reliability_bins: int = 10,
                 histogram_bins: int = 50):
        self.num_classes = num_classes
        self.rbins = reliability_bins
        self.hbins = histogram_bins
        # device histogram resolution: a multiple of both report binnings
        # (~200 bins) so host-side rebinning is exact, never interpolated
        lcm = int(np.lcm(reliability_bins, histogram_bins))
        bins = lcm * max(1, round(200 / lcm))
        self._roc = ROCBinary(num_outputs=num_classes, threshold_steps=bins)

    def eval(self, labels, probs):
        labels = jnp.asarray(labels)
        if labels.ndim == 1:
            labels = jax.nn.one_hot(labels, self.num_classes)
        self._roc.eval(labels, probs)
        return self

    def merge(self, other: "EvaluationCalibration"):
        self._roc.merge(other._roc)
        return self

    def _rebin(self, hist: np.ndarray, nbins: int) -> np.ndarray:
        b = hist.shape[-1]
        assert b % nbins == 0
        return hist.reshape(*hist.shape[:-1], nbins, b // nbins).sum(-1)

    def reliability_curve(self, cls: int = 0):
        """(bin_centers, observed_frequency, count) per reliability bin."""
        pos, neg = self._roc._counts(cls)
        pos = self._rebin(pos, self.rbins)
        neg = self._rebin(neg, self.rbins)
        count = pos + neg
        freq = np.divide(pos, count, out=np.zeros_like(pos), where=count > 0)
        centers = (np.arange(self.rbins) + 0.5) / self.rbins
        return centers, freq, count

    def ece(self, cls: int = 0) -> float:
        """Expected calibration error: sum_b (n_b/N) |freq_b - center_b|."""
        centers, freq, count = self.reliability_curve(cls)
        n = max(count.sum(), 1.0)
        return float(np.sum(count / n * np.abs(freq - centers)))

    def probability_histogram(self, cls: int = 0):
        """(bin_edges, counts) of predicted probabilities for ``cls``
        (↔ getProbabilityHistogramAllClasses)."""
        pos, neg = self._roc._counts(cls)
        counts = self._rebin(pos + neg, self.hbins)
        edges = np.arange(self.hbins + 1) / self.hbins
        return edges, counts

    def residual_plot(self, cls: int = 0):
        """(bin_centers, |label - prob| mass per bin) (↔ getResidualPlot)."""
        pos, neg = self._roc._counts(cls)
        pos = self._rebin(pos, self.hbins)
        neg = self._rebin(neg, self.hbins)
        centers = (np.arange(self.hbins) + 0.5) / self.hbins
        # positives at prob p contribute |1-p|, negatives |p|
        return centers, pos * (1.0 - centers) + neg * centers


def evaluate_roc(model, variables, data_iter, *, num_classes: int = 2,
                 threshold_steps: int = 200,
                 output_name: Optional[str] = None):
    """↔ MultiLayerNetwork.evaluateROC / evaluateROCMultiClass: run the
    model over an iterator and accumulate ROC curves — binary ``ROC`` for
    num_classes=2, one-vs-all ``ROCMultiClass`` otherwise. For multi-output
    graph models pass ``output_name`` to pick the head to evaluate."""
    ev = (ROC(threshold_steps) if num_classes == 2
          else ROCMultiClass(num_classes, threshold_steps))
    for ds in data_iter:
        out = model.output(variables, getattr(ds, "features", None)
                           if hasattr(ds, "features") else ds["features"])
        out = select_output(out, output_name, "evaluate_roc")
        labels = ds.labels if hasattr(ds, "labels") else ds["labels"]
        ev.eval(labels, out)
    return ev
