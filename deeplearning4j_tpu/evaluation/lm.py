"""Language-model evaluation (perplexity / bits-per-token).

The reference's evaluation stack covers classification/regression; its
LM examples report raw loss. With a causal-LM family in the zoo
(models/gpt.py) the standard LM metrics belong in the evaluation module:
on-device accumulation (sum of token NLL + token count — mergeable
across shards/batches like Evaluation's confusion matrix), metrics
derived at report time.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops import loss as losses


class LMEvaluation:
    """Accumulates token-level NLL over batches; derives perplexity,
    cross-entropy (nats and bits) per token. ``eval`` takes next-token
    logits [N,T,V] and label ids [N,T] (+ optional 0/1 mask)."""

    def __init__(self):
        self._nll = jnp.zeros((), jnp.float64 if jax.config.jax_enable_x64
                              else jnp.float32)
        self._count = jnp.zeros((), jnp.float32)

    def eval(self, logits, labels, mask=None):
        per_tok = losses.sparse_softmax_cross_entropy(
            logits, labels, reduction="none")
        w = (jnp.ones(per_tok.shape, jnp.float32) if mask is None
             else jnp.asarray(mask, jnp.float32))
        self._nll = self._nll + jnp.sum(per_tok * w)
        self._count = self._count + jnp.sum(w)
        return self

    def merge(self, other: "LMEvaluation"):
        self._nll = self._nll + other._nll
        self._count = self._count + other._count
        return self

    # -- derived metrics (host-side) ---------------------------------------

    def token_count(self) -> float:
        return float(jax.device_get(self._count))

    def cross_entropy(self) -> float:
        """Mean NLL per token, nats."""
        n = self.token_count()
        return float(jax.device_get(self._nll)) / max(n, 1.0)

    def bits_per_token(self) -> float:
        return self.cross_entropy() / float(np.log(2.0))

    def perplexity(self) -> float:
        return float(np.exp(self.cross_entropy()))

    def stats(self) -> str:
        return (f"# tokens: {int(self.token_count())}\n"
                f"Cross entropy: {self.cross_entropy():.4f} nats "
                f"({self.bits_per_token():.4f} bits)\n"
                f"Perplexity:    {self.perplexity():.4f}")


def evaluate_lm(model, variables, batches) -> LMEvaluation:
    """Run a causal LM over an iterable of batches ({"features":
    {"token_ids": [N,T]}, optional "mask", optional "labels"}) and
    accumulate next-token perplexity. Labels default to ids shifted by
    one; an explicit batch["labels"] overrides — the same convention
    Gpt.loss_fn trains with, so eval ppl matches the training objective."""
    ev = LMEvaluation()
    fwd = jax.jit(lambda v, f: model.apply(v, f, train=False)[0])
    for batch in batches:
        labels = batch.get("labels") if isinstance(batch, dict) else None
        feats = batch["features"] if (isinstance(batch, dict)
                                      and "features" in batch) else batch
        if not isinstance(feats, dict):
            feats = {"token_ids": feats}
        ids = jnp.asarray(feats["token_ids"])
        logits = fwd(variables, feats)[:, :-1]
        mask = feats.get("mask")
        ev.eval(logits,
                ids[:, 1:] if labels is None else jnp.asarray(labels),
                None if mask is None else jnp.asarray(mask)[:, 1:])
    return ev
