"""Regression evaluation (↔ org.nd4j.evaluation.regression.RegressionEvaluation).

Metrics per output column: MSE, MAE, RMSE, RSE (relative squared error),
PC (Pearson correlation), R². Accumulated with streaming sums on device.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.evaluation.util import select_output


@jax.jit
def _acc_update(acc, pred, target, mask=None):
    """Streaming sums; [rows, C] inputs (callers flatten time first).
    ``mask`` [rows] zero-weights excluded rows (padded timesteps)."""
    # Weights in f32 regardless of pred dtype: bf16 row counts round
    # (1001 -> 1000) and would drift n across batches.
    if mask is None:
        w = jnp.ones((pred.shape[0], 1), jnp.float32)
    else:
        w = mask.astype(jnp.float32).reshape(-1, 1)
    return {
        "n": acc["n"] + jnp.sum(w),
        "se": acc["se"] + jnp.sum(w * jnp.square(pred - target), axis=0),
        "ae": acc["ae"] + jnp.sum(w * jnp.abs(pred - target), axis=0),
        "sum_t": acc["sum_t"] + jnp.sum(w * target, axis=0),
        "sum_t2": acc["sum_t2"] + jnp.sum(w * jnp.square(target), axis=0),
        "sum_p": acc["sum_p"] + jnp.sum(w * pred, axis=0),
        "sum_p2": acc["sum_p2"] + jnp.sum(w * jnp.square(pred), axis=0),
        "sum_pt": acc["sum_pt"] + jnp.sum(w * pred * target, axis=0),
    }


class RegressionEvaluation:
    def __init__(self, n_columns: int):
        z = jnp.zeros((n_columns,), jnp.float32)
        self.acc = {
            "n": jnp.zeros((), jnp.float32),
            "se": z, "ae": z, "sum_t": z, "sum_t2": z,
            "sum_p": z, "sum_p2": z, "sum_pt": z,
        }

    def eval(self, labels, predictions):
        predictions = jnp.asarray(predictions)
        if predictions.ndim == 3:
            return self.eval_time_series(labels, predictions)
        self.acc = _acc_update(self.acc, predictions, jnp.asarray(labels))
        return self

    def eval_time_series(self, labels, predictions, mask=None):
        """↔ RegressionEvaluation.evalTimeSeries: [N,T,C] with optional
        [N,T] mask; padded steps carry zero weight."""
        predictions = jnp.asarray(predictions)
        labels = jnp.asarray(labels)
        c = predictions.shape[-1]
        m = None if mask is None else jnp.asarray(mask).reshape(-1)
        self.acc = _acc_update(self.acc, predictions.reshape(-1, c),
                               labels.reshape(-1, c), m)
        return self

    def _h(self):
        return {k: np.asarray(jax.device_get(v)) for k, v in self.acc.items()}

    def mse(self):
        a = self._h()
        return a["se"] / max(a["n"], 1)

    def mae(self):
        a = self._h()
        return a["ae"] / max(a["n"], 1)

    def rmse(self):
        return np.sqrt(self.mse())

    def r2(self):
        a = self._h()
        n = max(a["n"], 1)
        ss_tot = a["sum_t2"] - np.square(a["sum_t"]) / n
        return 1.0 - a["se"] / np.maximum(ss_tot, 1e-12)

    def pearson(self):
        a = self._h()
        n = max(a["n"], 1)
        cov = a["sum_pt"] - a["sum_p"] * a["sum_t"] / n
        vp = a["sum_p2"] - np.square(a["sum_p"]) / n
        vt = a["sum_t2"] - np.square(a["sum_t"]) / n
        return cov / np.maximum(np.sqrt(vp * vt), 1e-12)

    def stats(self) -> str:
        return (
            f"MSE:  {np.mean(self.mse()):.6f}\n"
            f"MAE:  {np.mean(self.mae()):.6f}\n"
            f"RMSE: {np.mean(self.rmse()):.6f}\n"
            f"R^2:  {np.mean(self.r2()):.6f}"
        )


def evaluate_regression(model, variables, data_iter,
                        n_columns: int, *,
                        output_name: Optional[str] = None,
                        ) -> RegressionEvaluation:
    """↔ MultiLayerNetwork.evaluateRegression(DataSetIterator). For
    multi-output graph models pass ``output_name`` to pick the head."""
    ev = RegressionEvaluation(n_columns)
    for ds in data_iter:
        feats = ds.features if hasattr(ds, "features") else ds["features"]
        labels = ds.labels if hasattr(ds, "labels") else ds["labels"]
        out = model.output(variables, feats)
        out = select_output(out, output_name, "evaluate_regression")
        ev.eval(labels, out)
    return ev
