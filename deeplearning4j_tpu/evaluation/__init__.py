"""Evaluation stack (↔ org.nd4j.evaluation.**)."""

from deeplearning4j_tpu.evaluation.classification import (
    Evaluation,
    EvaluationBinary,
    evaluate_model,
)
from deeplearning4j_tpu.evaluation.curves import (
    ROC,
    EvaluationCalibration,
    ROCBinary,
    ROCMultiClass,
    evaluate_roc,
)
from deeplearning4j_tpu.evaluation.lm import LMEvaluation, evaluate_lm
from deeplearning4j_tpu.evaluation.regression import (
    RegressionEvaluation,
    evaluate_regression,
)

__all__ = [
    "Evaluation", "EvaluationBinary", "evaluate_model",
    "RegressionEvaluation", "evaluate_regression",
    "ROC", "ROCBinary", "ROCMultiClass", "EvaluationCalibration",
    "evaluate_roc",
    "LMEvaluation", "evaluate_lm",
]
