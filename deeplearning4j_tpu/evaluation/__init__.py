"""Evaluation stack (↔ org.nd4j.evaluation.**)."""

from deeplearning4j_tpu.evaluation.classification import (
    Evaluation,
    EvaluationBinary,
    evaluate_model,
)
from deeplearning4j_tpu.evaluation.curves import (
    ROC,
    EvaluationCalibration,
    ROCBinary,
    ROCMultiClass,
)
from deeplearning4j_tpu.evaluation.lm import LMEvaluation, evaluate_lm
from deeplearning4j_tpu.evaluation.regression import RegressionEvaluation

__all__ = [
    "LMEvaluation", "evaluate_lm","Evaluation", "EvaluationBinary", "evaluate_model",
           "RegressionEvaluation",
           "ROC", "ROCBinary", "ROCMultiClass", "EvaluationCalibration"]
