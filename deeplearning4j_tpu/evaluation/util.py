"""Shared evaluation helpers."""

from __future__ import annotations


def select_output(out, output_name, caller: str):
    """Resolve a (possibly multi-output graph) model's output dict.

    Single-output dicts resolve unambiguously; multi-output dicts require
    ``output_name`` — silently evaluating an arbitrary head would produce
    a plausible-looking but wrong metric. Non-dict outputs pass through.
    """
    if not isinstance(out, dict):
        return out
    if output_name is not None:
        if output_name not in out:
            raise KeyError(
                f"{caller}: output '{output_name}' not found; model "
                f"outputs are {sorted(out)}")
        return out[output_name]
    if len(out) == 1:
        return next(iter(out.values()))
    raise ValueError(
        f"{caller}: model has multiple outputs {sorted(out)}; pass "
        f"output_name= to choose which one to evaluate")
