"""Classification evaluation (↔ org.nd4j.evaluation.classification.Evaluation).

ref: Evaluation (confusion matrix, accuracy/precision/recall/F1 micro+macro,
top-N accuracy), incremental ``eval(labels, predictions)`` batching.

TPU-native: the per-batch statistic is a confusion-matrix accumulation done
ON DEVICE (one segment-sum — and under pjit it psums across data shards),
with metrics derived host-side at report time. This replaces the
reference's host-side per-batch INDArray bookkeeping.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops import math as opsmath


@jax.jit
def _confusion_update(cm, logits_or_probs, labels, mask=None):
    """Confusion accumulation for [N,C] or (flattened) [N,T,C] inputs;
    optional mask weights exclude entries (padded timesteps) while keeping
    shapes static under jit."""
    pred = jnp.argmax(logits_or_probs, axis=-1).reshape(-1)
    lab = (jnp.argmax(labels, axis=-1)
           if labels.ndim == logits_or_probs.ndim else labels).reshape(-1)
    w = None if mask is None else mask.astype(jnp.float32).reshape(-1)
    return cm + opsmath.confusion_matrix(lab, pred, cm.shape[0], weights=w)


@partial(jax.jit, static_argnums=(3,))
def _topn_update(correct, probs, labels, n, mask=None):
    """Count rows whose true class is among the n highest scores;
    optional flat mask zero-weights excluded rows (padded steps)."""
    lab = (jnp.argmax(labels, axis=-1)
           if labels.ndim == probs.ndim else labels).reshape(-1)
    flat = probs.reshape(-1, probs.shape[-1])
    hit = opsmath.in_top_k(flat, lab, n).astype(jnp.float32)
    if mask is not None:
        hit = hit * mask.astype(jnp.float32).reshape(-1)
    return correct + jnp.sum(hit)


class Evaluation:
    """↔ org.nd4j.evaluation.classification.Evaluation.

    ``top_n``: like the reference's ``Evaluation(int topN)`` constructor,
    additionally tracks top-N accuracy (true class among the N highest
    scores) — only meaningful when ``eval`` receives scores, not argmaxed
    labels.
    """

    def __init__(self, num_classes: int, labels_list: Optional[list] = None,
                 top_n: Optional[int] = None):
        self.num_classes = num_classes
        self.labels_list = labels_list or [str(i) for i in range(num_classes)]
        self.cm = jnp.zeros((num_classes, num_classes), jnp.float32)
        if top_n is not None and not 1 <= top_n <= num_classes:
            raise ValueError(
                f"top_n={top_n} must be in [1, num_classes={num_classes}]")
        self.top_n = top_n
        self._topn_correct = jnp.zeros((), jnp.float32)
        self._topn_total = 0

    # -- accumulation ------------------------------------------------------

    def eval(self, labels, predictions):
        """Accumulate one batch (device-side). For sequence outputs
        ([N,T,C]) use eval_time_series (mask-aware)."""
        predictions = jnp.asarray(predictions)
        if predictions.ndim == 3:
            return self.eval_time_series(labels, predictions)
        self.cm = _confusion_update(self.cm, predictions, labels)
        if self.top_n:
            self._topn_correct = _topn_update(
                self._topn_correct, predictions, jnp.asarray(labels),
                self.top_n)
            self._topn_total += predictions.shape[0]
        return self

    def top_n_accuracy(self) -> float:
        """↔ Evaluation.topNAccuracy()."""
        if not self.top_n:
            raise ValueError("construct Evaluation(..., top_n=N) to track it")
        total = int(self._topn_total)
        return float(jax.device_get(self._topn_correct)) / max(total, 1)

    def eval_time_series(self, labels, predictions, mask=None):
        """↔ Evaluation.evalTimeSeries: per-timestep accumulation over
        [N,T,C] predictions with an optional [N,T] mask excluding padded
        steps (zero-weighted, so the update stays static-shaped).

        Top-N tracking honors the mask too (padded steps excluded from
        both numerator and denominator)."""
        predictions = jnp.asarray(predictions)
        labels = jnp.asarray(labels)
        m = None if mask is None else jnp.asarray(mask)
        self.cm = _confusion_update(self.cm, predictions, labels, m)
        if self.top_n:
            self._topn_correct = _topn_update(
                self._topn_correct, predictions, labels, self.top_n, m)
            self._topn_total += (int(np.prod(predictions.shape[:-1]))
                                 if m is None
                                 else int(np.asarray(jax.device_get(
                                     jnp.sum(m)))))
        return self

    def merge(self, other: "Evaluation"):
        """↔ Evaluation.merge (for sharded/parallel eval)."""
        # validate BEFORE mutating: a raise must not leave self half-merged
        if self.top_n != other.top_n:
            raise ValueError(
                f"cannot merge top_n={self.top_n} with top_n={other.top_n}")
        self.cm = self.cm + other.cm
        self._topn_correct = self._topn_correct + other._topn_correct
        self._topn_total += other._topn_total
        return self

    # -- derived metrics (host-side) ---------------------------------------

    def _np(self):
        return np.asarray(jax.device_get(self.cm))

    def accuracy(self) -> float:
        cm = self._np()
        return float(np.trace(cm) / max(cm.sum(), 1))

    def precision(self, cls: Optional[int] = None, average: str = "macro") -> float:
        cm = self._np()
        tp = np.diag(cm)
        denom = cm.sum(axis=0)
        per = np.divide(tp, denom, out=np.zeros_like(tp), where=denom > 0)
        if cls is not None:
            return float(per[cls])
        if average == "macro":
            present = denom > 0
            return float(per[present].mean()) if present.any() else 0.0
        return float(tp.sum() / max(cm.sum(), 1))

    def recall(self, cls: Optional[int] = None, average: str = "macro") -> float:
        cm = self._np()
        tp = np.diag(cm)
        denom = cm.sum(axis=1)
        per = np.divide(tp, denom, out=np.zeros_like(tp), where=denom > 0)
        if cls is not None:
            return float(per[cls])
        if average == "macro":
            present = denom > 0
            return float(per[present].mean()) if present.any() else 0.0
        return float(tp.sum() / max(cm.sum(), 1))

    def f1(self, cls: Optional[int] = None, average: str = "macro") -> float:
        if cls is not None:
            p, r = self.precision(cls), self.recall(cls)
            return 2 * p * r / max(p + r, 1e-12)
        cm = self._np()
        tp = np.diag(cm)
        pden = cm.sum(axis=0)
        rden = cm.sum(axis=1)
        p = np.divide(tp, pden, out=np.zeros_like(tp), where=pden > 0)
        r = np.divide(tp, rden, out=np.zeros_like(tp), where=rden > 0)
        f = np.divide(2 * p * r, p + r, out=np.zeros_like(tp), where=(p + r) > 0)
        present = rden > 0
        return float(f[present].mean()) if present.any() else 0.0

    def confusion(self) -> np.ndarray:
        return self._np()

    def stats(self, *, confusion: bool = True,
              per_class: bool = True) -> str:
        """↔ Evaluation.stats() summary string: headline metrics, the
        confusion matrix (rows = actual, cols = predicted — reference
        orientation), and per-class precision/recall/F1. Both blocks are
        suppressible for compact logs."""
        cm = self._np()
        lines = [
            f"# examples: {int(cm.sum())}",
            f"Accuracy:  {self.accuracy():.4f}",
            f"Precision: {self.precision():.4f} (macro)",
            f"Recall:    {self.recall():.4f} (macro)",
            f"F1 Score:  {self.f1():.4f} (macro)",
        ]
        if self.top_n:
            lines.append(
                f"Top-{self.top_n} Accuracy: {self.top_n_accuracy():.4f}")
        k = cm.shape[0]
        if confusion:
            w = max(5, len(str(int(cm.max()))) + 1)
            lines.append("")
            lines.append("Confusion matrix (rows=actual, cols=predicted):")
            lines.append(" " * 6 + "".join(f"{c:>{w}}" for c in range(k)))
            for r in range(k):
                lines.append(f"{r:>5} " + "".join(
                    f"{int(cm[r, c]):>{w}}" for c in range(k)))
        if per_class:
            lines.append("")
            lines.append(f"{'class':>5}  {'precision':>9}  {'recall':>9}  "
                         f"{'f1':>9}  {'support':>8}")
            for c in range(k):
                lines.append(
                    f"{c:>5}  {self.precision(c):>9.4f}  "
                    f"{self.recall(c):>9.4f}  {self.f1(c):>9.4f}  "
                    f"{int(cm[c].sum()):>8}")
        return "\n".join(lines)


@jax.jit
def _binary_counts_update(counts, probs, labels, thresholds):
    """counts: [4, L] stacked TP/FP/TN/FN per output column."""
    pred = (probs >= thresholds).astype(jnp.float32)
    lab = labels.astype(jnp.float32)
    tp = jnp.sum(pred * lab, axis=0)
    fp = jnp.sum(pred * (1 - lab), axis=0)
    tn = jnp.sum((1 - pred) * (1 - lab), axis=0)
    fn = jnp.sum((1 - pred) * lab, axis=0)
    return counts + jnp.stack([tp, fp, tn, fn])


class EvaluationBinary:
    """↔ org.nd4j.evaluation.classification.EvaluationBinary: independent
    binary metrics PER OUTPUT column (multi-label networks with sigmoid
    outputs), not mutually-exclusive classes like ``Evaluation``.

    Per-batch accumulation is one on-device update of a [4, L] TP/FP/TN/FN
    count array; metrics derive host-side at report time. ``thresholds``
    mirrors the reference's per-output decision thresholds (default 0.5).
    """

    def __init__(self, num_outputs: int, labels_list: Optional[list] = None,
                 thresholds=None):
        self.num_outputs = num_outputs
        self.labels_list = labels_list or [str(i) for i in range(num_outputs)]
        t = np.full((num_outputs,), 0.5, np.float32) if thresholds is None \
            else np.asarray(thresholds, np.float32)
        self.thresholds = jnp.asarray(t)
        self.counts = jnp.zeros((4, num_outputs), jnp.float32)
        self._host = None  # memoized device_get of counts

    def eval(self, labels, predictions):
        labels = jnp.asarray(labels)
        predictions = jnp.asarray(predictions)
        if labels.ndim == 1:      # [N] with num_outputs=1 → [N,1]
            labels = labels[:, None]
        if predictions.ndim == 1:
            predictions = predictions[:, None]
        if predictions.shape[-1] != self.num_outputs:
            raise ValueError(
                f"predictions last dim {predictions.shape[-1]} != "
                f"num_outputs {self.num_outputs}")
        if labels.shape != predictions.shape:
            raise ValueError(
                f"labels shape {labels.shape} != predictions shape "
                f"{predictions.shape}")
        self.counts = _binary_counts_update(
            self.counts, predictions, labels, self.thresholds)
        self._host = None
        return self

    def merge(self, other: "EvaluationBinary"):
        self.counts = self.counts + other.counts
        self._host = None
        return self

    def _np(self):
        if self._host is None:
            self._host = np.asarray(jax.device_get(self.counts))
        return self._host

    def true_positives(self):
        return self._np()[0]

    def false_positives(self):
        return self._np()[1]

    def true_negatives(self):
        return self._np()[2]

    def false_negatives(self):
        return self._np()[3]

    def accuracy(self, output: Optional[int] = None):
        tp, fp, tn, fn = self._np()
        tot = np.maximum(tp + fp + tn + fn, 1)
        per = (tp + tn) / tot
        return float(per[output]) if output is not None else float(per.mean())

    @staticmethod
    def _agg(per, defined, output):
        """Per-output value, or macro mean over DEFINED outputs only
        (matching Evaluation's macro averaging of present classes)."""
        if output is not None:
            return float(per[output])
        return float(per[defined].mean()) if defined.any() else 0.0

    def precision(self, output: Optional[int] = None):
        tp, fp, _, _ = self._np()
        per = np.divide(tp, tp + fp, out=np.zeros_like(tp), where=(tp + fp) > 0)
        return self._agg(per, (tp + fp) > 0, output)

    def recall(self, output: Optional[int] = None):
        tp, _, _, fn = self._np()
        per = np.divide(tp, tp + fn, out=np.zeros_like(tp), where=(tp + fn) > 0)
        return self._agg(per, (tp + fn) > 0, output)

    def f1(self, output: Optional[int] = None):
        tp, fp, _, fn = self._np()
        denom = 2 * tp + fp + fn
        per = np.divide(2 * tp, denom, out=np.zeros_like(tp), where=denom > 0)
        return self._agg(per, denom > 0, output)

    def stats(self) -> str:
        rows = [f"{'label':>12} {'acc':>7} {'prec':>7} {'recall':>7} {'f1':>7}"]
        for i, name in enumerate(self.labels_list):
            rows.append(
                f"{name:>12} {self.accuracy(i):7.4f} {self.precision(i):7.4f} "
                f"{self.recall(i):7.4f} {self.f1(i):7.4f}")
        return "\n".join(rows)


def evaluate_model(model, variables, data_iter, num_classes: int,
                   mesh=None,
                   output_name: Optional[str] = None) -> Evaluation:
    """↔ MultiLayerNetwork.evaluate(DataSetIterator).

    The per-batch statistic (forward + confusion accumulation) is ONE jit'd
    program carrying the confusion matrix on device — no host sync inside
    the loop (SURVEY §5.5). With ``mesh``, the same program pjits over the
    data axis: parameters replicated, batch sharded, and the confusion
    accumulation psums across shards via GSPMD (the reference's
    distributed-eval aggregation without explicit collectives). For
    multi-output graph models pass ``output_name`` to pick the head."""
    import jax

    from deeplearning4j_tpu.evaluation.util import select_output

    ev = Evaluation(num_classes)

    def eval_step(cm, variables, feats, labels):
        out = model.output(variables, feats)
        out = select_output(out, output_name, "evaluate_model")
        return _confusion_update(cm, out, labels)

    jit_kwargs = {}
    n_shards = 1
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        axis = "data" if "data" in mesh.axis_names else mesh.axis_names[0]
        n_shards = mesh.shape[axis]
        rep = NamedSharding(mesh, PartitionSpec())
        batch_sh = NamedSharding(mesh, PartitionSpec(axis))
        jit_kwargs = {"in_shardings": (rep, rep, batch_sh, batch_sh),
                      "out_shardings": rep}
    step = jax.jit(eval_step, **jit_kwargs)
    plain_step = step if mesh is None else None

    cm = ev.cm
    for batch in data_iter:
        from deeplearning4j_tpu.data.dataset import as_batch_dict

        b = as_batch_dict(batch)  # DataSet-likes, (x,y), or dict batches
        feats, labels = b["features"], b["labels"]
        use = step
        if mesh is not None and len(feats) % n_shards != 0:
            # partial tail batch (drop_last=False): not shardable over the
            # data axis — run it unsharded, same math
            if plain_step is None:
                plain_step = jax.jit(eval_step)
            use = plain_step
        cm = use(cm, variables, jnp.asarray(feats), jnp.asarray(labels))
    ev.cm = cm
    return ev
