"""Request caching tier: the exact-match response cache.

At consumer scale most serving traffic is redundant — identical
classify/embed requests hitting the same model version over and over —
yet every request still takes an admission slot and a batch seat. This
module makes "work we already did" a first-class serving primitive
(the spirit of the cuDNN primitive catalog: the reusable unit IS the
product): a bounded LRU + TTL cache of full predict responses,
consulted *before* the circuit breaker and admission controller take a
batch slot, so a hit costs the overloaded data plane nothing.

Design points:

- **Key** (:func:`response_cache_key`): sha256 over the canonical JSON
  of (model, version, registry epoch, request payload minus
  ``deadline_ms``). The epoch — bumped by the registry on every
  hot-swap/rollback pointer swap — makes entries from a replaced
  version structurally unreachable even before the invalidation
  listener reclaims them.
- **Tenant isolation**: every entry is stored under a composite
  ``(tenant, key)`` — a lookup for tenant B can never return tenant
  A's entry, whatever the payload, because B's probe key is a
  different dict key. The anonymous namespace (no ``X-Tenant``) is its
  own tenant, isolated from all named ones.
- **Brownout interaction**: ``set_stale_serve(True)`` (the
  ``cache_pressure`` brownout rung) lets expired-but-present entries
  keep serving while the ladder is engaged — a degraded answer beats a
  shed — counted as ``outcome="stale"`` so the stale-serve burn-rate
  rule sees exactly how much staleness the brownout bought;
  ``pressure_evict`` drops the LRU half so the cache's host memory
  participates in pressure shedding.
- **Shared tier**: the fleet router runs the same class with
  ``plane="router"`` — a fleet-wide hit is answered without touching a
  backend, and the ``cache_*`` families federate per plane.

Everything is stdlib + the repo's own telemetry spine; locks go
through :func:`~deeplearning4j_tpu.analysis.lockcheck.make_lock` so
the lockorder sanitizer sees this tier like every other.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from collections import OrderedDict
from typing import Callable, Optional

from deeplearning4j_tpu.analysis.lockcheck import make_lock
from deeplearning4j_tpu.observability.flightrecorder import record_event
from deeplearning4j_tpu.observability.metrics import MetricsRegistry

ENV_CACHE = "DL4J_TPU_CACHE"
ENV_CACHE_CAPACITY = "DL4J_TPU_CACHE_CAPACITY"
ENV_CACHE_TTL_S = "DL4J_TPU_CACHE_TTL_S"
ENV_CACHE_MAX_BYTES = "DL4J_TPU_CACHE_MAX_BYTES"

DEFAULT_CAPACITY = 1024
DEFAULT_TTL_S = 60.0
DEFAULT_MAX_BYTES = 64 << 20


class CacheMetrics:
    """The cache tier's instrument bundle. ``plane`` distinguishes the
    server-side response cache from the router's fleet-level one when
    both land in a federated scrape; the prefix-KV families label by
    model (engine route names — a bounded, operator-chosen set)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = (registry if registry is not None
                         else MetricsRegistry())
        r = self.registry
        self.requests_total = r.counter(
            "cache_requests_total",
            "Response-cache lookups by outcome: hit (fresh entry "
            "served), miss, stale (expired entry served under the "
            "cache_pressure brownout rung — the stale-serve burn-rate "
            "rule's bad events), bypass (client sent X-Cache-Bypass).",
            ("plane", "outcome"))
        self.insertions_total = r.counter(
            "cache_insertions_total",
            "Responses written into the cache (200s on a consulted "
            "key).", ("plane",))
        self.evictions_total = r.counter(
            "cache_evictions_total",
            "Entries dropped, by reason: lru (capacity/byte bound), "
            "ttl (expired on lookup), invalidate (registry epoch bump "
            "on hot-swap/rollback), pressure (brownout rung), purge "
            "(administrative clear).", ("plane", "reason"))
        self.invalidations_total = r.counter(
            "cache_invalidations_total",
            "Invalidation passes (not entries — evictions_total counts "
            "those), by reason.", ("plane", "reason"))
        self.entries = r.gauge(
            "cache_entries", "Entries currently cached.", ("plane",))
        self.size_bytes = r.gauge(
            "cache_bytes", "Approximate bytes of cached response "
            "bodies.", ("plane",))
        # prefix-KV reuse (serving/prefixkv.py + generation.py)
        self.prefix_requests_total = r.counter(
            "cache_prefix_requests_total",
            "Prefix-KV lookups at generation prefill, by outcome "
            "(hit = a shared prefix slab was grafted instead of a "
            "full prefill).", ("model", "outcome"))
        self.prefix_insertions_total = r.counter(
            "cache_prefix_insertions_total",
            "Prefix KV slabs captured from completed prefills.",
            ("model",))
        self.prefix_evictions_total = r.counter(
            "cache_prefix_evictions_total",
            "Prefix slabs dropped, by reason (lru = byte bound; "
            "pinned entries are never evicted).", ("model", "reason"))
        self.prefix_entries = r.gauge(
            "cache_prefix_entries",
            "Prefix KV slabs currently held.", ("model",))
        self.prefix_bytes = r.gauge(
            "cache_prefix_bytes",
            "Bytes of shared prefix KV slabs.", ("model",))
        self.prefix_tokens_reused_total = r.counter(
            "cache_prefix_tokens_reused_total",
            "Prompt tokens whose prefill was skipped by grafting a "
            "shared prefix slab (the prefill-FLOP savings signal).",
            ("model",))


def response_cache_key(model: str, version: str, epoch: int,
                       payload) -> Optional[str]:
    """The exact-match key: sha256 of the canonical JSON of
    (model, version, epoch, payload minus ``deadline_ms``).

    ``deadline_ms`` is excluded — it parameterizes the client's wait,
    not the computation. Returns None when the payload defeats
    canonical serialization (the caller treats that as a bypass: an
    uncacheable request must not 500)."""
    if isinstance(payload, dict):
        payload = {k: v for k, v in payload.items() if k != "deadline_ms"}
    try:
        doc = json.dumps([model, version, epoch, payload],
                         sort_keys=True, separators=(",", ":"),
                         default=_canon_default)
    except (TypeError, ValueError):
        return None
    return hashlib.sha256(doc.encode()).hexdigest()


def _canon_default(obj):
    """Canonical fallback for direct (non-HTTP) callers passing numpy
    scalars/arrays in the payload: anything exposing ``tolist`` is
    serialized by value, everything else is uncacheable."""
    tolist = getattr(obj, "tolist", None)
    if callable(tolist):
        return tolist()
    raise TypeError(f"uncacheable payload element {type(obj).__name__}")


class CacheHit:
    """One successful lookup: the stored value plus enough context for
    the caller's response decoration (``stale`` drives the
    ``cache_stale`` body marker and the ledger outcome)."""

    __slots__ = ("value", "model", "version", "stale", "age_s")

    def __init__(self, value, model, version, stale, age_s):
        self.value = value
        self.model = model
        self.version = version
        self.stale = stale
        self.age_s = age_s


class _Entry:
    __slots__ = ("value", "model", "version", "nbytes", "expires_at",
                 "created_at")

    def __init__(self, value, model, version, nbytes, expires_at,
                 created_at):
        self.value = value
        self.model = model
        self.version = version
        self.nbytes = nbytes
        self.expires_at = expires_at
        self.created_at = created_at


class ResponseCache:
    """Bounded LRU + TTL exact-match response cache with strict
    per-tenant isolation.

    Entries are keyed ``(tenant, key)`` in one ordered map — global
    LRU across tenants (one tenant's burst ages everyone's cold tail,
    like any shared cache tier) while lookups remain structurally
    tenant-scoped. Values are opaque to the cache (the server stores
    response dicts, the router raw backend bytes); ``nbytes`` is the
    serialized size either way and both ``capacity`` and ``max_bytes``
    bound the cache."""

    def __init__(self, *, capacity: int = DEFAULT_CAPACITY,
                 ttl_s: float = DEFAULT_TTL_S,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 metrics: Optional[CacheMetrics] = None,
                 plane: str = "serving",
                 clock: Callable[[], float] = time.monotonic):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if ttl_s <= 0:
            raise ValueError(f"ttl_s must be > 0, got {ttl_s}")
        if max_bytes < 1:
            raise ValueError(f"max_bytes must be >= 1, got {max_bytes}")
        self.capacity = int(capacity)
        self.ttl_s = float(ttl_s)
        self.max_bytes = int(max_bytes)
        self.plane = plane
        self._metrics = metrics
        self._clock = clock
        self._lock = make_lock("ResponseCache._lock")
        self._entries: "OrderedDict" = OrderedDict()
        self._bytes = 0
        self._stale_ok = False
        # lifetime counters for describe() — the metrics bundle may be
        # absent (router tests build bare caches), the debug endpoint
        # must still answer
        self._hits = 0
        self._misses = 0
        self._stale_serves = 0
        self._bypasses = 0
        self._insertions = 0
        self._evictions = 0

    # -- wiring ---------------------------------------------------------------

    def attach_metrics(self, metrics: CacheMetrics) -> None:
        """Adopt an instrument bundle after construction (the server
        attaches its registry-backed bundle to a user-supplied
        instance, mirroring ``ModelRegistry.attach_metrics``)."""
        self._metrics = metrics

    def set_stale_serve(self, flag: bool) -> None:
        """Arm/disarm serving expired entries (the ``cache_pressure``
        brownout rung toggles this)."""
        self._stale_ok = bool(flag)

    @property
    def stale_serve(self) -> bool:
        return self._stale_ok

    @staticmethod
    def _tenant_key(tenant: Optional[str]) -> str:
        return tenant if tenant else ""

    # -- data path ------------------------------------------------------------

    def get(self, tenant: Optional[str], key: Optional[str],
            ) -> Optional[CacheHit]:
        """Look one key up in ``tenant``'s namespace. Fresh entries hit;
        expired entries hit as ``stale`` only while stale-serve is
        armed (brownout), otherwise they evict as ``ttl`` and miss."""
        if key is None:
            return None
        now = self._clock()
        hit = None
        outcome = "miss"
        with self._lock:
            e = self._entries.get((self._tenant_key(tenant), key))
            if e is not None:
                if now < e.expires_at:
                    self._entries.move_to_end(
                        (self._tenant_key(tenant), key))
                    outcome = "hit"
                    self._hits += 1
                    hit = CacheHit(e.value, e.model, e.version, False,
                                   now - e.created_at)
                elif self._stale_ok:
                    outcome = "stale"
                    self._stale_serves += 1
                    hit = CacheHit(e.value, e.model, e.version, True,
                                   now - e.created_at)
                else:
                    self._drop_locked((self._tenant_key(tenant), key))
                    self._count_eviction_locked("ttl", 1)
            if hit is None and outcome == "miss":
                self._misses += 1
            self._report_locked()
        m = self._metrics
        if m is not None:
            m.requests_total.inc(plane=self.plane, outcome=outcome)
        if outcome == "stale":
            record_event("cache.stale_serve", plane=self.plane,
                         model=hit.model, age_s=round(hit.age_s, 3))
        return hit

    def put(self, tenant: Optional[str], key: Optional[str], value, *,
            model: str, version: str,
            nbytes: Optional[int] = None) -> bool:
        """Insert one response. ``nbytes`` defaults to the serialized
        size (``len`` for bytes, canonical-JSON length for dicts); a
        value larger than the whole byte bound is refused rather than
        evicting everything else."""
        if key is None:
            return False
        if nbytes is None:
            if isinstance(value, (bytes, bytearray)):
                nbytes = len(value)
            else:
                try:
                    nbytes = len(json.dumps(value, default=_canon_default))
                except (TypeError, ValueError):
                    return False
        if nbytes > self.max_bytes:
            return False
        now = self._clock()
        entry = _Entry(value, model, version, int(nbytes),
                       now + self.ttl_s, now)
        evicted = 0
        with self._lock:
            full_key = (self._tenant_key(tenant), key)
            old = self._entries.pop(full_key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[full_key] = entry
            self._bytes += entry.nbytes
            self._insertions += 1
            while (len(self._entries) > self.capacity
                   or self._bytes > self.max_bytes):
                self._drop_locked(next(iter(self._entries)))
                evicted += 1
            if evicted:
                self._count_eviction_locked("lru", evicted)
            self._report_locked()
        m = self._metrics
        if m is not None:
            m.insertions_total.inc(plane=self.plane)
        return True

    def note_bypass(self) -> None:
        """Count one client-requested bypass (``X-Cache-Bypass``)."""
        with self._lock:
            self._bypasses += 1
        m = self._metrics
        if m is not None:
            m.requests_total.inc(plane=self.plane, outcome="bypass")

    # -- invalidation ---------------------------------------------------------

    def invalidate_model(self, model: str, *,
                         reason: str = "invalidate") -> int:
        """Drop every entry for ``model`` across all tenants — the
        registry's hot-swap/rollback listener. Returns entries
        dropped. (The epoch in the key already makes them unreachable;
        this reclaims the memory and keeps the gauges honest.)"""
        with self._lock:
            doomed = [k for k, e in self._entries.items()
                      if e.model == model]
            for k in doomed:
                self._drop_locked(k)
            self._count_eviction_locked("invalidate", len(doomed))
            self._report_locked()
        m = self._metrics
        if m is not None:
            m.invalidations_total.inc(plane=self.plane, reason=reason)
        record_event("cache.invalidate", plane=self.plane, model=model,
                     reason=reason, entries=len(doomed))
        return len(doomed)

    def purge(self, *, reason: str = "purge") -> int:
        """Drop everything (fleet rolling deploy, backend readmit)."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._bytes = 0
            self._count_eviction_locked("purge", n)
            self._report_locked()
        m = self._metrics
        if m is not None:
            m.invalidations_total.inc(plane=self.plane, reason=reason)
        record_event("cache.purge", plane=self.plane, reason=reason,
                     entries=n)
        return n

    def pressure_evict(self, fraction: float = 0.5) -> int:
        """Drop the LRU ``fraction`` of entries — the cache's host
        memory participates in brownout pressure shedding."""
        if not 0.0 < fraction <= 1.0:
            raise ValueError(f"fraction must be in (0, 1], got {fraction}")
        with self._lock:
            n = int(len(self._entries) * fraction)
            for _ in range(n):
                self._drop_locked(next(iter(self._entries)))
            self._count_eviction_locked("pressure", n)
            self._report_locked()
        if n:
            record_event("cache.pressure", plane=self.plane, evicted=n)
        return n

    # -- internals (caller holds the lock) ------------------------------------

    def _drop_locked(self, full_key) -> None:
        e = self._entries.pop(full_key, None)
        if e is not None:
            self._bytes -= e.nbytes

    def _count_eviction_locked(self, reason: str, n: int) -> None:
        if n <= 0:
            return
        self._evictions += n
        m = self._metrics
        if m is not None:
            m.evictions_total.inc(n, plane=self.plane, reason=reason)

    def _report_locked(self) -> None:
        m = self._metrics
        if m is not None:
            m.entries.set(len(self._entries), plane=self.plane)
            m.size_bytes.set(self._bytes, plane=self.plane)

    # -- introspection --------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def describe(self) -> dict:
        """The ``/debug/cache`` document."""
        with self._lock:
            tenants = len({tk for tk, _ in self._entries})
            return {
                "plane": self.plane,
                "entries": len(self._entries),
                "bytes": self._bytes,
                "capacity": self.capacity,
                "max_bytes": self.max_bytes,
                "ttl_s": self.ttl_s,
                "tenants": tenants,
                "stale_serve": self._stale_ok,
                "hits": self._hits,
                "misses": self._misses,
                "stale_serves": self._stale_serves,
                "bypasses": self._bypasses,
                "insertions": self._insertions,
                "evictions": self._evictions,
            }


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() in (
        "1", "true", "yes", "on")


def resolve_response_cache(arg, *, metrics: Optional[CacheMetrics] = None,
                           plane: str = "serving",
                           ) -> Optional[ResponseCache]:
    """The server's cache-construction policy (mirrors
    ``warmstart.resolve_warmup_manifest``): ``False`` disables
    explicitly, an instance passes through (adopting ``metrics`` when
    it has none), ``True`` builds a default, and ``None`` defers to the
    ``DL4J_TPU_CACHE`` env knob (sized by ``DL4J_TPU_CACHE_CAPACITY`` /
    ``DL4J_TPU_CACHE_TTL_S`` / ``DL4J_TPU_CACHE_MAX_BYTES``)."""
    if arg is False:
        return None
    if isinstance(arg, ResponseCache):
        if arg._metrics is None and metrics is not None:
            arg.attach_metrics(metrics)
        return arg
    if arg is None and not _env_flag(ENV_CACHE):
        return None
    if arg is not None and arg is not True:
        raise TypeError(
            "cache must be None, a bool, or a ResponseCache, got "
            f"{type(arg).__name__}")
    capacity = int(os.environ.get(ENV_CACHE_CAPACITY, DEFAULT_CAPACITY))
    ttl_s = float(os.environ.get(ENV_CACHE_TTL_S, DEFAULT_TTL_S))
    max_bytes = int(os.environ.get(ENV_CACHE_MAX_BYTES,
                                   DEFAULT_MAX_BYTES))
    return ResponseCache(capacity=capacity, ttl_s=ttl_s,
                         max_bytes=max_bytes, metrics=metrics,
                         plane=plane)
