"""Multi-model registry with warmed hot-swap and rollback.

One ``ModelRegistry`` holds N named entries; each entry owns the live
``ParallelInference`` replica set for its active version plus a version
history. Deployment discipline (↔ TF-Serving's version policy):

1. ``deploy(name, variables)`` builds a NEW replica set from the new
   variables,
2. pre-compiles every batch bucket against it (warmup) while the old
   version keeps serving,
3. atomically switches the active pointer under the entry lock,
4. drains the old replicas (``shutdown()`` serves everything already
   queued, FIFO, then the workers exit).

No request ever observes a torn model: a request is served entirely by
whichever replica set it was enqueued on, and a request that loses the
race against the old set's drain (enqueue raises "shut down") retries
once on the new active set.

``register_from_checkpoint`` loads entries straight from serde
checkpoints (config.json rebuilds the model, state.npz supplies the
variables) — the registry is the serving-side consumer of the training
side's checkpoint rotation.
"""

from __future__ import annotations

import functools
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.analysis.lockcheck import make_lock
from deeplearning4j_tpu.parallel.inference import (
    InferenceShutdown,
    ParallelInference,
)
from deeplearning4j_tpu.serving.errors import (
    BadRequestError,
    ModelNotFoundError,
    NotReadyError,
    ServingError,
)
from deeplearning4j_tpu.serving.warmup import (
    bucket_sizes,
    warm_all_replicas,
    warmup_inference,
)


class _Active:
    __slots__ = ("pi", "version")

    def __init__(self, pi, version):
        self.pi = pi
        self.version = version


class ModelEntry:
    """One named model: active replica set + version history."""

    def __init__(self, registry: "ModelRegistry", name: str,
                 forward: Callable[[Any, Any], Any], input_spec: Any, *,
                 mode: str = "batched", max_batch_size: int = 32,
                 queue_limit: int = 256, batch_wait_s: float = 0.0,
                 devices: Optional[Sequence] = None):
        self._registry = registry
        self.name = name
        self.forward = forward
        self.input_spec = input_spec
        self.mode = mode
        self.max_batch_size = max_batch_size
        self.queue_limit = queue_limit
        self.batch_wait_s = batch_wait_s
        self.devices = devices
        # registered-but-dormant cheaper variables the brownout ladder
        # hot-swaps in at its deepest rung (set_fallback / the
        # registry's engage_fallback / disengage_fallback). With
        # prewarm (the default) the fallback's replica set is built and
        # bucket-warmed at registration, so engaging it under overload
        # is a pointer swap — ZERO compiles exactly when the process
        # can least afford a recompile storm.
        self.fallback_variables: Any = None
        self.fallback_version: Optional[str] = None
        self.fallback_engaged = False
        self._fallback_pi = None          # prewarmed dormant replica set
        self._fallback_warmed_sizes: List[int] = []
        self._fallback_lock = make_lock("ModelEntry._fallback_lock")
        self._lock = make_lock("ModelEntry._lock")
        # Serializes deploy/rollback (history mutation + swap) so
        # concurrent deploys can't leave the active version out of sync
        # with history[-1]. Never held while _lock is already held.
        self._deploy_lock = make_lock("ModelEntry._deploy_lock")
        self._active: Optional[_Active] = None
        self.history: List[Tuple[str, Any]] = []  # (version, variables)
        # monotone swap counter: bumps on every activation (deploy,
        # rollback, fallback engage/disengage). Response-cache keys
        # include it, so entries cached against a superseded set of
        # weights can never be served — even if the version string is
        # reused by a later deploy.
        self.epoch = 0
        self.warmed = False
        # the buckets the last warm() actually compiled: traffic landing
        # outside this set after warm is a recompile-after-warmup — the
        # regression warmup_recompiles_after_warm_total machine-checks
        self.warmed_buckets: set = set()
        # static cost analyses are a compile each — cache per (version,
        # rows) so /debug/costs polling never recompiles
        self._cost_cache: Dict[Tuple[str, int], dict] = {}

    # -- replica-set lifecycle ---------------------------------------------

    def _build_pi(self, variables) -> ParallelInference:
        return ParallelInference(
            self.forward, variables, devices=self.devices, mode=self.mode,
            max_batch_size=self.max_batch_size, queue_limit=self.queue_limit,
            batch_wait_s=self.batch_wait_s,
            on_batch=functools.partial(
                self._registry._record_batch, self.name),
            on_expired=functools.partial(
                self._registry._record_expired, self.name),
            on_respawn=functools.partial(
                self._registry._record_respawn, self.name))

    def set_batch_wait(self, seconds: float):
        """Adjust the batched-mode coalesce wait live (active replica
        set now, future deploys inherit it) — the brownout ladder's
        first rung."""
        if seconds < 0:
            raise ValueError(f"batch_wait_s must be >= 0, got {seconds}")
        self.batch_wait_s = float(seconds)
        with self._lock:
            active = self._active
        if active is not None:
            active.pi.set_batch_wait(seconds)

    def set_fallback(self, variables: Any, version: Optional[str] = None,
                     *, prewarm: bool = True):
        """Register dormant cheaper variables (a distilled/quantized
        twin) the brownout ladder deploys at its deepest rung;
        ``disengage`` rolls back.

        ``prewarm`` (default): build + bucket-warm the fallback's
        replica set NOW — paying the compiles at registration, when the
        process is healthy — so ``engage_fallback`` under overload is a
        pointer swap with zero compiles instead of the recompile storm
        brownout exists to avoid. The prewarmed set idles (worker
        threads parked on an empty queue) until engaged; disengaging
        re-prewarms in the background for the next brownout cycle.
        ``prewarm=False`` keeps the historical lazy behavior (the
        compiles happen inside ``engage_fallback``'s warmed deploy)."""
        self.fallback_variables = variables
        self.fallback_version = version
        if prewarm:
            self._prewarm_fallback()

    def _manifest_warm_sizes(self) -> List[int]:
        """Manifest-restricted buckets when traffic data exists, the
        full vocabulary otherwise — a deploy (or fallback prewarm) must
        be warm for the shapes traffic is actually hitting."""
        manifest = getattr(self._registry, "_warm_manifest", None)
        all_sizes = bucket_sizes(self.max_batch_size, self.mode)
        if manifest is not None:
            observed = manifest.predict_buckets(self.name)
            if observed:
                sizes = [s for s in all_sizes if s in set(observed)]
                if sizes:
                    return sizes
        return all_sizes

    def _dead(self) -> bool:
        with self._lock:
            return self._active is None and bool(self.history)

    def _prewarm_fallback(self):
        """Build + warm a dormant replica set from the registered
        fallback variables; a failure records a flight event and
        leaves the lazy engage path as the fallback's fallback.

        The compiles run OUTSIDE ``_fallback_lock`` — a background
        re-prewarm must never make ``entry.shutdown()`` (a drain
        deadline) or the next ``engage_fallback`` (an overloaded
        process) wait out minutes of warmup. Install is a short
        critical section that re-checks liveness, so a prewarm racing
        shutdown discards its own set instead of leaking it."""
        with self._fallback_lock:
            if self.fallback_variables is None or self._fallback_pi \
                    is not None or self._dead():
                return
            variables = self.fallback_variables
        pi = self._build_pi(variables)
        sizes = self._manifest_warm_sizes()
        try:
            # full (bucket x replica) coverage: the engage-under-
            # overload contract is ZERO compiles, so queue-routed
            # warmup (one device per bucket) is not enough here
            warm_all_replicas(pi, self.input_spec, sizes)
        except BaseException:
            pi.shutdown()
            _record_flight("serving.fallback_prewarm_failed",
                           model=self.name)
            raise
        with self._fallback_lock:
            if self._dead() or self._fallback_pi is not None:
                # the entry shut down (or a concurrent prewarm won)
                # while this set compiled: discard, don't park worker
                # threads + replicas on a dead/duplicated slot
                pi.shutdown()
                return
            self._fallback_pi = pi
            # what THIS set actually compiled — engage must stamp these,
            # not whatever the manifest says by then (buckets observed
            # in between were never warmed on the fallback replicas)
            self._fallback_warmed_sizes = list(sizes)
        _record_flight("serving.fallback_prewarm", model=self.name,
                       version=self.fallback_version or "")

    def warm(self, sizes: Optional[Sequence[int]] = None,
             progress=None, source: str = "full") -> Dict[int, float]:
        """Pre-compile batch buckets on the active replica set —
        ``sizes`` (e.g. a warmup manifest's observed buckets) or the
        full vocabulary.

        Expects no concurrent traffic on this entry (the standard paths —
        ``ModelServer.start(warm=True)`` before serving begins, and
        ``deploy``'s warm of a not-yet-active set — are quiescent): a live
        request coalescing with a warmup batch would shift it into a
        different bucket, leaving the intended one uncompiled."""
        with self._lock:
            active = self._active
        if active is None:
            raise NotReadyError(f"model '{self.name}' is shut down")
        if sizes is None:
            sizes = bucket_sizes(self.max_batch_size, self.mode)
        wm = _warmstart_metrics()

        def note(rows, seconds, _cb=progress):
            if wm is not None:
                wm.warmup_shapes_total.inc(plane="predict", source=source)
                wm.warmup_seconds.observe(seconds, plane="predict")
            if _cb is not None:
                _cb(rows, seconds)

        stats = warmup_inference(active.pi, self.input_spec, sizes,
                                 progress=note)
        self.warmed = True
        self.warmed_buckets = set(sizes)
        self._registry._record_ready(self.name, True)
        return stats

    @property
    def version(self) -> str:
        with self._lock:
            return self._active.version if self._active else ""

    # -- serving -----------------------------------------------------------

    def predict(self, features, timeout: Optional[float] = None,
                trace=None, deadline: Optional[float] = None):
        """Serve one request on the active replica set."""
        return self.predict_versioned(features, timeout=timeout,
                                      trace=trace, deadline=deadline)[0]

    def predict_versioned(self, features, timeout: Optional[float] = None,
                          trace=None, deadline: Optional[float] = None
                          ) -> Tuple[Any, str]:
        """Serve one request; returns ``(outputs, version)`` where
        ``version`` is the version of the replica set that actually
        served — read under the same lock as the pointer grab, so a
        concurrent hot-swap can never mislabel a response.

        ``trace``: optional ``(trace_id, parent_span_id)`` correlation
        context forwarded to ``ParallelInference.output`` for the
        batch/dispatch spans.

        Retries once if the grabbed replica set was drained by a
        concurrent hot-swap between the pointer read and the enqueue —
        the swap guarantees a live active set exists."""
        for attempt in range(2):
            with self._lock:
                if self._active is None:
                    if self.history:
                        # had versions, now none: the entry was shut down
                        # (server stopping) — retryable 503, not a 500
                        raise NotReadyError(
                            f"model '{self.name}' is shut down")
                    raise ServingError(f"model '{self.name}' has no "
                                       "deployed version")
                pi, version = self._active.pi, self._active.version
            try:
                return pi.output(features, timeout=timeout,
                                 trace=trace, deadline=deadline), version
            except InferenceShutdown:
                if attempt == 0:
                    continue
                raise
            except RuntimeError as e:
                # legacy string match kept for custom replica sets that
                # raise their own "shut down" RuntimeError
                if "shut down" in str(e) and attempt == 0:
                    continue
                raise

    def parse_inputs(self, inputs):
        """JSON-decoded inputs → feature arrays matching the input spec.

        Array-spec models accept a nested list (any layout whose row size
        matches — a flat 784-float row reshapes to (28,28,1)); dict-spec
        models accept an object with exactly the spec's keys.

        Batched-mode requests larger than ``max_batch_size`` are rejected:
        oversized batches fall outside the pre-compiled (warmed) buckets,
        so admitting them would hand arbitrary clients fresh XLA compiles."""
        if isinstance(self.input_spec, dict):
            if not isinstance(inputs, dict):
                raise BadRequestError(
                    f"model '{self.name}' takes a dict of inputs "
                    f"{sorted(self.input_spec)}")
            extra = set(inputs) - set(self.input_spec)
            if extra:
                raise BadRequestError(f"unknown inputs {sorted(extra)}; "
                                      f"expected {sorted(self.input_spec)}")
            out, rows = {}, None
            for key, s in self.input_spec.items():
                if key not in inputs:
                    raise BadRequestError(f"missing input '{key}'")
                out[key] = self._coerce(inputs[key], s, key)
                n = out[key].shape[0]
                if rows is not None and n != rows:
                    raise BadRequestError(
                        f"inputs disagree on batch size ({rows} vs {n})")
                rows = n
            self._check_rows(rows)
            return out
        arr = self._coerce(inputs, self.input_spec, "inputs")
        self._check_rows(arr.shape[0])
        return arr

    def _check_rows(self, rows: int):
        if self.mode == "batched" and rows > self.max_batch_size:
            raise BadRequestError(
                f"batch of {rows} rows exceeds this model's "
                f"max_batch_size={self.max_batch_size}; split the request")

    def _coerce(self, value, s, label: str):
        try:
            arr = np.asarray(value, dtype=np.dtype(s.dtype))
            return arr.reshape((-1,) + tuple(s.shape))
        except Exception as e:  # noqa: BLE001 — anything here is the client's
            raise BadRequestError(
                f"{label}: cannot coerce to shape (N, "
                f"{', '.join(map(str, s.shape))}) {np.dtype(s.dtype).name}: "
                f"{e}") from None

    def describe(self) -> dict:
        with self._lock:
            version = self._active.version if self._active else ""
        return {"name": self.name, "version": version,
                "versions": [v for v, _ in self.history],
                "warmed": self.warmed, "mode": self.mode,
                "max_batch_size": self.max_batch_size}

    def cost_analysis(self, rows: Optional[int] = None) -> dict:
        """Static XLA cost analysis of this entry's forward program at
        ``rows`` examples (default: the largest warmed bucket) — flops,
        bytes accessed, arithmetic intensity, per-row flops. Compilation
        only, no execution; cached per (version, rows). The roofline
        inputs for ``GET /debug/costs``."""
        from deeplearning4j_tpu.serving.warmup import zeros_batch
        from deeplearning4j_tpu.train.profiling import (
            arithmetic_intensity,
            op_costs,
        )

        with self._lock:
            if self._active is None:
                raise NotReadyError(f"model '{self.name}' is shut down")
            version = self._active.version
        if rows is None:
            rows = self.max_batch_size if self.mode == "batched" else 1
        cached = self._cost_cache.get((version, rows))
        if cached is not None:
            return dict(cached)
        variables = next((v for ver, v in reversed(self.history)
                          if ver == version and v is not None), None)
        out: dict = {"model": self.name, "version": version, "rows": rows}
        if variables is None:
            out.update(available=False,
                       reason="active version's variables were released")
            return out
        example = zeros_batch(self.input_spec, rows)
        try:
            costs = op_costs(self.forward, variables, example)
        except Exception as e:  # noqa: BLE001 — diagnostics never 500 on
            costs = {}          # a backend without cost analysis
            out["reason"] = str(e)[:200]
        if not costs:
            # NOT cached: a transient compile failure must not pin this
            # version's roofline data to "unavailable" forever
            out.setdefault("reason", "backend reports no cost analysis")
            out["available"] = False
            return out
        out["available"] = True
        out["flops"] = costs.get("flops")
        out["bytes_accessed"] = costs.get("bytes accessed")
        out["arithmetic_intensity"] = arithmetic_intensity(costs)
        if costs.get("flops"):
            out["flops_per_row"] = costs["flops"] / rows
        self._cost_cache[(version, rows)] = dict(out)
        return out

    def shutdown(self):
        with self._lock:
            active, self._active = self._active, None
        if active is not None:
            active.pi.shutdown()
        with self._fallback_lock:
            fb, self._fallback_pi = self._fallback_pi, None
        if fb is not None:
            fb.shutdown()


class ModelRegistry:
    def __init__(self, *, metrics=None):
        self._entries: Dict[str, ModelEntry] = {}
        self._lock = make_lock("ModelRegistry._lock")
        self._metrics = metrics
        self._admission = None
        self._warm_manifest = None
        # called as fn(name, version, epoch, reason) after every swap —
        # the response-cache tier subscribes to drop entries for weights
        # that just stopped serving
        self._invalidation_listeners: List[Callable[..., None]] = []
        # called as fn(name, n_requests, rows, bucket, seconds) for
        # every dispatched device batch — the usage meter subscribes
        # for device-batch-seconds / FLOPs attribution
        self._batch_listeners: List[Callable[..., None]] = []

    def attach_metrics(self, metrics):
        """Wire a ServingMetrics bundle (occupancy/device-latency hooks
        take effect immediately — entries call back through the registry)."""
        self._metrics = metrics

    def attach_admission(self, admission):
        """Wire the AdmissionController so worker batch service times
        feed its Retry-After overshoot EWMA."""
        self._admission = admission

    def attach_manifest(self, manifest):
        """Wire a :class:`~deeplearning4j_tpu.serving.warmstart.
        WarmupManifest`: every dispatched batch's bucket feeds the live
        traffic mix the next restart warms against."""
        self._warm_manifest = manifest

    def add_invalidation_listener(self, fn: Callable[..., None]):
        """Subscribe ``fn(name, version, epoch, reason)`` to activation
        swaps (deploy / rollback / fallback engage). Listeners fire
        AFTER the new replica set is live, outside entry locks; a
        raising listener is swallowed — cache invalidation must never
        fail a deploy."""
        self._invalidation_listeners.append(fn)

    def _notify_invalidation(self, name: str, version: str, epoch: int,
                             reason: str):
        for fn in list(self._invalidation_listeners):
            try:
                fn(name, version, epoch, reason)
            except Exception:  # noqa: BLE001 — see add_invalidation_listener
                pass

    def add_batch_listener(self, fn: Callable[..., None]):
        """Subscribe ``fn(name, n_requests, rows, bucket, seconds)`` to
        every dispatched device batch (warm batches included). Runs on
        the worker's dispatch path, so listeners must be cheap; a
        raising listener is swallowed — metering never fails serving."""
        self._batch_listeners.append(fn)

    # -- metrics hooks (called from ParallelInference workers) -------------

    def _record_batch(self, name: str, n_requests: int, rows: int,
                      bucket: int, seconds: float, *,
                      record_manifest: bool = True):
        m = self._metrics
        if m is not None:
            m.batch_occupancy.observe(rows / max(bucket, 1), model=name)
            m.device_latency.observe(seconds, model=name)
        ac = self._admission
        if ac is not None and hasattr(ac, "observe_service_time"):
            ac.observe_service_time(seconds)
        entry = self._entries.get(name)
        wm = self._warm_manifest
        if wm is not None and record_manifest \
                and (entry is None or entry.warmed):
            # LIVE traffic only: warmup's own zero-batches flow through
            # this hook too (entry not yet warmed) and recording them
            # would teach the manifest the full vocabulary, defeating
            # the restrict-to-traffic restart
            try:
                wm.note_batch(name, bucket)
            except Exception:  # noqa: BLE001 — recording traffic never
                pass           # fails serving
        # recompile-after-warm detection: a dispatched bucket outside
        # the warmed set compiled on the hot path (counted once — the
        # program exists afterwards). The entry lookup is a dict get;
        # the set test is O(1).
        if entry is not None and entry.warmed \
                and entry.warmed_buckets \
                and bucket not in entry.warmed_buckets:
            entry.warmed_buckets.add(bucket)
            wsm = _warmstart_metrics()
            if wsm is not None:
                wsm.recompiles_after_warm_total.inc(plane="predict")
            _record_flight("serving.recompile_after_warm", model=name,
                           bucket=bucket)
        for fn in list(self._batch_listeners):
            try:
                fn(name, n_requests, rows, bucket, seconds)
            except Exception:  # noqa: BLE001 — see add_batch_listener
                pass

    def _record_expired(self, name: str, n: int):
        m = self._metrics
        if m is not None and hasattr(m, "deadline_expired_total"):
            m.deadline_expired_total.inc(n, model=name)

    def _record_ready(self, name: str, ready: bool):
        m = self._metrics
        if m is not None:
            m.model_ready.set(1.0 if ready else 0.0, model=name)

    def _record_respawn(self, name: str, worker_idx: int):
        m = self._metrics
        if m is not None and hasattr(m, "worker_respawns_total"):
            m.worker_respawns_total.inc(model=name)

    # -- registration / deployment -----------------------------------------

    def register(self, name: str, forward: Callable[[Any, Any], Any],
                 variables: Any, *, input_spec: Any, version: str = "v1",
                 mode: str = "batched", max_batch_size: int = 32,
                 queue_limit: int = 256, batch_wait_s: float = 0.0,
                 devices: Optional[Sequence] = None,
                 warm: bool = False) -> ModelEntry:
        """Create an entry and deploy ``variables`` as its first version."""
        entry = ModelEntry(self, name, forward, input_spec, mode=mode,
                           max_batch_size=max_batch_size,
                           queue_limit=queue_limit,
                           batch_wait_s=batch_wait_s, devices=devices)
        with self._lock:
            if name in self._entries:
                raise ValueError(f"model '{name}' already registered")
        # Activate BEFORE publishing: a concurrent predict must never see
        # a registered entry with no deployed version.
        entry._active = _Active(entry._build_pi(variables), version)
        entry.history.append((version, variables))
        with self._lock:
            if name in self._entries:  # lost a register-register race
                entry.shutdown()
                raise ValueError(f"model '{name}' already registered")
            self._entries[name] = entry
        self._record_ready(name, False)
        if warm:
            entry.warm()
        return entry

    def register_from_checkpoint(self, name: str, ckpt_dir, *,
                                 forward: Optional[Callable] = None,
                                 input_spec: Any = None,
                                 version: Optional[str] = None,
                                 **kw) -> ModelEntry:
        """Load a registry entry from a serde checkpoint directory.

        ``config.json`` rebuilds the model; ``state.npz`` supplies the
        inference variables (works for both TrainState and bare-variables
        checkpoints). ``forward`` defaults to ``model.output``;
        ``input_spec`` defaults to the config's ``input_shape`` (float32)
        when it has one."""
        from deeplearning4j_tpu.serde.checkpoint import (
            load_inference_variables,
            load_model_config,
        )
        from deeplearning4j_tpu.serving.warmup import spec

        cfg = load_model_config(ckpt_dir)
        model = _model_for_config(cfg)
        variables = load_inference_variables(ckpt_dir, model)
        if forward is None:
            forward = lambda v, x: model.output(v, x)  # noqa: E731
        if input_spec is None:
            shape = getattr(cfg, "input_shape", None)
            if shape is None:
                raise ValueError(
                    "config has no input_shape; pass input_spec explicitly")
            input_spec = spec(tuple(shape))
        if version is None:
            import pathlib

            version = pathlib.Path(str(ckpt_dir)).name
        return self.register(name, forward, variables,
                             input_spec=input_spec, version=version, **kw)

    def deploy(self, name: str, variables: Any, *,
               version: Optional[str] = None, warm: bool = True) -> str:
        """Warmed hot-swap: build + pre-compile a new replica set, switch
        atomically, drain the old one. Returns the deployed version."""
        entry = self.get(name)
        with entry._deploy_lock:
            if version is None:
                version = f"v{len(entry.history) + 1}"
            # Swap first, record second: a failed warmup must not leave a
            # phantom never-activated version in the history.
            self._swap(entry, variables, version, warm)
            entry.history.append((version, variables))
            # Rollback reaches exactly one version back, so older entries
            # keep only their name — holding every past version's full
            # variables would grow host memory per hot-swap forever.
            if len(entry.history) > 2:
                old_version, _ = entry.history[-3]
                entry.history[-3] = (old_version, None)
        _record_flight("serving.deploy", model=name, version=version,
                       warm=warm)
        return version

    def rollback(self, name: str) -> str:
        """Drop the active version and redeploy the previous one (itself
        rebuilt + rewarmed — the drained replica set is gone)."""
        entry = self.get(name)
        with entry._deploy_lock:
            if len(entry.history) < 2:
                raise ServingError(f"model '{name}' has no previous version "
                                   "to roll back to")
            version, variables = entry.history[-2]
            if variables is None:
                raise ServingError(
                    f"model '{name}' version {version} is too old to roll "
                    "back to (only the previous version's variables are "
                    "retained)")
            self._swap(entry, variables, version, warm=True)
            entry.history.pop()  # only after the swap succeeded
        _record_flight("serving.rollback", model=name, version=version)
        return version

    # -- brownout fallback versions ----------------------------------------

    def engage_fallback(self, name: str) -> Optional[str]:
        """Swap the entry's registered fallback in. With a prewarmed
        fallback set (the ``set_fallback`` default) this is a pointer
        swap — ZERO compiles, the property the regression test pins;
        otherwise it falls back to the normal warmed hot-swap (the old
        version keeps serving while the cheaper one pre-compiles).
        Returns the deployed version, or None when no fallback is
        registered / it is already engaged."""
        entry = self.get(name)
        if entry.fallback_variables is None or entry.fallback_engaged:
            return None
        fb_version = entry.fallback_version or f"{entry.version}-fallback"
        with entry._fallback_lock:
            pi, entry._fallback_pi = entry._fallback_pi, None
            warmed_sizes = entry._fallback_warmed_sizes
        if pi is not None:
            with entry._deploy_lock:
                self._swap_prewarmed(entry, pi, fb_version, warmed_sizes)
                entry.history.append((fb_version,
                                      entry.fallback_variables))
                if len(entry.history) > 2:
                    old_version, _ = entry.history[-3]
                    entry.history[-3] = (old_version, None)
            version = fb_version
            _record_flight("serving.deploy", model=name, version=version,
                           warm=True, prewarmed=True)
        else:
            version = self.deploy(name, entry.fallback_variables,
                                  version=fb_version)
        entry.fallback_engaged = True
        _record_flight("serving.fallback", model=name, version=version,
                       engaged=True)
        return version

    def disengage_fallback(self, name: str) -> Optional[str]:
        """Roll back from the engaged fallback to the version that was
        serving before the brownout, then re-prewarm the fallback in
        the background for the next brownout cycle (cheap under an
        active persistent compile cache). Returns the restored version,
        or None when no fallback is engaged."""
        entry = self.get(name)
        if not entry.fallback_engaged:
            return None
        version = self.rollback(name)
        entry.fallback_engaged = False
        _record_flight("serving.fallback", model=name, version=version,
                       engaged=False)
        threading.Thread(target=self._reprewarm, args=(entry,),
                         daemon=True,
                         name=f"fallback-prewarm-{name}").start()
        return version

    @staticmethod
    def _reprewarm(entry: ModelEntry):
        try:
            entry._prewarm_fallback()
        except Exception:  # noqa: BLE001 — flight event already recorded;
            pass           # the lazy engage path remains

    def _swap_prewarmed(self, entry: ModelEntry, pi, version: str,
                        warmed_sizes: Sequence[int]):
        """Activate an already-warmed replica set (the prewarmed
        fallback): the pointer swap of ``_swap`` without the build or
        the compiles. ``warmed_sizes`` is what the set ACTUALLY
        compiled at prewarm time — stamping the manifest's current view
        instead would blind the recompile-after-warm check for buckets
        observed since. Caller holds the deploy lock and appends
        history."""
        with entry._lock:
            old, entry._active = entry._active, _Active(pi, version)
            entry.warmed = True
            entry.warmed_buckets = set(warmed_sizes)
            entry.epoch += 1
            epoch = entry.epoch
        self._record_ready(entry.name, True)
        self._notify_invalidation(entry.name, version, epoch,
                                  "hot_swap")
        if old is not None:
            old.pi.shutdown()

    def _swap(self, entry: ModelEntry, variables, version: str, warm: bool):
        new_pi = entry._build_pi(variables)
        sizes = entry._manifest_warm_sizes()
        if warm:
            # warm batches from the not-yet-active set report through
            # the same on_batch hook as live traffic: pre-extend the
            # warmed set so they never count as recompiles-after-warm,
            # and mute manifest recording on the new set for the warm
            # window — the OLD version is warmed, so the live-traffic
            # gate alone would record these zero-batches and teach the
            # manifest the full vocabulary
            added = set(sizes) - entry.warmed_buckets
            entry.warmed_buckets |= added
            new_pi._on_batch = functools.partial(
                self._record_batch, entry.name, record_manifest=False)
            try:
                warmup_inference(new_pi, entry.input_spec, sizes)
            except BaseException:
                # failed deploy: the old version keeps serving — don't
                # leak the half-built replica set's worker threads, and
                # roll the warmed-set pre-extension back or the old
                # version's recompile-after-warm check goes blind for
                # buckets it never compiled
                entry.warmed_buckets -= added
                new_pi.shutdown()
                raise
            new_pi._on_batch = functools.partial(
                self._record_batch, entry.name)
        with entry._lock:
            old, entry._active = entry._active, _Active(new_pi, version)
            entry.warmed = warm
            entry.warmed_buckets = set(sizes) if warm else set()
            entry.epoch += 1
            epoch = entry.epoch
        self._record_ready(entry.name, warm)
        self._notify_invalidation(entry.name, version, epoch,
                                  "hot_swap")
        if old is not None:
            old.pi.shutdown()  # FIFO drain: queued requests still served

    # -- lookup -------------------------------------------------------------

    def get(self, name: str) -> ModelEntry:
        with self._lock:
            entry = self._entries.get(name)
        if entry is None:
            raise ModelNotFoundError(f"no model named '{name}'")
        return entry

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def entries(self) -> List[ModelEntry]:
        with self._lock:
            return [self._entries[n] for n in sorted(self._entries)]

    def describe(self) -> List[dict]:
        return [e.describe() for e in self.entries()]

    def shutdown_all(self):
        for entry in self.entries():
            entry.shutdown()


def _warmstart_metrics():
    """Warmstart bundle, or None when telemetry is off — the
    recompile-after-warm counter and warmup histograms."""
    from deeplearning4j_tpu.observability.metrics import (
        warmstart_metrics_or_none,
    )

    return warmstart_metrics_or_none()


def _record_flight(kind: str, **data):
    """Deployment lifecycle into the black-box ring — a post-mortem must
    show hot-swaps/rollbacks next to the traffic they affected."""
    try:
        from deeplearning4j_tpu.observability.flightrecorder import (
            record_event,
        )

        record_event(kind, **data)
    except Exception:  # noqa: BLE001 — telemetry never fails a deploy
        pass


def _model_for_config(cfg):
    from deeplearning4j_tpu.nn.config import GraphConfig, SequentialConfig
    from deeplearning4j_tpu.nn.model import GraphModel, SequentialModel

    if isinstance(cfg, SequentialConfig):
        return SequentialModel(cfg)
    if isinstance(cfg, GraphConfig):
        return GraphModel(cfg)
    raise TypeError(f"cannot build a servable model from {type(cfg).__name__}")
